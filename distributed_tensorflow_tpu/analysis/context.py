"""Jit/scope context for dtlint: which defs trace, which args are static,
which buffers are donated, and which mesh axes are in scope.

The registry is built per-module in one sweep so every rule shares the same
answers to:

* "is this ``def`` traced?" — decorated by ``jit``/``pjit``/``pmap``/
  ``shard_map`` (directly or via ``functools.partial``), or referenced by
  name as the first argument of such a wrapper call anywhere in the module
  (the repo's dominant idiom: ``return jax.jit(step, donate_argnums=0)``).
  Everything lexically inside a traced def traces too.
* "which params are static / donated?" — literal ``static_argnums``/
  ``static_argnames``/``donate_argnums`` pulled from the wrapper call.
* "which mesh axis names exist?" — the canonical ``AXIS_ORDER`` parsed out
  of ``parallel/mesh.py`` (never imported: the linter stays JAX-free), plus
  any literal ``axis_name=...`` bindings in the module (``pmap``/``vmap``)
  and literal ``Mesh(..., ('a', 'b'))`` axis tuples.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .walker import Source, enclosing, literal_strings

__all__ = ["JitSite", "JitRegistry", "mesh_axes_for", "DEFAULT_MESH_AXES",
           "JIT_WRAPPERS", "TRACED_WRAPPERS"]

# Canonical dotted names (post alias expansion) that compile their operand.
# Bare "shard_map" covers relative imports (``from ._compat import
# shard_map``) — relative modules have no canonical prefix to expand.
JIT_WRAPPERS: Set[str] = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.pmap",
    "jax.experimental.shard_map.shard_map",
    "jax.shard_map",
    "shard_map",
    "distributed_tensorflow_tpu.parallel._compat.shard_map",
}
# Wrappers that trace but take axis bindings rather than static/donate args.
TRACED_WRAPPERS: Set[str] = JIT_WRAPPERS | {"jax.vmap", "jax.checkpoint",
                                            "jax.remat"}

# Builders whose return value is a jitted step donating its first arg
# (train/step.py's make_train_step family) — the cross-module half of the
# "registered as a train step" contract.
_STEP_BUILDER_RE = re.compile(r"^make_.*train_step$")

# Fallback when parallel/mesh.py is not reachable from the analyzed paths.
DEFAULT_MESH_AXES: Tuple[str, ...] = ("pipe", "data", "fsdp", "expert",
                                      "seq", "tensor")


@dataclasses.dataclass
class JitSite:
    """One wrapper application: ``jax.jit(step, donate_argnums=0)`` or a
    decorator.  ``target`` is the wrapped def when it could be resolved."""

    call: Optional[ast.Call]          # None for bare @jax.jit decorators
    wrapper: str                      # canonical wrapper name
    target: Optional[ast.AST]         # FunctionDef / Lambda
    target_name: Optional[str]
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    axis_names: Tuple[str, ...] = ()  # literal axis bindings (pmap/vmap)


def _literal_ints(node: Optional[ast.AST]) -> Tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _unwrap_partial(src: Source, call: ast.Call
                    ) -> Tuple[Optional[str], ast.Call]:
    """``functools.partial(jax.jit, static_argnums=0)`` -> ('jax.jit', call)
    with the partial's keywords visible on the returned call."""
    name = src.call_canonical(call)
    if name in ("functools.partial", "partial") and call.args:
        inner = call.args[0]
        inner_name = None
        if isinstance(inner, (ast.Name, ast.Attribute)):
            probe = ast.Call(func=inner, args=[], keywords=[])
            inner_name = src.call_canonical(probe)
        if inner_name in TRACED_WRAPPERS:
            return inner_name, call
    return name, call


class JitRegistry:
    """Per-module index of traced defs and their wrapper metadata."""

    def __init__(self, src: Source):
        self.src = src
        self.sites: List[JitSite] = []
        # def name -> all FunctionDefs with that name (module-wide)
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.traced_defs: Set[ast.AST] = set()
        # def name -> JitSite (for static/donate lookups at call sites)
        self.site_by_name: Dict[str, JitSite] = {}
        self.module_axis_bindings: Set[str] = set()
        self._build()

    # ------------------------------------------------------------ build

    def _build(self) -> None:
        tree = self.src.tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_decorators(node)
            elif isinstance(node, ast.Call):
                self._scan_call(node)

        # transitive closure is lexical: nested defs inside traced defs
        # trace too, which the rules get via ``in_traced_scope``.

        # Cross-module train-step registration: the train.make_*train_step
        # builders all return jax.jit(step, donate_argnums=0) — a call
        # site in another module donates its first argument even though
        # the jit wrapper is out of lexical reach.
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) \
                    or not isinstance(node.value, ast.Call):
                continue
            name = self.src.call_canonical(node.value) or ""
            if _STEP_BUILDER_RE.search(name.rsplit(".", 1)[-1]):
                self.site_by_name.setdefault(tgt.id, JitSite(
                    call=None, wrapper="jax.jit", target=None,
                    target_name=None, donate_argnums=(0,)))

    def _scan_decorators(self, fn: ast.AST) -> None:
        for dec in fn.decorator_list:  # type: ignore[attr-defined]
            if isinstance(dec, ast.Call):
                name, call = _unwrap_partial(self.src, dec)
                if name in TRACED_WRAPPERS:
                    self._add_site(call, name, fn,
                                   fn.name)  # type: ignore[attr-defined]
            elif isinstance(dec, (ast.Name, ast.Attribute)):
                probe = ast.Call(func=dec, args=[], keywords=[])
                name = self.src.call_canonical(probe)
                if name in TRACED_WRAPPERS:
                    self._add_site(None, name, fn,
                                   fn.name)  # type: ignore[attr-defined]

    def _scan_call(self, call: ast.Call) -> None:
        name = self.src.call_canonical(call)
        if name not in TRACED_WRAPPERS or not call.args:
            return
        operand = call.args[0]
        target: Optional[ast.AST] = None
        target_name: Optional[str] = None
        if isinstance(operand, ast.Name):
            target_name = operand.id
            target = self._resolve_def(operand.id, call)
        elif isinstance(operand, ast.Lambda):
            target = operand
        self._add_site(call, name, target, target_name)

    def _resolve_def(self, name: str, at: ast.AST) -> Optional[ast.AST]:
        """Prefer a def sharing an enclosing function with the wrapper call
        (the builder idiom); fall back to any module-level def."""
        candidates = self.defs_by_name.get(name, [])
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        encl = enclosing(at, (ast.FunctionDef, ast.AsyncFunctionDef))
        if encl is not None:
            from .walker import is_ancestor
            near = [c for c in candidates if is_ancestor(encl, c)]
            if near:
                return near[-1]
        return candidates[-1]

    def _add_site(self, call: Optional[ast.Call], wrapper: str,
                  target: Optional[ast.AST],
                  target_name: Optional[str]) -> None:
        static_nums: Tuple[int, ...] = ()
        static_names: Tuple[str, ...] = ()
        donate: Tuple[int, ...] = ()
        axes: Tuple[str, ...] = ()
        if call is not None:
            static_nums = _literal_ints(_kw(call, "static_argnums"))
            sa = _kw(call, "static_argnames")
            if sa is not None:
                static_names = tuple(literal_strings(sa))
            donate = _literal_ints(_kw(call, "donate_argnums"))
            ax = _kw(call, "axis_name")
            if ax is not None:
                axes = tuple(literal_strings(ax))
        site = JitSite(call=call, wrapper=wrapper, target=target,
                       target_name=target_name,
                       static_argnums=static_nums,
                       static_argnames=static_names,
                       donate_argnums=donate, axis_names=axes)
        self.sites.append(site)
        if target is not None and wrapper in JIT_WRAPPERS:
            self.traced_defs.add(target)
        if target_name and wrapper in JIT_WRAPPERS:
            self.site_by_name.setdefault(target_name, site)
        # `train_step = jax.jit(step, ...)` — call sites use the new name
        if call is not None and wrapper in JIT_WRAPPERS:
            parent = getattr(call, "parent", None)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                self.site_by_name.setdefault(parent.targets[0].id, site)
        self.module_axis_bindings.update(axes)

    # ------------------------------------------------------------ query

    def in_traced_scope(self, node: ast.AST) -> Optional[ast.AST]:
        """The outermost traced def lexically containing ``node``, if any."""
        found = None
        cur = getattr(node, "parent", None)
        while cur is not None:
            if cur in self.traced_defs:
                found = cur
            cur = getattr(cur, "parent", None)
        return found

    def static_param_names(self, fn: ast.AST) -> Set[str]:
        """Param names marked static for a traced def (best effort)."""
        site = None
        for s in self.sites:
            if s.target is fn:
                site = s
                break
        if site is None or not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return set()
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        out = set(site.static_argnames)
        for i in site.static_argnums:
            if 0 <= i < len(params):
                out.add(params[i])
        return out


def _parse_axis_order(mesh_path: str) -> Optional[Tuple[str, ...]]:
    try:
        with open(mesh_path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError, ValueError):
        return None
    for node in ast.walk(tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "AXIS_ORDER":
                names = literal_strings(value)
                if names:
                    return tuple(names)
    return None


def mesh_axes_for(path: str) -> Tuple[str, ...]:
    """Canonical axis names for the package owning ``path``.

    Walks up from ``path`` looking for ``<pkg>/parallel/mesh.py`` (or a
    sibling ``distributed_tensorflow_tpu/parallel/mesh.py``) and parses its
    ``AXIS_ORDER``; falls back to the baked-in default.
    """
    probe = os.path.abspath(path)
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    for _ in range(8):
        for rel in (("parallel", "mesh.py"),
                    ("distributed_tensorflow_tpu", "parallel", "mesh.py")):
            cand = os.path.join(probe, *rel)
            if os.path.isfile(cand):
                axes = _parse_axis_order(cand)
                if axes:
                    return axes
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return DEFAULT_MESH_AXES
