"""dtlint lifecycle-tier rules (DT601-DT605) over typestate events.

``analysis.lifecycle`` interprets every project function against the
declared resource protocols and emits rule-tagged
:class:`~.lifecycle.LifecycleEvent` records; this module is the thin
findings layer — catalog, severity, select/ignore, and per-line
suppression via the owning :class:`~.walker.Source`.

Catalog (docs/ANALYSIS.md has the worked examples):

* **DT601** (error) — a leak-tracked resource (page lease, adapter
  pin) is still held when an exception edge or a return path leaves
  the function: the acquire has no release on that path and ownership
  never transferred (stored, returned, handed to a releasing callee,
  or published via ``handoff``).
* **DT602** (error) — use-after-release or double release of a
  *non-idempotent* resource: a second ``adapters.release(aid)``
  over-decrements the refcount and drops someone else's pin.
  Idempotent double releases (``PagePool.release`` checks
  ``lease.released``) are deliberately silent — they match runtime.
* **DT603** (warning) — bare ``.acquire()`` on a lock without
  ``.release()`` on every path.  Complements the DT3xx lock-set tier:
  DT301/DT302 check *which* locks are held, DT603 checks they are
  *always dropped* — ``with``/try-finally discipline.
* **DT604** (warning) — a resource held across a ``yield`` (the
  consumer runs arbitrary code while the resource is pinned) or
  across an un-shimmed user callback (``on_*``/``*_callback`` call
  outside any try-with-handlers).  ``@contextmanager`` and pytest
  ``@fixture`` generators are exempt: there the yield *is* the
  handoff point.
* **DT605** (error) — protocol-order violation on an idempotent or
  terminal protocol: ``lease.register``/``handoff`` after
  ``release`` (the runtime silently no-ops, so the pages never
  publish), or re-running a terminal op (``handle.cancel`` on an
  already-terminal request handle).
"""
from __future__ import annotations

from typing import List, Optional, Set, Tuple

from .callgraph import Project
from .lifecycle import LifecycleModel, PROTOCOLS
from .report import Finding, Severity

__all__ = ["LIFECYCLE_RULES", "lifecycle_rule_catalog",
           "run_lifecycle_rules"]

LIFECYCLE_RULES: List[Tuple[str, str, str]] = [
    ("DT601", Severity.ERROR,
     "resource leaked on an exception or return path (acquire with no "
     "release and no ownership transfer on that path)"),
    ("DT602", Severity.ERROR,
     "use-after-release / double release of a non-idempotent resource "
     "(over-decrements a refcount or touches freed state)"),
    ("DT603", Severity.WARNING,
     "bare .acquire() without .release() on all paths — use `with` "
     "or try/finally (DT3xx checks which locks are held; this checks "
     "they are always dropped)"),
    ("DT604", Severity.WARNING,
     "resource held across a yield or an un-shimmed user callback "
     "(arbitrary foreign code runs while the resource is pinned)"),
    ("DT605", Severity.ERROR,
     "protocol-order violation: an intermediate op after release/"
     "handoff, or a terminal op repeated on a finished handle"),
]

_SEVERITY = {rule: sev for rule, sev, _ in LIFECYCLE_RULES}


def lifecycle_rule_catalog() -> List[Tuple[str, str, str]]:
    return list(LIFECYCLE_RULES)


def run_lifecycle_rules(project: Project,
                        select: Optional[Set[str]] = None,
                        ignore: Optional[Set[str]] = None
                        ) -> List[Finding]:
    """Run the typestate engine and convert its events to findings.

    ``select``/``ignore`` filter by rule id; per-line
    ``# dtlint: disable=DT60x`` suppressions are honored through the
    owning :class:`Source`.
    """
    model = LifecycleModel(project, PROTOCOLS)
    by_path = {info.src.path: info.src
               for info in project.functions.values()}
    findings: List[Finding] = []
    for event in model.events():
        if select is not None and event.rule not in select:
            continue
        if ignore is not None and event.rule in ignore:
            continue
        src = by_path.get(event.path)
        if src is not None and src.suppressed(event.rule, event.line):
            continue
        findings.append(Finding(
            rule=event.rule,
            severity=_SEVERITY.get(event.rule, Severity.WARNING),
            path=event.path, line=event.line, col=event.col,
            message=event.message,
            source_line=src.line_text(event.line) if src else ""))
    return findings
