"""Runtime resource ledger — the DT6xx tier's dynamic sibling.

The lifecycle typestate tier proves release-on-all-paths for the code
it can see statically; :class:`ResourceLedger` closes the gap at
runtime: it instruments the real acquire/release surfaces at class
level — ``PagePool.begin``/``release``/``handoff``,
``AdapterTable.acquire``/``release``, goodput ``_Frame`` enter/exit,
and the reqtrace live-span table — counts semantic transitions (an
idempotent second ``PagePool.release`` is *not* a release; an
``AdapterTable.release`` that finds no pin is an over-release, not a
balance credit), and raises :class:`LedgerImbalance` at exit when
anything acquired in its extent was never released.

Opt in per test with ``@pytest.mark.resource_ledger`` (the conftest
fixture wraps the test body) and drive it under the resilience fault
plans: an injected decode failure or replica kill that leaks a lease
fails the test *here*, with a per-resource imbalance table, instead of
poisoning a later test through a shared pool.

Class-level patching means every instance constructed inside the
extent is covered — no plumbing a probe through fixtures.  The ledger
additionally snapshots each pool/table it sees on first touch and
checks the instance gauges (``PagePool._lease_count``,
``AdapterTable._refs``) return to that snapshot, so pre-existing
long-lived instances balance relative to where they started.

When the body itself raises, the ledger restores the patches and
stays silent — an imbalance report must never mask the real failure.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["LedgerImbalance", "ResourceLedger"]

_SURFACES = ("pages", "adapters", "goodput", "reqtrace")


class LedgerImbalance(AssertionError):
    """A resource surface finished the ledger extent unbalanced."""


class ResourceLedger:
    """Context manager counting acquire/release transitions.

    ``track`` selects surfaces (default: all four).  ``counts()``
    exposes the raw counters for tests that want to assert exact
    traffic, not just balance.
    """

    _active_lock = threading.Lock()
    _active: Optional["ResourceLedger"] = None

    def __init__(self, track: Sequence[str] = _SURFACES):
        unknown = set(track) - set(_SURFACES)
        if unknown:
            raise ValueError(f"unknown ledger surface(s): "
                             f"{sorted(unknown)}; valid: {_SURFACES}")
        self.track = tuple(track)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._patches: List[Tuple[Any, str, Any]] = []
        self._pools: Dict[int, Tuple[Any, int]] = {}
        self._tables: Dict[int, Tuple[Any, Dict[str, int]]] = {}
        self._live_before: Optional[set] = None

    # ------------------------------------------------------- counters

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    # ------------------------------------------------------- patching

    def _patch(self, owner: Any, name: str, wrapper: Any) -> None:
        self._patches.append((owner, name, owner.__dict__[name]))
        setattr(owner, name, wrapper)

    def _instrument_pages(self) -> None:
        from ..serve.pages import PagePool
        ledger = self

        orig_begin = PagePool.begin
        orig_release = PagePool.release
        orig_handoff = PagePool.handoff

        def begin(pool, prompt, total_cols):
            ledger._note_pool(pool)
            lease = orig_begin(pool, prompt, total_cols)
            ledger._bump("pages.begin")
            return lease

        def release(pool, lease):
            ledger._note_pool(pool)
            was = lease.released
            orig_release(pool, lease)
            if not was and lease.released:
                ledger._bump("pages.release")

        def handoff(pool, lease, context):
            ledger._note_pool(pool)
            published = orig_handoff(pool, lease, context)
            ledger._bump("pages.handoff")
            return published

        self._patch(PagePool, "begin", begin)
        self._patch(PagePool, "release", release)
        self._patch(PagePool, "handoff", handoff)

    def _note_pool(self, pool: Any) -> None:
        with self._lock:
            if id(pool) not in self._pools:
                self._pools[id(pool)] = (pool, pool._lease_count)

    def _instrument_adapters(self) -> None:
        from ..serve.adapters import AdapterTable
        ledger = self

        orig_acquire = AdapterTable.acquire
        orig_release = AdapterTable.release

        def acquire(table, adapter_id):
            ledger._note_table(table)
            row = orig_acquire(table, adapter_id)
            if adapter_id is not None:
                ledger._bump("adapters.acquire")
            return row

        def release(table, adapter_id):
            ledger._note_table(table)
            if adapter_id is not None:
                # a release that finds no pin silently no-ops in the
                # table; the ledger books it as an over-release
                had = table._refs.get(adapter_id, 0) > 0
                orig_release(table, adapter_id)
                ledger._bump("adapters.release" if had
                             else "adapters.over_release")
            else:
                orig_release(table, adapter_id)

        self._patch(AdapterTable, "acquire", acquire)
        self._patch(AdapterTable, "release", release)

    def _note_table(self, table: Any) -> None:
        with self._lock:
            if id(table) not in self._tables:
                self._tables[id(table)] = (table, dict(table._refs))

    def _instrument_goodput(self) -> None:
        from ..obs.goodput import _Frame
        ledger = self

        orig_enter = _Frame.__enter__
        orig_exit = _Frame.__exit__

        def enter(frame):
            out = orig_enter(frame)
            ledger._bump("goodput.enter")
            return out

        def exit_(frame, *exc):
            out = orig_exit(frame, *exc)
            ledger._bump("goodput.exit")
            return out

        self._patch(_Frame, "__enter__", enter)
        self._patch(_Frame, "__exit__", exit_)

    def _instrument_reqtrace(self) -> None:
        from ..obs import reqtrace
        ledger = self
        self._live_before = set(reqtrace.live_ids())

        orig_submitted = reqtrace.submitted
        orig_imported = reqtrace.imported
        orig_retired = reqtrace.retired

        def submitted(*a, **kw):
            out = orig_submitted(*a, **kw)
            ledger._bump("reqtrace.submitted")
            return out

        def imported(*a, **kw):
            out = orig_imported(*a, **kw)
            ledger._bump("reqtrace.imported")
            return out

        def retired(*a, **kw):
            out = orig_retired(*a, **kw)
            ledger._bump("reqtrace.retired")
            return out

        self._patch(reqtrace, "submitted", submitted)
        self._patch(reqtrace, "imported", imported)
        self._patch(reqtrace, "retired", retired)

    # -------------------------------------------------------- extent

    def __enter__(self) -> "ResourceLedger":
        with ResourceLedger._active_lock:
            if ResourceLedger._active is not None:
                raise RuntimeError("ResourceLedger extents cannot nest "
                                   "(class-level patches would collide)")
            ResourceLedger._active = self
        try:
            if "pages" in self.track:
                self._instrument_pages()
            if "adapters" in self.track:
                self._instrument_adapters()
            if "goodput" in self.track:
                self._instrument_goodput()
            if "reqtrace" in self.track:
                self._instrument_reqtrace()
        except BaseException:
            self._restore()
            raise
        return self

    def _restore(self) -> None:
        for owner, name, orig in reversed(self._patches):
            setattr(owner, name, orig)
        self._patches.clear()
        with ResourceLedger._active_lock:
            if ResourceLedger._active is self:
                ResourceLedger._active = None

    def imbalances(self) -> List[str]:
        """Human-readable imbalance lines; empty when balanced."""
        c = self.counts()
        with self._lock:
            pools = list(self._pools.values())
            tables = list(self._tables.values())
        out: List[str] = []

        def pair(acq: str, rel: str, what: str) -> None:
            a, r = c.get(acq, 0), c.get(rel, 0)
            if a != r:
                out.append(f"{what}: {a} acquired vs {r} released "
                           f"({a - r:+d} leaked)" if a > r else
                           f"{what}: {r} released vs {a} acquired "
                           f"({r - a} excess releases)")

        if "pages" in self.track:
            pair("pages.begin", "pages.release", "page leases")
            for pool, before in pools:
                now = pool._lease_count
                if now != before:
                    out.append(f"PagePool {hex(id(pool))}: _lease_count "
                               f"{before} -> {now} across the extent")
        if "adapters" in self.track:
            pair("adapters.acquire", "adapters.release", "adapter pins")
            if c.get("adapters.over_release"):
                out.append(f"adapter pins: "
                           f"{c['adapters.over_release']} release(s) "
                           f"found no pin (double release)")
            for table, before in tables:
                now = dict(table._refs)
                if now != before:
                    out.append(f"AdapterTable {hex(id(table))}: _refs "
                               f"{before} -> {now} across the extent")
        if "goodput" in self.track:
            pair("goodput.enter", "goodput.exit", "goodput frames")
        if "reqtrace" in self.track and self._live_before is not None:
            from ..obs import reqtrace
            live_now = set(reqtrace.live_ids())
            leaked = live_now - self._live_before
            vanished = self._live_before - live_now
            if leaked:
                out.append(f"reqtrace: {len(leaked)} span(s) still "
                           f"live at exit: {sorted(leaked)[:8]}")
            if vanished:
                out.append(f"reqtrace: {len(vanished)} pre-existing "
                           f"span(s) retired inside the extent: "
                           f"{sorted(vanished)[:8]}")
        return out

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._restore()
        if exc_type is not None:
            return False       # never mask the test's own failure
        problems = self.imbalances()
        if problems:
            c = self.counts()
            traffic = ", ".join(f"{k}={v}" for k, v in sorted(c.items()))
            raise LedgerImbalance(
                "resource ledger unbalanced at extent exit:\n  - "
                + "\n  - ".join(problems)
                + (f"\n  traffic: {traffic}" if traffic else ""))
        return False
