"""Incremental dtlint result cache (``.dtlint-cache/``).

An unchanged tree must re-lint in well under a second: the expensive
work — per-file AST rule passes, the interprocedural/concurrency
project passes, and the graph tier's jax import + abstract traces — is
memoized on *content*, never on timestamps:

* per-file DT1xx results are keyed by ``sha1(path + file content)``;
* the DT2xx / DT3xx project passes and the DT4xx graph tier are keyed
  by a *tree hash* (every walked file's path + content hash) — any edit
  anywhere re-runs them, which is exactly their interprocedural
  contract.  The graph tier's key uses only the files under the package
  root (the entry registry traces package code; fixtures outside it
  can't change a trace);
* everything is invalidated wholesale when the rule catalog (ids +
  summaries), the ``--select``/``--ignore`` sets, or the cache format
  version change.

Storage is ONE json file (``index.json``) written atomically via
``tmp + os.replace``; each save writes only the current tree's entries,
so stale keys from old contents garbage-collect themselves.  All I/O is
best-effort: a corrupt or unwritable cache degrades to a cold run,
never to an error.  ``--no-cache`` (CI runs cold) skips it entirely;
``DTLINT_CACHE_DIR`` relocates it (tests point it at a tmpdir).
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, Iterable, List, Optional, Tuple

from .report import Finding

__all__ = ["ResultCache", "cache_dir"]

_VERSION = 1


def cache_dir() -> str:
    return os.environ.get("DTLINT_CACHE_DIR", ".dtlint-cache")


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8", "surrogatepass")).hexdigest()


class ResultCache:
    """Content-keyed findings cache.  Load once per run, ``save()`` once
    at the end (only when something was recomputed)."""

    def __init__(self, root: Optional[str] = None,
                 catalog: Iterable[Tuple[str, str, str]] = (),
                 flags: str = ""):
        self.root = root or cache_dir()
        self.path = os.path.join(self.root, "index.json")
        self.catalog_key = _sha1(
            f"v{_VERSION}|{flags}|"
            + "|".join(f"{r}:{s}:{m}" for r, s, m in catalog))
        self._files: Dict[str, list] = {}
        self._tiers: Dict[str, list] = {}
        self._dirty = False
        self._hits = 0
        self._misses = 0
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            if (doc.get("version") == _VERSION
                    and doc.get("catalog") == self.catalog_key):
                self._files = dict(doc.get("files", {}))
                self._tiers = dict(doc.get("tiers", {}))
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------- keys

    @staticmethod
    def content_hash(text: str) -> str:
        return _sha1(text)

    def file_key(self, path: str, content_hash: str,
                 mesh_axes: Iterable[str]) -> str:
        return _sha1(f"{path}|{content_hash}|{','.join(mesh_axes)}")

    @staticmethod
    def tree_key(tier: str,
                 hashes: Iterable[Tuple[str, str]]) -> str:
        body = "\n".join(f"{p}:{h}" for p, h in sorted(hashes))
        return f"{tier}:{_sha1(body)}"

    # ---------------------------------------------------------- get/put

    def get_file(self, key: str) -> Optional[List[Finding]]:
        return self._decode(self._files.get(key))

    def put_file(self, key: str, findings: List[Finding]) -> None:
        self._files[key] = [f.to_dict() for f in findings]
        self._dirty = True

    def get_tier(self, key: str) -> Optional[List[Finding]]:
        return self._decode(self._tiers.get(key))

    def put_tier(self, key: str, findings: List[Finding]) -> None:
        self._tiers[key] = [f.to_dict() for f in findings]
        self._dirty = True

    def _decode(self, rows) -> Optional[List[Finding]]:
        if rows is None:
            self._misses += 1
            return None
        self._hits += 1
        try:
            return [Finding(rule=r["rule"], severity=r["severity"],
                            path=r["path"], line=int(r["line"]),
                            col=int(r["col"]), message=r["message"],
                            source_line=r.get("source_line", ""))
                    for r in rows]
        except (KeyError, TypeError, ValueError):
            self._misses += 1
            return None

    # -------------------------------------------------------------- save

    def save(self, live_file_keys: Optional[Iterable[str]] = None,
             live_tier_keys: Optional[Iterable[str]] = None) -> None:
        """Persist — keeping only the keys the CURRENT run touched, so
        content churn garbage-collects old entries automatically."""
        if not self._dirty:
            return
        files = self._files
        tiers = self._tiers
        if live_file_keys is not None:
            live = set(live_file_keys)
            files = {k: v for k, v in files.items() if k in live}
        if live_tier_keys is not None:
            live = set(live_tier_keys)
            tiers = {k: v for k, v in tiers.items() if k in live}
        doc = {"version": _VERSION, "catalog": self.catalog_key,
               "files": files, "tiers": tiers}
        try:
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass          # best-effort: a read-only tree just runs cold
