"""Project model for dtlint's interprocedural pass: modules, functions,
imports, and call resolution.

``Project`` owns every parsed ``Source`` in an analysis run, keyed by
dotted module name derived from the file path (``pkg/train/step.py`` →
``pkg.train.step``; ``__init__.py`` names the package itself).  On top of
that it builds:

* a **function index** — every module-level ``def`` and every class
  method, addressable as ``(module, qualname)``;
* an **import table** per module — the walker's absolute-alias map plus
  relative imports (``from .step import make_train_step``) resolved
  against the module's package, which the walker deliberately skips;
* **call resolution** — a best-effort mapping from a ``Call`` node to the
  ``FunctionInfo`` it invokes, chasing re-export chains through package
  ``__init__`` barrels (``train.make_train_step`` →
  ``train.step.make_train_step``).

Resolution is deliberately conservative: bare names resolve to same-module
defs, dotted names resolve through imports/exports, ``self.method`` and
``cls.method`` resolve within the enclosing class.  Arbitrary
``obj.method`` attribute calls do NOT resolve (no type inference) — the
interprocedural rules err toward silence, never noise, exactly like the
per-module tier.  Pure stdlib, no JAX import.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Tuple

from .context import JitRegistry
from .walker import Source, call_name

__all__ = ["ClassInfo", "FunctionInfo", "Project", "module_name_for"]

_RESOLVE_DEPTH = 12  # re-export chains are short; bound against cycles


def module_name_for(path: str) -> str:
    """Dotted module name for a file path, e.g. ``pkg/a/b.py`` → ``pkg.a.b``.

    Leading ``./`` and drive/absolute prefixes are stripped; the caller is
    expected to hand in repo-relative paths (what ``collect_files`` emits).
    ``__init__.py`` maps to its package name.
    """
    norm = os.path.normpath(path).replace(os.sep, "/").lstrip("/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [p for p in norm.split("/") if p and p != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclasses.dataclass
class FunctionInfo:
    """One addressable function: a module-level def or a class method."""

    module: str
    qualname: str               # "fn" or "Class.method"
    node: ast.AST               # FunctionDef / AsyncFunctionDef
    src: Source

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def key(self) -> str:
        return f"{self.module}::{self.qualname}"

    def param_names(self, drop_self: bool = True) -> List[str]:
        a = self.node.args  # type: ignore[attr-defined]
        names = [p.arg for p in a.posonlyargs + a.args]
        if drop_self and "." in self.qualname and names \
                and names[0] in ("self", "cls"):
            names = names[1:]
        return names


@dataclasses.dataclass
class ClassInfo:
    """A project class — the anchor for instance-method resolution."""

    module: str
    name: str
    node: ast.ClassDef
    src: Source

    @property
    def key(self) -> str:
        return f"{self.module}::{self.name}"


def _relative_base(module: str, is_package: bool, level: int) -> Optional[str]:
    """Package that a level-``level`` relative import resolves against."""
    parts = module.split(".") if module else []
    if not is_package:
        parts = parts[:-1]
    up = level - 1
    if up > len(parts):
        return None
    return ".".join(parts[:len(parts) - up] if up else parts)


class Project:
    """All sources of one analysis run, with cross-module indexes."""

    def __init__(self, sources: Dict[str, Source],
                 packages: Optional[set] = None):
        # module name -> Source.  ``packages`` marks which module names are
        # packages (came from __init__.py) so relative imports resolve.
        self.sources = dict(sources)
        self.packages = set(packages or ())
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        self._registries: Dict[str, JitRegistry] = {}
        self._type_envs: Dict[int, Dict[str, str]] = {}
        for mod, src in self.sources.items():
            self._index_functions(mod, src)
            self.imports[mod] = self._import_table(mod, src)

    # ----------------------------------------------------------- build

    @classmethod
    def from_files(cls, paths: List[str]) -> "Project":
        sources: Dict[str, Source] = {}
        packages = set()
        for path in paths:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    text = fh.read()
                src = Source(path, text)
            except Exception:
                continue   # unparsable files are reported by the per-file pass
            mod = module_name_for(path)
            if not mod:
                continue
            sources[mod] = src
            if os.path.basename(path) == "__init__.py":
                packages.add(mod)
        return cls(sources, packages)

    @classmethod
    def from_sources(cls, sources: Dict[str, Source],
                     packages: Optional[set] = None) -> "Project":
        return cls(sources, packages)

    def _index_functions(self, mod: str, src: Source) -> None:
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(mod, node.name, node, src)
                self.functions[info.key] = info
            elif isinstance(node, ast.ClassDef):
                cinfo = ClassInfo(mod, node.name, node, src)
                self.classes[cinfo.key] = cinfo
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info = FunctionInfo(mod, f"{node.name}.{item.name}",
                                            item, src)
                        self.functions[info.key] = info

    def _import_table(self, mod: str, src: Source) -> Dict[str, str]:
        """local name -> dotted target, including RELATIVE imports (which
        the walker's alias map skips — it has no module context)."""
        table = dict(src.aliases)
        is_pkg = mod in self.packages
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ImportFrom) or not node.level:
                continue
            base = _relative_base(mod, is_pkg, node.level)
            if base is None:
                continue
            target = f"{base}.{node.module}" if node.module else base
            for a in node.names:
                if a.name == "*":
                    continue
                table[a.asname or a.name] = f"{target}.{a.name}"
        return table

    # ----------------------------------------------------------- query

    def registry(self, mod: str) -> JitRegistry:
        reg = self._registries.get(mod)
        if reg is None:
            reg = self._registries[mod] = JitRegistry(self.sources[mod])
        return reg

    def function(self, mod: str, qualname: str) -> Optional[FunctionInfo]:
        return self.functions.get(f"{mod}::{qualname}")

    def iter_functions(self) -> Iterator[FunctionInfo]:
        return iter(self.functions.values())

    def expand(self, mod: str, dotted: str) -> str:
        """Expand the head of ``dotted`` through ``mod``'s import table."""
        head, _, rest = dotted.partition(".")
        base = self.imports.get(mod, {}).get(head, head)
        return f"{base}.{rest}" if rest else base

    def resolve_dotted(self, dotted: str,
                      _depth: int = 0) -> Optional[FunctionInfo]:
        """Absolute dotted name -> FunctionInfo, chasing re-exports."""
        hit = self._resolve_dotted_any(dotted, _depth)
        return hit if isinstance(hit, FunctionInfo) else None

    def resolve_class_dotted(self, dotted: str) -> Optional[ClassInfo]:
        hit = self._resolve_dotted_any(dotted, 0)
        return hit if isinstance(hit, ClassInfo) else None

    def _resolve_dotted_any(self, dotted: str, _depth: int):
        if _depth > _RESOLVE_DEPTH:
            return None
        # longest module prefix wins: "pkg.train.step.make" tries
        # "pkg.train.step" before "pkg.train" before "pkg"
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod not in self.sources:
                continue
            rest = parts[cut:]
            if len(rest) <= 2:
                info = self.function(mod, ".".join(rest))
                if info is not None:
                    return info
            if len(rest) == 1:
                cinfo = self.classes.get(f"{mod}::{rest[0]}")
                if cinfo is not None:
                    return cinfo
            # re-export chase: the first remaining segment may be an
            # imported name inside ``mod`` (package barrel idiom)
            table = self.imports.get(mod, {})
            target = table.get(rest[0])
            if target is not None:
                tail = ".".join([target] + rest[1:])
                return self._resolve_dotted_any(tail, _depth + 1)
            return None
        return None

    def resolve_call(self, mod: str, call: ast.Call,
                     enclosing_class: Optional[str] = None,
                     types: Optional[Dict[str, str]] = None
                     ) -> Optional[FunctionInfo]:
        """Best-effort resolution of a Call in module ``mod``.

        ``types`` maps local instance names to ClassInfo keys (from
        :meth:`instance_types`), resolving ``model.init(...)`` when the
        scope contains ``model = GPT(...)``.
        """
        return self.resolve_name(mod, call_name(call), enclosing_class,
                                 types)

    def resolve_name(self, mod: str, dotted: Optional[str],
                     enclosing_class: Optional[str] = None,
                     types: Optional[Dict[str, str]] = None
                     ) -> Optional[FunctionInfo]:
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if head in ("self", "cls") and enclosing_class and rest \
                and "." not in rest:
            return self.function(mod, f"{enclosing_class}.{rest}")
        if types and head in types and rest and "." not in rest:
            cmod, _, cname = types[head].partition("::")
            return self.function(cmod, f"{cname}.{rest}")
        if not rest:
            # bare name: same-module def first, then imported function
            info = self.function(mod, head)
            if info is not None:
                return info
        target = self.imports.get(mod, {}).get(head)
        if target is None:
            return None
        tail = f"{target}.{rest}" if rest else target
        return self.resolve_dotted(tail)

    def resolve_class(self, mod: str, dotted: Optional[str]
                      ) -> Optional[ClassInfo]:
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            cinfo = self.classes.get(f"{mod}::{head}")
            if cinfo is not None:
                return cinfo
        target = self.imports.get(mod, {}).get(head)
        if target is None:
            return None
        tail = f"{target}.{rest}" if rest else target
        return self.resolve_class_dotted(tail)

    def instance_types(self, mod: str, scope: ast.AST) -> Dict[str, str]:
        """name -> ClassInfo key for ``x = SomeProjectClass(...)`` bindings
        visible in ``scope`` (module-level bindings merged under function
        scopes; conflicting rebinds drop to unknown).  Flow-insensitive —
        enough for the ``model = GPT(cfg); model.init(key)`` idiom."""
        cached = self._type_envs.get(id(scope))
        if cached is not None:
            return cached
        env: Dict[str, str] = {}
        src = self.sources.get(mod)
        at_module = src is not None and scope is src.tree
        if src is not None and not at_module:
            env.update(self.instance_types(mod, src.tree))
        # module scope: only top-level statements bind module names —
        # a function-local ``model = GPT()`` must not leak module-wide
        nodes = (scope.body if at_module
                 else [n for n in ast.walk(scope)])
        poisoned = set()
        for node in nodes:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            key = None
            if isinstance(node.value, ast.Call):
                cinfo = self.resolve_class(mod, call_name(node.value))
                if cinfo is not None:
                    key = cinfo.key
            if key is None:
                poisoned.add(tgt.id)
            elif env.get(tgt.id, key) != key:
                poisoned.add(tgt.id)
            else:
                env[tgt.id] = key
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                poisoned.add(p.arg)
            if a.vararg:
                poisoned.add(a.vararg.arg)
            if a.kwarg:
                poisoned.add(a.kwarg.arg)
        for name in poisoned:
            env.pop(name, None)
        self._type_envs[id(scope)] = env
        return env


def enclosing_class_of(node: ast.AST) -> Optional[str]:
    """Name of the nearest enclosing ClassDef, for self.method resolution."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = getattr(cur, "parent", None)
    return None


def positional_index(call: ast.Call, params: List[str],
                     name: str) -> Optional[Tuple[int, ast.AST]]:
    """(param index, arg node) at which plain Name ``name`` is passed."""
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Name) and a.id == name:
            return i, a
    for k in call.keywords:
        if k.arg and isinstance(k.value, ast.Name) and k.value.id == name:
            if k.arg in params:
                return params.index(k.arg), k.value
    return None
