"""dtlint graph-tier rules (DT400-DT405) over traced entry points.

Each rule reads the ``TracedEntry`` records ``analysis.graph`` produced
by abstractly tracing the registered entry points — program-level facts
the AST tiers cannot see.  Findings anchor at the *registration site*
(the ``@trace_entry``/``expect_census`` line), so the standard
``# dtlint: disable=DT40x`` comment there suppresses them and the
baseline fingerprints stay stable while the traced code churns.

Catalog (docs/ANALYSIS.md has the worked examples):

* **DT400** (error) — a registered entry failed to build or trace: the
  census and every other DT4xx answer is unverifiable until it's fixed.
* **DT401** (error) — large constant baked into the jaxpr: weights
  captured by closure instead of passed as arguments recompile per
  checkpoint and double-count HBM.  Threshold per entry
  (``const_bytes_limit``, default 1 MiB).
* **DT402** (warning/error) — dtype-promotion surprise: a matmul/conv
  consuming an operand that was *converted* to f32 from
  bf16/f16/int8 runs the hot-path FLOPs at full precision (warning);
  any f64/i64 aval anywhere is x64 leakage (error).
* **DT403** (error) — donated input not aliasable to any output
  (no output shares its shape/dtype): XLA silently rejects the
  donation, so the buffer the caller gave up is still resident —
  statically, what ``RetraceGuard`` only catches at runtime.
* **DT404** (error) — the entry's liveness peak (upper bound) exceeds
  the HBM budget declared at registration.
* **DT405** (error) — executable census: a census group's number of
  distinct traced program signatures differs from the pinned count
  (the serve tier pins "exactly 3 hot executables").
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .graph import (DEFAULT_CONST_BYTES_LIMIT, Registry, TracedEntry,
                    _CALL_PRIMS, _closed, _sub_jaxpr)
from .report import Finding, Severity

__all__ = ["GRAPH_RULES", "graph_rule_catalog", "run_graph_rules"]

GRAPH_RULES: List[Tuple[str, str, str]] = [
    ("DT400", Severity.ERROR,
     "registered graph entry failed to build or trace"),
    ("DT401", Severity.ERROR,
     "large constant baked into the jaxpr (closure-captured weights)"),
    ("DT402", Severity.WARNING,
     "dtype promotion surprise: f32 upcast of low-precision operands "
     "on the hot path / x64 leakage"),
    ("DT403", Severity.ERROR,
     "donated input not aliasable to any output (XLA rejects the "
     "donation silently)"),
    ("DT404", Severity.ERROR,
     "peak live-buffer estimate exceeds the entry's HBM budget"),
    ("DT405", Severity.ERROR,
     "executable census mismatch: distinct traced signatures != pinned "
     "count"),
]


def graph_rule_catalog() -> List[Tuple[str, str, str]]:
    return list(GRAPH_RULES)


# ---------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*dtlint:\s*disable\s*(?:=\s*([A-Z0-9,\s]+))?")

_LINE_CACHE: Dict[str, List[str]] = {}


def _line_text(path: str, line: int) -> str:
    if path not in _LINE_CACHE:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                _LINE_CACHE[path] = fh.read().splitlines()
        except OSError:
            _LINE_CACHE[path] = []
    lines = _LINE_CACHE[path]
    if 1 <= line <= len(lines):
        return lines[line - 1].strip()
    return ""


def _suppressed(path: str, line: int, rule: str) -> bool:
    m = _SUPPRESS_RE.search(_line_text(path, line))
    if not m:
        return False
    ids = m.group(1)
    if not ids:
        return True
    return rule in {r.strip() for r in ids.split(",")}


def _rel(path: str) -> str:
    try:
        cand = os.path.relpath(path)
        if not cand.startswith(".."):
            return cand
    except ValueError:
        pass
    return path


def _finding(rule: str, severity: str, path: str, line: int,
             message: str) -> Optional[Finding]:
    if _suppressed(path, line, rule):
        return None
    return Finding(rule=rule, severity=severity, path=_rel(path),
                   line=line, col=0, message=message,
                   source_line=_line_text(path, line))


def _fmt_bytes(n: float) -> str:
    return f"{n / (1 << 20):.1f} MiB"


# ------------------------------------------------------- DT402 traversal

_LOW_DTYPES = ("bfloat16", "float16", "int8", "uint8", "float8_e4m3fn",
               "float8_e5m2")
_X64_DTYPES = ("float64", "int64", "uint64", "complex128")


def _is_low(dtype) -> bool:
    return str(dtype) in _LOW_DTYPES


def _find_upcasts(closed) -> Tuple[List[str], List[str]]:
    """(upcast sites, x64 sites) over the whole program.

    Origin tracking: a value *converted* from a low-precision dtype to
    f32 carries its origin dtype; elementwise ops propagate the origin;
    a ``dot_general``/``conv`` consuming an f32 operand with a
    low-precision origin is an upcast site.  Direct low-precision
    operands (bf16 x bf16 -> f32 via ``preferred_element_type``) are the
    GOOD mixed-precision pattern and never flagged.
    """
    upcasts: List[str] = []
    x64: List[str] = []

    def origin_of(origins, v):
        if not hasattr(v, "aval") or type(v).__name__ == "Literal":
            return None
        return origins.get(v)

    def walk(jaxpr, origins):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            for v in eqn.outvars:
                if str(getattr(v.aval, "dtype", "")) in _X64_DTYPES:
                    x64.append(f"{name} -> {v.aval}")
            if name == "convert_element_type":
                src = eqn.invars[0]
                out = eqn.outvars[0]
                src_dt = (origin_of(origins, src)
                          or getattr(getattr(src, "aval", None),
                                     "dtype", None))
                if (src_dt is not None and _is_low(src_dt)
                        and str(out.aval.dtype) == "float32"):
                    origins[out] = str(src_dt)
                continue
            if name in ("dot_general", "conv_general_dilated"):
                for v in eqn.invars:
                    o = origin_of(origins, v)
                    if (o is not None and hasattr(v, "aval")
                            and str(v.aval.dtype) == "float32"):
                        upcasts.append(
                            f"{name}({'x'.join(map(str, v.aval.shape))} "
                            f"f32 upcast from {o})")
                continue
            subs = []
            if name == "scan":
                subs = [(_closed(eqn.params["jaxpr"]), eqn.invars)]
            elif name == "cond":
                subs = [(_closed(br), eqn.invars[1:])
                        for br in eqn.params.get("branches", ())]
            elif name in _CALL_PRIMS:
                sub = _sub_jaxpr(eqn)
                if sub is not None:
                    subs = [(sub, eqn.invars)]
            if subs:
                for sub, operands in subs:
                    inner: Dict = {}
                    for outer_v, inner_v in zip(operands,
                                                sub.jaxpr.invars):
                        o = origin_of(origins, outer_v)
                        if o is not None:
                            inner[inner_v] = o
                    walk(sub.jaxpr, inner)
                continue
            # default propagation: f32 results of ops fed by an upcast
            # value keep the origin (the low-precision data is still
            # the payload)
            o = None
            for v in eqn.invars:
                o = origin_of(origins, v)
                if o is not None:
                    break
            if o is not None:
                for v in eqn.outvars:
                    if str(getattr(v.aval, "dtype", "")) == "float32":
                        origins[v] = o

    walk(closed.jaxpr, {})
    return upcasts, x64


# ------------------------------------------------------------- the rules


def _rule_dt400(traced, registry, add):
    for te in traced:
        if te.error:
            tail = te.error.strip().splitlines()[-1]
            add("DT400", Severity.ERROR, te.path, te.line,
                f"graph entry '{te.name}' failed to trace — every DT4xx "
                f"answer for it is unverifiable: {tail}")


def _rule_dt401(traced, registry, add):
    for te in traced:
        if te.error:
            continue
        limit = te.const_bytes_limit or DEFAULT_CONST_BYTES_LIMIT
        big = [(s, d, n) for s, d, n in te.consts if n >= limit]
        if not big:
            continue
        total = sum(n for _, _, n in big)
        s, d, n = big[0]
        add("DT401", Severity.ERROR, te.path, te.line,
            f"entry '{te.name}' bakes {len(big)} constant(s) totalling "
            f"{_fmt_bytes(total)} into the jaxpr (largest: {d}"
            f"[{','.join(map(str, s))}] = {_fmt_bytes(n)}); closure-"
            f"captured weights recompile per checkpoint and double-"
            f"count HBM — pass them as traced arguments")


def _rule_dt402(traced, registry, add):
    for te in traced:
        if te.error:
            continue
        upcasts, x64 = _find_upcasts(te.closed)
        if upcasts:
            add("DT402", Severity.WARNING, te.path, te.line,
                f"entry '{te.name}' runs {len(upcasts)} matmul/conv "
                f"site(s) on f32-upcast low-precision operands (first: "
                f"{upcasts[0]}); the hot-path FLOPs run at full "
                f"precision — keep the operands narrow and accumulate "
                f"via preferred_element_type")
        if x64:
            add("DT402", Severity.ERROR, te.path, te.line,
                f"entry '{te.name}' traces {len(x64)} 64-bit value(s) "
                f"(first: {x64[0]}); x64 leakage doubles bytes and "
                f"falls off the TPU fast path")


def _rule_dt403(traced, registry, add):
    for te in traced:
        if te.error:
            continue
        rejected = [a for a, ok in te.donations if not ok]
        if not rejected:
            continue
        a = rejected[0]
        add("DT403", Severity.ERROR, te.path, te.line,
            f"entry '{te.name}' donates {len(rejected)} buffer(s) no "
            f"output can alias (first: {a.dtype}"
            f"[{','.join(map(str, a.shape))}]); XLA rejects such "
            f"donations silently — the 'freed' buffer stays resident "
            f"(drop the donation or return a matching output)")


def _rule_dt404(traced, registry, add):
    for te in traced:
        if te.error or te.hbm_budget is None or te.cost is None:
            continue
        peak = te.cost.peak_bytes
        if peak > te.hbm_budget:
            add("DT404", Severity.ERROR, te.path, te.line,
                f"entry '{te.name}' peak live-buffer estimate "
                f"{_fmt_bytes(peak)} exceeds its declared HBM budget "
                f"{_fmt_bytes(te.hbm_budget)} (liveness upper bound; "
                f"raise the budget only with a measured justification)")


def _rule_dt405(traced, registry, add):
    by_group: Dict[str, List[TracedEntry]] = {}
    for te in traced:
        if te.group:
            by_group.setdefault(te.group, []).append(te)
    for group, (expected, path, line) in registry.census.items():
        members = by_group.get(group, [])
        failed = [te.name for te in members if te.error]
        if failed:
            add("DT405", Severity.ERROR, path, line,
                f"census group '{group}' is unverifiable: "
                f"{len(failed)} member(s) failed to trace "
                f"({', '.join(sorted(failed))})")
            continue
        sigs: Dict[str, List[str]] = {}
        for te in members:
            sigs.setdefault(te.signature, []).append(te.name)
        if len(sigs) != expected:
            names = "; ".join(
                f"{sig[:8]}: {', '.join(sorted(ns))}"
                for sig, ns in sorted(sigs.items()))
            add("DT405", Severity.ERROR, path, line,
                f"census group '{group}' has {len(sigs)} distinct "
                f"traced executable(s), pinned at {expected} "
                f"({names or 'no members registered'}); a new "
                f"executable here means admission recompiles")


_RULE_FNS = [
    ("DT400", _rule_dt400), ("DT401", _rule_dt401),
    ("DT402", _rule_dt402), ("DT403", _rule_dt403),
    ("DT404", _rule_dt404), ("DT405", _rule_dt405),
]


def run_graph_rules(traced: List[TracedEntry], registry: Registry,
                    select: Optional[Set[str]] = None,
                    ignore: Optional[Set[str]] = None) -> List[Finding]:
    findings: List[Finding] = []

    for rule_id, fn in _RULE_FNS:
        if select is not None and rule_id not in select:
            continue
        if ignore is not None and rule_id in ignore:
            continue

        def add(rule, severity, path, line, message):
            f = _finding(rule, severity, path, line, message)
            if f is not None:
                findings.append(f)

        fn(traced, registry, add)
    return findings
