"""dtlint — JAX-aware static analysis for distributed-training hazards.

Catches, *before anything is compiled or run on an accelerator*, the
bug classes that otherwise surface as silent recompiles, HBM blowups,
or wrong numerics on the TPU.  Six tiers share one file walk:

* per-module (lexical, DT101-DT107): host syncs inside jit, PRNG key
  reuse, unbound mesh axes, non-hashable static args, jit wrappers
  built in loop bodies, reads of donated buffers, and wall-clock timing
  of unsynced jitted calls — the async-dispatch measurement lie;
* interprocedural (call-graph + dataflow summaries, DT201-DT204,
  ``callgraph.py`` / ``dataflow.py``): keys passed unsplit to multiple
  consumers across function boundaries, mesh-axis names flowing through
  cross-module constants, collective sequences diverging across
  ``lax.cond`` branches inside shard_map, and the donation contract
  propagated through the call graph;
* host-concurrency (lock-set inference, DT301-DT308,
  ``concurrency.py``): data races, lock-order cycles, callbacks and
  blocking calls under locks, thread hygiene;
* graph (jaxpr-level, DT400-DT405, ``graph.py`` / ``graph_rules.py``):
  registered entry points abstractly traced on CPU — constants baked
  into the program, f32 upcasts of low-precision operands, donations
  XLA rejects, liveness peaks over declared HBM budgets, and the
  executable census (``expect_census``) pinning invariants like "the
  serve tier has exactly 3 hot executables".  The same traversal prices
  every entry (FLOPs/bytes — ``entry_cost``), which bench.py reports
  as ``analytical_*`` fields next to measured numbers;
* SPMD (sharding propagation, DT501-DT505, ``spmd.py`` /
  ``spmd_rules.py``): reuses the graph tier's trace to propagate
  shardings and price every collective into a static comm ledger;
* resource lifecycle (typestate, DT601-DT605, ``lifecycle.py`` /
  ``lifecycle_rules.py``): declared acquire→release protocols (page
  leases, adapter pins, locks, request handles) proven released on
  every try/except/finally/return/raise path, with ownership-transfer
  rules so storing/returning/handing off a resource is not a leak.

Run it as a module::

    python -m distributed_tensorflow_tpu.analysis pkg/ --format json

or programmatically::

    from distributed_tensorflow_tpu import analysis
    findings = analysis.analyze_paths(["distributed_tensorflow_tpu"])

The static tier's runtime sibling lives in ``analysis.sanitizer``:
``RetraceGuard`` budgets jit retraces (with an actionable arg-diff per
unexpected recompile) and enforces donated-buffer invalidation at
execution time — see docs/ANALYSIS.md.

Suppress a single site with ``# dtlint: disable=DT101`` on the flagged
line (graph-tier findings anchor at the registration line); grandfather
existing debt with ``--write-baseline`` / ``--baseline``, and drop
fixed entries with ``--prune`` (see docs/ANALYSIS.md).  The AST tiers
are pure stdlib — analyzed code is parsed, never imported; the graph
tier imports the package and abstractly traces registered entries on
CPU (no devices, no compiles — the CLI defaults ``JAX_PLATFORMS=cpu``).
Results are content-hash cached under ``.dtlint-cache/`` (``--no-cache``
runs cold).
"""
from .baseline import (load_baseline, partition, prune_baseline,
                       write_baseline)
from .cache import ResultCache
from .callgraph import FunctionInfo, Project, module_name_for
from .cli import (analyze_file, analyze_paths, collect_files,
                  full_rule_catalog, main)
from .concurrency import (CONCURRENCY_RULES, ConcurrencyModel,
                          concurrency_rule_catalog,
                          run_concurrency_rules)
from .dataflow import ProjectDataflow
from .graph import (REGISTRY, Cost, Registry, Target, TracedEntry,
                    entry_cost, estimate_cost, expect_census,
                    program_signature, render_costs, trace_entry,
                    trace_registry)
from .graph_rules import (GRAPH_RULES, graph_rule_catalog,
                          run_graph_rules)
from .leak_ledger import LedgerImbalance, ResourceLedger
from .lifecycle import PROTOCOLS, LifecycleEvent, LifecycleModel
from .lifecycle_rules import (LIFECYCLE_RULES, lifecycle_rule_catalog,
                              run_lifecycle_rules)
from .project_rules import (PROJECT_RULES, project_rule_catalog,
                            run_project_rules)
from .race_harness import RaceHarness
from .report import (Finding, Severity, render_github, render_json,
                     render_text)
from .rules import RULES, run_rules
from .sanitizer import RetraceBudgetExceeded, RetraceGuard, retrace_guard
from .walker import Source, SourceError

rule_catalog = full_rule_catalog

__all__ = [
    "CONCURRENCY_RULES", "ConcurrencyModel", "Cost", "Finding",
    "FunctionInfo", "GRAPH_RULES", "LIFECYCLE_RULES", "LedgerImbalance",
    "LifecycleEvent", "LifecycleModel", "PROJECT_RULES", "PROTOCOLS",
    "Project", "ProjectDataflow", "REGISTRY", "RULES", "RaceHarness",
    "Registry", "ResourceLedger", "ResultCache",
    "RetraceBudgetExceeded", "RetraceGuard",
    "Severity", "Source", "SourceError", "Target", "TracedEntry",
    "analyze_file", "analyze_paths", "collect_files",
    "concurrency_rule_catalog", "entry_cost", "estimate_cost",
    "expect_census", "full_rule_catalog", "graph_rule_catalog",
    "lifecycle_rule_catalog", "load_baseline", "main",
    "module_name_for", "partition",
    "program_signature", "project_rule_catalog", "prune_baseline",
    "render_costs", "render_github", "render_json", "render_text",
    "retrace_guard", "rule_catalog", "run_concurrency_rules",
    "run_graph_rules", "run_lifecycle_rules", "run_project_rules",
    "run_rules", "trace_entry", "trace_registry", "write_baseline",
]
