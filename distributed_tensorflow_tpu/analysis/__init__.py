"""dtlint — JAX-aware static analysis for distributed-training hazards.

Catches, *before anything is traced or compiled*, the bug classes that
otherwise surface as silent recompiles or wrong numerics on the TPU.
Two tiers share one file walk:

* per-module (lexical): host syncs inside jit (DT101), PRNG key reuse
  (DT102), collectives naming unbound mesh axes (DT103), non-hashable
  static args (DT104), jit wrappers built in loop bodies (DT105), reads
  of donated buffers (DT106), and wall-clock timing of unsynced jitted
  calls — the async-dispatch measurement lie (DT107);
* interprocedural (call-graph + dataflow summaries, ``callgraph.py`` /
  ``dataflow.py``): keys passed unsplit to multiple consumers across
  function boundaries (DT201), mesh-axis names flowing through
  cross-module constants and ``make_mesh`` dicts (DT202), collective
  sequences diverging across ``lax.cond`` branches inside shard_map
  (DT203), and the donation contract propagated through the call graph
  (DT204).

Run it as a module::

    python -m distributed_tensorflow_tpu.analysis pkg/ --format json

or programmatically::

    from distributed_tensorflow_tpu import analysis
    findings = analysis.analyze_paths(["distributed_tensorflow_tpu"])

The static tier's runtime sibling lives in ``analysis.sanitizer``:
``RetraceGuard`` budgets jit retraces (with an actionable arg-diff per
unexpected recompile) and enforces donated-buffer invalidation at
execution time — see docs/ANALYSIS.md.

Suppress a single site with ``# dtlint: disable=DT101`` on the flagged
line; grandfather existing debt with ``--write-baseline`` /
``--baseline`` (see docs/ANALYSIS.md).  The analysis modules themselves
are pure stdlib — analyzed code is parsed, never imported or traced
(``python -m distributed_tensorflow_tpu.analysis`` does execute the
parent package ``__init__``; set ``JAX_PLATFORMS=cpu`` where no
accelerator should be touched).
"""
from .baseline import load_baseline, partition, write_baseline
from .callgraph import FunctionInfo, Project, module_name_for
from .cli import (analyze_file, analyze_paths, collect_files,
                  full_rule_catalog, main)
from .concurrency import (CONCURRENCY_RULES, ConcurrencyModel,
                          concurrency_rule_catalog,
                          run_concurrency_rules)
from .dataflow import ProjectDataflow
from .project_rules import (PROJECT_RULES, project_rule_catalog,
                            run_project_rules)
from .race_harness import RaceHarness
from .report import (Finding, Severity, render_github, render_json,
                     render_text)
from .rules import RULES, run_rules
from .sanitizer import RetraceBudgetExceeded, RetraceGuard, retrace_guard
from .walker import Source, SourceError

rule_catalog = full_rule_catalog

__all__ = [
    "CONCURRENCY_RULES", "ConcurrencyModel", "Finding", "FunctionInfo",
    "PROJECT_RULES", "Project", "ProjectDataflow", "RULES",
    "RaceHarness", "RetraceBudgetExceeded", "RetraceGuard",
    "Severity", "Source", "SourceError",
    "analyze_file", "analyze_paths", "collect_files",
    "concurrency_rule_catalog", "full_rule_catalog",
    "load_baseline", "main", "module_name_for", "partition",
    "project_rule_catalog", "render_github", "render_json", "render_text",
    "retrace_guard", "rule_catalog", "run_concurrency_rules",
    "run_project_rules", "run_rules", "write_baseline",
]
