"""dtlint — JAX-aware static analysis for distributed-training hazards.

Catches, *before anything is traced or compiled*, the bug classes that
otherwise surface as silent recompiles or wrong numerics on the TPU:
host syncs inside jit (DT101), PRNG key reuse (DT102), collectives naming
unbound mesh axes (DT103), non-hashable static args (DT104), jit wrappers
built in loop bodies (DT105), and reads of donated buffers (DT106).

Run it as a module::

    python -m distributed_tensorflow_tpu.analysis pkg/ --format json

or programmatically::

    from distributed_tensorflow_tpu import analysis
    findings = analysis.analyze_paths(["distributed_tensorflow_tpu"])

Suppress a single site with ``# dtlint: disable=DT101`` on the flagged
line; grandfather existing debt with ``--write-baseline`` /
``--baseline`` (see docs/ANALYSIS.md).  The analysis modules themselves
are pure stdlib — analyzed code is parsed, never imported or traced
(``python -m distributed_tensorflow_tpu.analysis`` does execute the
parent package ``__init__``; set ``JAX_PLATFORMS=cpu`` where no
accelerator should be touched).
"""
from .baseline import load_baseline, partition, write_baseline
from .cli import analyze_file, analyze_paths, collect_files, main
from .report import Finding, Severity, render_json, render_text
from .rules import RULES, rule_catalog, run_rules
from .walker import Source, SourceError

__all__ = [
    "Finding", "Severity", "Source", "SourceError", "RULES",
    "analyze_file", "analyze_paths", "collect_files", "main",
    "render_json", "render_text", "rule_catalog", "run_rules",
    "load_baseline", "partition", "write_baseline",
]
