"""dtlint DT2xx rules — interprocedural hazards over a whole Project.

  DT201  error    PRNG key passed unsplit to two consumers across
                  function boundaries (callee summaries, not names)
  DT202  error    mesh-axis names flowing through constants / make_mesh
                  checked against the project-wide axis registry
  DT203  error    lax.cond/lax.switch branches with mismatched collective
                  sequences inside shard_map/pmap (SPMD deadlock hazard)
  DT204  error    buffer read after a call to a function whose summary
                  donates that parameter (DT106's contract propagated
                  through the call graph)

These run AFTER the per-module tier over the same parsed sources; every
rule consumes ``dataflow.ProjectDataflow`` summaries and keeps the
family contract: resolution failures mean silence, never noise.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .callgraph import (FunctionInfo, Project, enclosing_class_of,
                        positional_index)
from .dataflow import TOP, ProjectDataflow
from .context import JitRegistry
from .report import Finding, Severity
from .rules import DonatedReuse, KeyReuse, UnknownMeshAxis, _is_key_param
from .walker import Source, assigned_names

__all__ = ["PROJECT_RULES", "run_project_rules", "project_rule_catalog"]


class ProjectContext:
    def __init__(self, project: Project, mesh_axes: Sequence[str]):
        self.project = project
        self.mesh_axes = tuple(mesh_axes)
        self.flow = ProjectDataflow(project)

    def finding(self, rule: str, severity: str, src: Source, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, severity=severity, path=src.path,
                       line=line, col=col, message=message,
                       source_line=src.line_text(line))


class ProjectRule:
    id: str = "DT200"
    severity: str = Severity.ERROR
    summary: str = ""

    def check(self, pctx: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------- DT201

class CrossFunctionKeyReuse(ProjectRule):
    id = "DT201"
    severity = Severity.ERROR
    summary = ("a PRNG key is passed unsplit to two key-consuming callees "
               "(or to one callee inside a loop) — every consumer derives "
               "identical random streams; split/fold_in per consumer")

    def check(self, pctx: ProjectContext) -> Iterator[Finding]:
        for mod, src in pctx.project.sources.items():
            scopes = [src.tree] + [
                n for n in ast.walk(src.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            for scope in scopes:
                yield from self._check_scope(pctx, mod, src, scope)

    def _check_scope(self, pctx: ProjectContext, mod: str, src: Source,
                     scope: ast.AST) -> Iterator[Finding]:
        last_assign: Dict[str, ast.AST] = {}
        # key var -> (node, "direct" | callee description)
        consumed_at: Dict[str, Tuple[ast.AST, Optional[str]]] = {}
        key_vars: Set[str] = set()
        cls = enclosing_class_of(scope)
        types = pctx.project.instance_types(mod, scope)

        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = scope.args
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if _is_key_param(a.arg):
                    key_vars.add(a.arg)
                    last_assign[a.arg] = scope

        own = [n for n in ast.walk(scope)
               if n is not scope and hasattr(n, "lineno")
               and KeyReuse._nearest_def(n) is scope]
        for node in sorted(own, key=lambda n: (n.lineno, n.col_offset)):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.NamedExpr,
                                 ast.AugAssign, ast.For)):
                value = node.iter if isinstance(node, ast.For) \
                    else node.value
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for nm in assigned_names(t):
                        last_assign[nm] = node
                        consumed_at.pop(nm, None)
                        if value is not None and KeyReuse._produces_key(
                                src, value):
                            key_vars.add(nm)
                        elif value is not None:
                            key_vars.discard(nm)
                continue
            if not isinstance(node, ast.Call):
                continue
            key_arg, kind = self._consumption(pctx, mod, src, node,
                                              key_vars, cls, types)
            if key_arg is None:
                continue
            prior = consumed_at.get(key_arg)
            if prior is not None and KeyReuse._exclusive_branches(
                    prior[0], node):
                continue
            if prior is not None:
                # at least one side must be an interprocedural consumer —
                # direct/direct pairs are DT102's finding, not ours
                if kind is None and prior[1] is None:
                    continue
                who = kind or "a jax.random call"
                prior_who = prior[1] or "a jax.random call"
                if not src.suppressed(self.id, node.lineno):
                    yield pctx.finding(
                        self.id, self.severity, src, node,
                        f"PRNG key '{key_arg}' already consumed by "
                        f"{prior_who} at line {prior[0].lineno} and is "
                        f"passed unsplit to {who} — both derive the same "
                        "random stream; split or fold_in between "
                        "consumers")
                continue
            if kind is not None:
                loop = KeyReuse._loop_outside_assignment(
                    node, last_assign.get(key_arg), scope)
                if loop is not None:
                    if not src.suppressed(self.id, node.lineno):
                        yield pctx.finding(
                            self.id, self.severity, src, node,
                            f"PRNG key '{key_arg}' is passed unsplit to "
                            f"{kind} inside a loop but produced outside "
                            "it — every iteration replays the same "
                            "stream; fold_in the loop index")
                    continue
            consumed_at[key_arg] = (node, kind)

    @staticmethod
    def _consumption(pctx: ProjectContext, mod: str, src: Source,
                     call: ast.Call, key_vars: Set[str],
                     cls: Optional[str],
                     types: Optional[Dict[str, str]] = None
                     ) -> Tuple[Optional[str], Optional[str]]:
        """(consumed key var, consumer description|None-for-direct)."""
        direct = KeyReuse._consumed_key(src, call)
        if direct is not None and direct in key_vars:
            return direct, None
        callee = pctx.project.resolve_call(mod, call, cls, types)
        if callee is None:
            return None, None
        summ = pctx.flow.summary(callee)
        if not summ.key_params:
            return None, None
        cparams = callee.param_names()
        for kv in key_vars:
            hit = positional_index(call, cparams, kv)
            if hit is None:
                continue
            i, _node = hit
            if i < len(cparams) and cparams[i] in summ.key_params:
                return kv, (f"'{callee.qualname}' "
                            f"({callee.module}, key-consuming)")
        return None, None


# --------------------------------------------------------------- DT202

class CrossFileMeshAxis(ProjectRule):
    id = "DT202"
    severity = Severity.ERROR
    summary = ("an axis name reaching a collective/PartitionSpec through a "
               "module-level constant — or a make_mesh axis dict — names "
               "an axis no mesh construction in the project binds")

    def check(self, pctx: ProjectContext) -> Iterator[Finding]:
        allowed = set(pctx.mesh_axes)
        for mod, src in pctx.project.sources.items():
            allowed |= UnknownMeshAxis._locally_declared(src)
            allowed |= pctx.project.registry(mod).module_axis_bindings
        for mod, src in pctx.project.sources.items():
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = src.call_canonical(node)
                if not name:
                    continue
                yield from self._check_constant_axes(
                    pctx, mod, src, node, name, allowed)
                yield from self._check_make_mesh(pctx, mod, src, node,
                                                 name)

    def _check_constant_axes(self, pctx, mod, src, node, name, allowed
                             ) -> Iterator[Finding]:
        """DT103's call positions, but for Name/Attribute operands that
        resolve to module-level string constants (cross-file reach)."""
        for cand in self._axis_operands(node, name):
            dotted = self._dotted(cand)
            if dotted is None:
                continue
            val = pctx.flow.consts.value_of(mod, dotted)
            if val is TOP:
                continue
            for axis in sorted(val):          # type: ignore[arg-type]
                if axis in allowed:
                    continue
                if src.suppressed(self.id, cand.lineno):
                    continue
                yield pctx.finding(
                    self.id, self.severity, src, cand,
                    f"axis '{axis}' (via constant '{dotted}') is not in "
                    f"AXIS_ORDER {tuple(sorted(pctx.mesh_axes))} and no "
                    "mesh construction or axis_name binding anywhere in "
                    "the project declares it")

    def _check_make_mesh(self, pctx, mod, src, node, name
                         ) -> Iterator[Finding]:
        """make_mesh({'axis': n}) keys must come from AXIS_ORDER — the
        runtime check raises ValueError only once a device mesh is built,
        typically deep inside a TPU window."""
        if name.rsplit(".", 1)[-1] != "make_mesh" or not node.args:
            return
        arg = node.args[0]
        keys: List[Tuple[str, ast.AST]] = []
        if isinstance(arg, ast.Dict):
            for k in arg.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.append((k.value, k))
                elif isinstance(k, (ast.Name, ast.Attribute)):
                    dotted = self._dotted(k)
                    if dotted is not None:
                        val = pctx.flow.consts.value_of(mod, dotted)
                        if val is not TOP:
                            keys.extend((a, k) for a in sorted(val))
        for axis, knode in keys:
            if axis in pctx.mesh_axes:
                continue
            if src.suppressed(self.id, knode.lineno):
                continue
            yield pctx.finding(
                self.id, self.severity, src, knode,
                f"make_mesh axis '{axis}' is not in AXIS_ORDER "
                f"{tuple(sorted(pctx.mesh_axes))} — make_mesh raises "
                "ValueError at runtime; fix the name or extend "
                "parallel/mesh.py AXIS_ORDER")

    @staticmethod
    def _axis_operands(node: ast.Call, name: str) -> Iterator[ast.AST]:
        """Axis-position operands that are Names/Attributes (the literal
        positions are DT103's, single-file)."""
        from .rules import (_COLLECTIVES_AXIS_ARG0, _COLLECTIVES_AXIS_ARG1,
                            _SPEC_MAKERS)
        short = name.rsplit(".", 1)[-1]
        cands: List[ast.AST] = []
        if name in _COLLECTIVES_AXIS_ARG1:
            if len(node.args) > 1:
                cands.append(node.args[1])
        elif name in _COLLECTIVES_AXIS_ARG0:
            if node.args:
                cands.append(node.args[0])
        elif short in _SPEC_MAKERS:
            cands.extend(node.args)
        elif short == "named_sharding":
            cands.extend(node.args[1:])
        for kw in node.keywords:
            if kw.arg == "axis_name":
                cands.append(kw.value)
        for c in cands:
            if isinstance(c, (ast.Name, ast.Attribute)):
                yield c

    @staticmethod
    def _dotted(node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            return ".".join(reversed(parts))
        return None


# --------------------------------------------------------------- DT203

_COND_NAMES = {"jax.lax.cond": "lax.cond", "jax.lax.switch": "lax.switch"}


class BranchCollectiveMismatch(ProjectRule):
    id = "DT203"
    severity = Severity.ERROR
    summary = ("lax.cond/lax.switch branches inside shard_map/pmap execute "
               "different collective sequences — if the predicate diverges "
               "across devices, the mismatched rendezvous deadlocks the "
               "mesh")

    def check(self, pctx: ProjectContext) -> Iterator[Finding]:
        regions = self._spmd_regions(pctx)
        seen: Set[int] = set()
        for info_like, region in regions:
            for node in ast.walk(region):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                name = info_like.src.call_canonical(node)
                if name not in _COND_NAMES:
                    continue
                seen.add(id(node))
                yield from self._check_cond(pctx, info_like, node,
                                            _COND_NAMES[name])

    def _spmd_regions(self, pctx: ProjectContext
                      ) -> List[Tuple[FunctionInfo, ast.AST]]:
        """(context fn, AST region) pairs traced by shard_map/pmap,
        plus project functions reachable from them via resolved calls."""
        out: List[Tuple[FunctionInfo, ast.AST]] = []
        work: List[FunctionInfo] = []
        done: Set[str] = set()
        for mod, src in pctx.project.sources.items():
            reg = pctx.project.registry(mod)
            for site in reg.sites:
                if "shard_map" not in site.wrapper \
                        and site.wrapper != "jax.pmap":
                    continue
                if site.target is None:
                    continue
                home = FunctionInfo(mod, getattr(site.target, "name",
                                                 "<lambda>"),
                                    site.target, src)
                out.append((home, site.target))
                work.append(home)
        while work:
            home = work.pop()
            cls = enclosing_class_of(home.node)
            types = pctx.project.instance_types(home.module, home.node) \
                if isinstance(home.node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) else {}
            for call in [n for n in ast.walk(home.node)
                         if isinstance(n, ast.Call)]:
                callee = pctx.project.resolve_call(home.module, call, cls,
                                                   types)
                if callee is None or callee.key in done:
                    continue
                done.add(callee.key)
                out.append((callee, callee.node))
                work.append(callee)
        return out

    def _check_cond(self, pctx: ProjectContext, home: FunctionInfo,
                    call: ast.Call, what: str) -> Iterator[Finding]:
        branches = self._branches(call, what)
        if branches is None or len(branches) < 2:
            return
        sigs: List[Tuple[str, Tuple[str, ...]]] = []
        for label, branch in branches:
            sig = self._branch_signature(pctx, home, branch)
            if sig is None:
                return        # unresolvable branch: stay silent
            sigs.append((label, sig))
        baseline = sigs[0][1]
        for label, sig in sigs[1:]:
            if sig != baseline:
                if home.src.suppressed(self.id, call.lineno):
                    return
                yield pctx.finding(
                    self.id, self.severity, home.src, call,
                    f"{what} branches disagree on collectives: "
                    f"{sigs[0][0]} runs {list(baseline) or 'none'}, "
                    f"{label} runs {list(sig) or 'none'} — inside "
                    "shard_map/pmap a divergent predicate deadlocks the "
                    "mesh; hoist the collectives out of the branches")
                return

    @staticmethod
    def _branches(call: ast.Call, what: str
                  ) -> Optional[List[Tuple[str, ast.AST]]]:
        if what == "lax.cond":
            if len(call.args) < 3:
                return None
            return [("true branch", call.args[1]),
                    ("false branch", call.args[2])]
        if len(call.args) < 2:
            return None
        seq = call.args[1]
        if not isinstance(seq, (ast.Tuple, ast.List)):
            return None
        return [(f"branch {i}", b) for i, b in enumerate(seq.elts)]

    def _branch_signature(self, pctx: ProjectContext, home: FunctionInfo,
                          branch: ast.AST
                          ) -> Optional[Tuple[str, ...]]:
        if isinstance(branch, ast.Lambda):
            return pctx.flow.signature_of_node(branch.body, home)
        if isinstance(branch, ast.Name):
            local = self._local_def(home.node, branch.id)
            if local is not None:
                return pctx.flow.signature_of_node(
                    local, FunctionInfo(home.module, branch.id, local,
                                        home.src))
            callee = pctx.project.resolve_name(
                home.module, branch.id, enclosing_class_of(home.node))
            if callee is not None:
                return pctx.flow.collective_signature(callee)
        return None

    @staticmethod
    def _local_def(scope: ast.AST, name: str) -> Optional[ast.AST]:
        best = None
        for n in ast.walk(scope):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name == name:
                best = n
        return best


# --------------------------------------------------------------- DT204

class InterprocDonatedReuse(ProjectRule):
    id = "DT204"
    severity = Severity.ERROR
    summary = ("a buffer is read after a call to a function whose summary "
               "donates that parameter (directly, transitively, or via a "
               "returned jit-with-donation callable) — dead buffer on TPU")

    def check(self, pctx: ProjectContext) -> Iterator[Finding]:
        for mod, src in pctx.project.sources.items():
            reg = pctx.project.registry(mod)
            builder_sites = self._builder_assignments(pctx, mod, src, reg)
            for call in [n for n in ast.walk(src.tree)
                         if isinstance(n, ast.Call)]:
                yield from self._check_call(pctx, mod, src, reg,
                                            builder_sites, call)

    def _builder_assignments(self, pctx: ProjectContext, mod: str,
                             src: Source, reg: JitRegistry
                             ) -> Dict[str, Tuple[Tuple[int, ...], str]]:
        """local name -> (donate_argnums, builder qualname) for names
        assigned from a resolved builder whose returned callable donates.
        Names the per-module registry already tracks (jit sites and the
        make_*train_step regex contract) stay DT106's — skipped here."""
        out: Dict[str, Tuple[Tuple[int, ...], str]] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) \
                    or not isinstance(node.value, ast.Call):
                continue
            if tgt.id in reg.site_by_name:
                continue
            callee = pctx.project.resolve_call(
                mod, node.value, enclosing_class_of(node))
            if callee is None:
                continue
            nums = pctx.flow.summary(callee).returns_donate_argnums
            if nums:
                out[tgt.id] = (nums, callee.qualname)
        return out

    def _check_call(self, pctx: ProjectContext, mod: str, src: Source,
                    reg: JitRegistry,
                    builder_sites: Dict[str, Tuple[Tuple[int, ...], str]],
                    call: ast.Call) -> Iterator[Finding]:
        func = call.func
        donated: List[Tuple[int, str]] = []   # (argnum, contract descr)
        if isinstance(func, ast.Name) and func.id in builder_sites:
            nums, builder = builder_sites[func.id]
            donated = [(i, f"built by '{builder}' (returns jit with "
                           f"donate_argnums={nums})") for i in nums]
        else:
            if isinstance(func, ast.Name) and func.id in reg.site_by_name:
                return                      # DT106's per-module domain
            scope = KeyReuse._nearest_def(call)
            types = pctx.project.instance_types(mod, scope) \
                if isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) else None
            callee = pctx.project.resolve_call(mod, call,
                                              enclosing_class_of(call),
                                              types)
            if callee is None:
                return
            summ = pctx.flow.summary(callee)
            if not summ.donated_params:
                return
            params = callee.param_names()
            donated = [(i, f"'{callee.qualname}' ({callee.module}) "
                           f"donates parameter '{p}'")
                       for i, p in enumerate(params)
                       if p in summ.donated_params]
        for i, descr in donated:
            if i >= len(call.args):
                continue
            arg = call.args[i]
            if not isinstance(arg, ast.Name):
                continue
            reuse = DonatedReuse._use_after(src, call, arg.id)
            if reuse is None:
                continue
            if src.suppressed(self.id, reuse.lineno):
                continue
            yield pctx.finding(
                self.id, self.severity, src, reuse,
                f"'{arg.id}' is read here but was donated at line "
                f"{call.lineno}: {descr} — the buffer is dead on TPU; "
                "rebind the result instead")


PROJECT_RULES: List[ProjectRule] = [
    CrossFunctionKeyReuse(), CrossFileMeshAxis(),
    BranchCollectiveMismatch(), InterprocDonatedReuse()]


def project_rule_catalog() -> List[Tuple[str, str, str]]:
    return [(r.id, r.severity, r.summary) for r in PROJECT_RULES]


def run_project_rules(project: Project, mesh_axes: Sequence[str],
                      select: Optional[Set[str]] = None,
                      ignore: Optional[Set[str]] = None) -> List[Finding]:
    pctx = ProjectContext(project, mesh_axes)
    by_path = {src.path: src for src in project.sources.values()}
    out: List[Finding] = []
    for rule in PROJECT_RULES:
        if select and rule.id not in select:
            continue
        if ignore and rule.id in ignore:
            continue
        for f in rule.check(pctx):
            src = by_path.get(f.path)
            if src is not None and src.suppressed(f.rule, f.line):
                continue
            out.append(f)
    return out
