"""Mixture-of-Experts FFN with expert parallelism over an ``expert`` axis.

The reference has no routing/expert code (SURVEY.md §2c EP row: NO); this
supplies expert parallelism TPU-natively so the full dp/fsdp/tp/sp/pp/ep
axis set of ``parallel.mesh.AXIS_ORDER`` is covered.

TPU-first design (GShard/Switch style, not a port):
  * routing, dispatch and combine are dense einsums over one-hot
    capacity-slot masks — static shapes, MXU-friendly, no gather/scatter or
    data-dependent control flow, so the whole layer jits into one XLA
    program;
  * expert weights carry a leading ``num_experts`` dim sharded
    ``P('expert')``; with tokens sharded over ``data``, XLA lowers the
    dispatch/combine einsums to ``all_to_all`` over ICI automatically — the
    collective is implied by shardings, never hand-written;
  * over-capacity tokens are dropped (output zeros) — callers add the
    residual connection so dropped tokens degrade to identity, the standard
    MoE-transformer contract.

``aux_loss`` (Switch load-balancing: E * Σ_e f_e·P_e, =1.0 at perfect
balance) and ``router_z_loss`` must be added to the training loss by the
caller to keep routing healthy.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import activations as act_lib
from . import initializers as init_lib

__all__ = ["init_moe", "apply_moe", "moe_partition_rules"]


def init_moe(key, d_model: int, d_ff: int, num_experts: int,
             param_dtype=jnp.float32) -> Dict[str, Any]:
    """Router + a bank of ``num_experts`` two-matmul FFNs (leading E dim)."""
    k_r, k_in, k_out = jax.random.split(key, 3)
    glorot = init_lib.get("glorot_uniform")
    w_in = jnp.stack([
        glorot(k, (d_model, d_ff), param_dtype)
        for k in jax.random.split(k_in, num_experts)])
    w_out = jnp.stack([
        glorot(k, (d_ff, d_model), param_dtype)
        for k in jax.random.split(k_out, num_experts)])
    return {
        "router": {"kernel": glorot(k_r, (d_model, num_experts), param_dtype)},
        "experts": {
            "w_in": w_in,                                   # [E, D, F]
            "b_in": jnp.zeros((num_experts, d_ff), param_dtype),
            "w_out": w_out,                                 # [E, F, D]
            "b_out": jnp.zeros((num_experts, d_model), param_dtype),
        },
    }


def moe_partition_rules():
    """(regex, PartitionSpec) rows for ``parallel.PartitionRules``: experts
    sharded over ``expert``, the FFN hidden dim optionally over ``tensor``,
    router replicated."""
    return [
        (r"experts/w_in$", P("expert", None, "tensor")),
        (r"experts/b_in$", P("expert", "tensor")),
        (r"experts/w_out$", P("expert", "tensor", None)),
        (r"experts/b_out$", P("expert", None)),
        (r"router/", P()),
    ]


def _top_k_dispatch(probs: jnp.ndarray, k: int, capacity: int):
    """One-hot capacity-slot dispatch/combine tensors from router probs.

    probs: [T, E].  Returns (dispatch [T, E, C] bool-ish float,
    combine [T, E, C] float, top1_mask [T, E]).
    Iterative arg-max (k is 1 or 2 in practice): choice i masks out the
    experts already taken, then tokens claim capacity slots in token order
    via a cumsum — all static-shape, no sort network needed.
    """
    t, e = probs.shape
    remaining = probs
    fill = jnp.zeros((e,), jnp.int32)          # slots already used per expert
    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    combine = jnp.zeros((t, e, capacity), probs.dtype)
    top1_mask = None
    gate_sum = jnp.zeros((t,), probs.dtype)

    for i in range(k):
        idx = jnp.argmax(remaining, axis=-1)               # [T]
        mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)   # [T, E]
        if i == 0:
            top1_mask = mask
        gate = jnp.sum(probs * mask, axis=-1)              # [T]
        # Position of each token within its chosen expert's capacity.
        pos = (jnp.cumsum(mask, axis=0) - 1) * mask + fill[None, :] * mask
        pos_tok = jnp.sum(pos, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos_tok < capacity) & (jnp.max(mask, axis=-1) > 0)
        slot = jax.nn.one_hot(pos_tok, capacity,
                              dtype=probs.dtype)           # [T, C]
        assign = (mask[:, :, None] * slot[:, None, :]
                  * keep[:, None, None].astype(probs.dtype))
        dispatch = dispatch + assign
        combine = combine + assign * gate[:, None, None]
        gate_sum = gate_sum + gate * keep.astype(probs.dtype)
        fill = fill + jnp.sum(assign, axis=(0, 2)).astype(jnp.int32)
        remaining = remaining * (1.0 - mask)

    # Normalize combine weights over the (kept) top-k gates per token.
    combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]
    return dispatch, combine, top1_mask


def apply_moe(params: Dict[str, Any], x: jnp.ndarray, *, k: int = 2,
              capacity_factor: float = 1.25,
              capacity: Optional[int] = None,
              group_size: Optional[int] = None,
              activation="gelu", train: bool = False, rng=None,
              jitter: float = 1e-2) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """x: [..., d_model] -> (y [..., d_model], metrics).

    Routing is GROUPED (GShard style): tokens are split into fixed-size
    groups and each group routes into its own per-expert capacity slots, so
    the dispatch/combine tensors are [G, S, E, C] with C ∝ S — linear in
    total tokens, never O(T²).  Default grouping: the leading (batch) dim
    when ``x`` has ≥3 dims, one group otherwise; ``group_size`` overrides
    (must divide the token count).  ``capacity`` is per group per expert.

    ``metrics['aux_loss']`` / ``metrics['router_z_loss']`` are scalars the
    caller adds to the loss (weighted ~1e-2 / ~1e-3).  Dropped (over-
    capacity) tokens return zeros — add the residual outside.
    ``jitter``: multiplicative router-input noise when ``train`` and ``rng``.
    """
    act = act_lib.get(activation)
    *lead, d = x.shape
    tokens = x.reshape(-1, d)
    t = tokens.shape[0]
    e = params["experts"]["w_in"].shape[0]

    if group_size is None:
        group_size = t // x.shape[0] if x.ndim >= 3 else t
    if t % group_size:
        raise ValueError(f"group_size {group_size} does not divide token "
                         f"count {t}")
    tok = tokens.reshape(-1, group_size, d)                # [G, S, D]
    if capacity is None:
        capacity = max(1, int(capacity_factor * k * group_size / e))

    router_in = tok
    if train and rng is not None and jitter > 0:
        router_in = tok * jax.random.uniform(
            rng, tok.shape, tok.dtype, 1.0 - jitter, 1.0 + jitter)
    logits = jnp.einsum("gsd,de->gse", router_in,
                        params["router"]["kernel"].astype(x.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch, combine, top1 = jax.vmap(
        lambda p: _top_k_dispatch(p, k, capacity))(probs)  # [G,S,E,C] x2
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    ex = params["experts"]
    # [G,S,E,C] x [G,S,D] -> [G,E,C,D]: the all_to_all boundary under
    # sharding (groups ride ``data``, experts ride ``expert``).
    staged = jnp.einsum("gsec,gsd->gecd", dispatch, tok)
    h = act(jnp.einsum("gecd,edf->gecf", staged, ex["w_in"].astype(x.dtype))
            + ex["b_in"].astype(x.dtype)[None, :, None, :])
    out_e = (jnp.einsum("gecf,efd->gecd", h, ex["w_out"].astype(x.dtype))
             + ex["b_out"].astype(x.dtype)[None, :, None, :])
    y = jnp.einsum("gsec,gecd->gsd", combine, out_e)

    frac_tokens = jnp.mean(top1, axis=(0, 1))              # f_e
    mean_probs = jnp.mean(probs, axis=(0, 1))              # P_e
    aux_loss = e * jnp.sum(frac_tokens * mean_probs)
    z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    metrics = {
        "aux_loss": aux_loss.astype(jnp.float32),
        "router_z_loss": jnp.mean(z ** 2),
        "dropped_fraction": 1.0 - jnp.sum(dispatch) / (k * t),
    }
    return y.reshape(*lead, d), metrics
