"""Functional op/layer library (compute tier: everything lowers to XLA HLO)."""

from . import activations, attention, initializers, losses, metrics, moe, quant
from .attention import MultiHeadAttention, causal_mask, dot_product_attention
from .moe import apply_moe, init_moe, moe_partition_rules
from .layers import (GRU, LSTM, Activation, AvgPool2D, BatchNorm, Conv1D,
                     Conv2D, Dense, DepthwiseConv2D, Dropout, Embedding,
                     Flatten, GlobalAvgPool, Layer, LayerNorm, MaxPool2D,
                     SeparableConv2D, Stack, serial)

__all__ = [
    "activations", "attention", "initializers", "losses", "metrics", "moe",
    "quant",
    "apply_moe", "init_moe", "moe_partition_rules",
    "MultiHeadAttention", "causal_mask", "dot_product_attention",
    "Activation", "AvgPool2D", "BatchNorm", "Conv1D", "Conv2D", "Dense",
    "DepthwiseConv2D", "Dropout", "Embedding", "Flatten", "GlobalAvgPool",
    "GRU", "LSTM", "Layer", "LayerNorm", "MaxPool2D", "SeparableConv2D",
    "Stack", "serial",
]
