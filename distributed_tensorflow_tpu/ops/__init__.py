"""Functional op/layer library (compute tier: everything lowers to XLA HLO)."""

from . import activations, initializers, losses, metrics
from .layers import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                     Embedding, Flatten, GlobalAvgPool, Layer, LayerNorm,
                     MaxPool2D, Stack, serial)

__all__ = [
    "activations", "initializers", "losses", "metrics",
    "Activation", "AvgPool2D", "BatchNorm", "Conv2D", "Dense", "Dropout",
    "Embedding", "Flatten", "GlobalAvgPool", "Layer", "LayerNorm",
    "MaxPool2D", "Stack", "serial",
]
