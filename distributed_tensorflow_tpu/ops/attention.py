"""Attention ops: scaled-dot-product and multi-head attention.

The reference has no attention at all (its model is an MLP, reference
example.py:149-155); this module exists for the driver's BERT-base baseline
config and the long-context design requirement (SURVEY.md §5 long-context
row).  TPU-first choices:

  * head layout ``[batch, seq, heads, head_dim]`` with projections stored
    ``[d_model, heads, head_dim]`` — the heads axis is the natural tensor-
    parallel shard (``P(None, 'tensor', None)``), so TP needs no reshapes;
  * logits/softmax computed in float32 regardless of activation dtype
    (bf16-safe), matmuls in the input dtype so they hit the MXU in bf16;
  * additive masks (0 / -inf convention) so causal+padding masks compose by
    addition and fuse into one XLA op.

``ring_attention`` (sequence parallelism over the ``seq`` mesh axis) builds
on this module from ``parallel.ring``; a fused Pallas flash-attention kernel
slots in behind the same ``dot_product_attention`` signature.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from . import initializers as init_lib
from .layers import Layer

__all__ = ["dot_product_attention", "causal_mask", "padding_mask",
           "attention_core", "ffn_core", "ffn_swiglu_core",
           "rotary_embedding", "rope_tables", "apply_rope",
           "MultiHeadAttention", "flash_wins", "resolve_use_flash",
           "paged_kernel_wins", "resolve_use_paged_kernel"]

NEG_INF = -1e9  # finite -inf stand-in: keeps softmax well-defined in f32

# Sequence length at/above which the fused Pallas flash kernel dispatches
# under use_flash="auto".  Measured on v5e with (512, 1024) blocks and
# RTT-amortised scan timing (docs/PERF.md, 2026-07-31): flash ties XLA at
# seq <= 1024 (0.95x), wins 1.3-1.7x at 2048 and ~3x at 4096 — XLA's
# materialised s^2 logits hit memory pressure exactly where the kernel's
# O(seq) streaming pays off.  Override with DTTPU_FLASH_MIN_SEQ;
# re-calibrate with scripts/validate_flash_tpu.py on new hardware.
_FLASH_MIN_SEQ_DEFAULT = 2048


def flash_wins(seq_len: int) -> bool:
    """Auto-dispatch policy: fused flash attention only on a real TPU
    backend and only at sequence lengths past the measured crossover."""
    import os

    import jax as _jax
    min_seq = int(os.environ.get("DTTPU_FLASH_MIN_SEQ",
                                 _FLASH_MIN_SEQ_DEFAULT))
    return seq_len >= min_seq and _jax.default_backend() == "tpu"


def resolve_activation(name: str):
    """Config ``hidden_act`` string -> activation fn — ONE mapping for
    every model config (BertConfig/ViTConfig) so numerics fixes and new
    activations land in exactly one place (same principle as ffn_core)."""
    import functools
    table = {
        "gelu_approx": jax.nn.gelu,                        # tanh, zoo default
        "gelu_new": jax.nn.gelu,                           # HF alias (tanh)
        "gelu_pytorch_tanh": jax.nn.gelu,                  # HF alias (tanh)
        "gelu": functools.partial(jax.nn.gelu, approximate=False),  # erf
        "relu": jax.nn.relu,
    }
    if name not in table:
        raise ValueError(f"unsupported hidden_act {name!r}; "
                         f"one of {sorted(table)}")
    return table[name]


def resolve_use_flash(use_flash, seq_len: int) -> bool:
    """Resolve a config's ``use_flash`` (True / False / "auto") for one
    forward at ``seq_len`` — the single dispatch point for BERT/GPT."""
    if use_flash == "auto":
        return flash_wins(seq_len)
    return bool(use_flash)


# Per-slot view length (pages_per_slot x page_size) at/above which the
# fused paged-attention kernel (ops/pallas/paged_attention.py)
# dispatches under use_paged_kernel="auto".  Seeded from the same v5e
# methodology as _FLASH_MIN_SEQ_DEFAULT: the XLA page-gather the kernel
# removes costs O(view_len) HBM traffic per layer per step, so the
# kernel wins as soon as the gathered operand stops fitting the fusion
# window — measured crossover printed by scripts/validate_paged_tpu.py;
# override with DTTPU_PAGED_KERNEL_MIN_VIEW, re-calibrate on new
# hardware.
_PAGED_KERNEL_MIN_VIEW_DEFAULT = 512


def paged_kernel_wins(view_len: int) -> bool:
    """Auto-dispatch policy: the fused paged-attention kernel only on a
    real TPU backend and only at per-slot view lengths past the measured
    crossover (off-TPU the interpret-mode kernel is a correctness tool,
    never a win)."""
    import os

    import jax as _jax
    min_view = int(os.environ.get("DTTPU_PAGED_KERNEL_MIN_VIEW",
                                  _PAGED_KERNEL_MIN_VIEW_DEFAULT))
    return view_len >= min_view and _jax.default_backend() == "tpu"


def resolve_use_paged_kernel(use_paged_kernel, view_len: int) -> bool:
    """Resolve a scheduler's ``use_paged_kernel`` (True / False /
    "auto") for a paged build whose slots see ``view_len`` logical
    columns — the single dispatch point for the serve tier's paged read
    path (serve/scheduler.py resolves once at construction; the page-
    size tileability check lives there too, so this stays a pure policy
    function)."""
    if use_paged_kernel == "auto":
        return paged_kernel_wins(view_len)
    return bool(use_paged_kernel)


def causal_mask(seq_len: int) -> jnp.ndarray:
    """[1, 1, seq, seq] additive mask; position i attends to j<=i."""
    mask = jnp.tril(jnp.ones((seq_len, seq_len), jnp.bool_))
    return jnp.where(mask, 0.0, NEG_INF)[None, None, :, :]


def padding_mask(valid: jnp.ndarray) -> jnp.ndarray:
    """valid: [batch, seq] bool/int (1 = real token) -> [b, 1, 1, seq]."""
    return jnp.where(valid.astype(jnp.bool_), 0.0, NEG_INF)[:, None, None, :]


def dot_product_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          mask: Optional[jnp.ndarray] = None,
                          scale: Optional[float] = None) -> jnp.ndarray:
    """q: [batch, seq, heads, head_dim]; k,v: same, or with FEWER heads
    (grouped-query attention) -> [batch, seq, heads, head_dim].

    Logit/softmax math in f32; matmuls stay in the input dtype for the MXU.
    The GQA path contracts each kv head against its query group directly —
    the kv tensors are never materialized at full head count.
    """
    head_dim = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(head_dim)
    hq, hk = q.shape[2], k.shape[2]
    if hq == hk:
        logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
                  * scale)
        if mask is not None:
            logits = logits + mask
        weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    if hq % hk:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hk}")
    group = hq // hk
    b, s = q.shape[0], q.shape[1]
    qg = q.reshape(b, s, hk, group, head_dim)
    logits = (jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
              * scale)
    if mask is not None:
        # masks are [b|1, 1, q, s]; insert the group axis
        logits = logits + mask[:, :, None, :, :]
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    ctx = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return ctx.reshape(b, s, hq, head_dim)


def rope_tables(positions: jnp.ndarray, head_dim: int,
                base: float = 10000.0):
    """(cos, sin) angle tables for RoPE, shaped to broadcast against
    [b, s, h, hd/2].  Compute ONCE per forward and reuse across layers —
    the tables are position-only, identical for every layer in a scan."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim} — "
                         "pick hidden_size/num_heads with an even quotient")
    half = head_dim // 2
    freqs = jnp.power(base, -jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., half]
    if angles.ndim == 2:                 # [s, half] -> [1, s, 1, half]
        angles = angles[None, :, None, :]
    else:                                # [b, s, half] -> [b, s, 1, half]
        angles = angles[:, :, None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray,
               sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate [b, s, h, hd] feature pairs by precomputed tables (f32 math,
    result cast back to x.dtype)."""
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def rotary_embedding(x: jnp.ndarray, positions: jnp.ndarray,
                     base: float = 10000.0) -> jnp.ndarray:
    """RoPE (Su et al., 2021): rotate feature pairs by position-dependent
    angles so q·k depends only on RELATIVE distance.

    ``x``: [b, s, h, hd] (hd even); ``positions``: [s] (shared across the
    batch) or [b, s].  One-shot convenience over
    ``rope_tables``/``apply_rope`` (use those to share tables across a
    layer scan).
    """
    cos, sin = rope_tables(positions, x.shape[-1], base)
    return apply_rope(x, cos, sin)


def attention_core(params, x, *, mask=None, dropout_rate: float = 0.0,
                   rng=None, train: bool = False,
                   attention_fn=dot_product_attention,
                   kv=None, qk_transform=None) -> jnp.ndarray:
    """The shared multi-head attention body.

    ``params``: {query,key,value: {kernel [d,h,hd], bias [h,hd]},
    out: {kernel [h,hd,d], bias [d]}} — used by both the
    ``MultiHeadAttention`` layer and the scanned BERT stack, so projection/
    dtype/dropout fixes land in exactly one place.  ``attention_fn``
    swaps the inner kernel (full softmax, ring attention, a Pallas flash
    kernel) behind the same signature.  ``kv``: optional memory sequence
    for cross-attention (keys/values project from it; queries from ``x``).
    """
    dtype = x.dtype

    def project(p, src):
        y = jnp.einsum("bsd,dhk->bshk", src, p["kernel"].astype(dtype))
        if "bias" in p:           # no-bias configs (Llama) omit the key
            y = y + p["bias"].astype(dtype)
        return y

    memory = x if kv is None else kv.astype(dtype)
    q = project(params["query"], x)
    k = project(params["key"], memory)
    v = project(params["value"], memory)
    if qk_transform is not None:
        # positional rotation (RoPE) — applied post-projection, pre-kernel
        q, k = qk_transform(q, k)
    if (k.shape[2] != q.shape[2]
            and attention_fn is not dot_product_attention
            and not getattr(attention_fn, "supports_gqa", False)):
        # grouped-query attention with a swapped kernel that expects equal
        # head counts: broadcast kv head groups here.  The default dense
        # kernel handles grouping natively (grouped einsum), and kernels
        # marked ``supports_gqa`` (the flash kernels, which map kv blocks
        # by q_head // group) take the raw shapes — no repeat either way.
        if q.shape[2] % k.shape[2]:
            raise ValueError(f"query heads {q.shape[2]} not a multiple of "
                             f"kv heads {k.shape[2]}")
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    ctx = attention_fn(q, k, v, mask=mask)
    if train and dropout_rate > 0.0:
        if rng is None:
            raise ValueError("attention dropout requires rng in train mode")
        keep = 1.0 - dropout_rate
        drop = jax.random.bernoulli(rng, keep, ctx.shape)
        ctx = jnp.where(drop, ctx / keep, jnp.zeros_like(ctx))
    out = jnp.einsum("bshk,hkd->bsd", ctx,
                     params["out"]["kernel"].astype(dtype))
    if "bias" in params["out"]:
        out = out + params["out"]["bias"].astype(dtype)
    return out


def ffn_core(params, x, activation=jax.nn.gelu) -> jnp.ndarray:
    """The shared transformer FFN body: w_in -> activation -> w_out, matmuls
    in the input dtype (MXU path) with params cast to match.

    ``params``: {w_in: {kernel [d, i], bias [i]}, w_out: {kernel [i, d],
    bias [d]}} — like ``attention_core``, one implementation serves
    BERT/GPT/seq2seq so dtype/numerics fixes land in exactly one place.
    """
    dtype = x.dtype
    h = activation(_affine(params["w_in"], x, dtype))
    return _affine(params["w_out"], h, dtype)


def _affine(p, x, dtype):
    """x @ kernel (+ bias when present — no-bias configs like Llama simply
    omit the key)."""
    y = jnp.einsum("...d,di->...i", x, p["kernel"].astype(dtype))
    if "bias" in p:
        y = y + p["bias"].astype(dtype)
    return y


def ffn_swiglu_core(params, x, activation=jax.nn.silu) -> jnp.ndarray:
    """Gated-linear FFN body (Llama / PaLM):
    ``w_out(silu(w_gate(x)) * w_in(x))`` — ``w_in`` is HF's up_proj,
    ``w_gate`` gate_proj, ``w_out`` down_proj.  Same param-dict shape
    conventions and dtype rules as ``ffn_core``."""
    dtype = x.dtype
    h = activation(_affine(params["w_gate"], x, dtype)) \
        * _affine(params["w_in"], x, dtype)
    return _affine(params["w_out"], h, dtype)


class MultiHeadAttention(Layer):
    """Self-attention with TP-ready [d, heads, head_dim] projections."""

    def __init__(self, num_heads: int, d_model: int,
                 head_dim: Optional[int] = None,
                 dropout_rate: float = 0.0,
                 kernel_init="glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name or "attention")
        self.num_heads = num_heads
        self.d_model = d_model
        self.head_dim = head_dim or d_model // num_heads
        self.dropout_rate = dropout_rate
        self.kernel_init = init_lib.get(kernel_init)

    def init(self, key, in_shape):
        d = in_shape[-1]
        keys = jax.random.split(key, 4)
        h, hd = self.num_heads, self.head_dim
        shape_in = (d, h, hd)

        def proj(k, shape):
            # variance-scaled on the flattened fan
            flat = self.kernel_init(k, (shape[0],
                                        int(jnp.prod(jnp.asarray(shape[1:])))))
            return flat.reshape(shape)

        params = {
            "query": {"kernel": proj(keys[0], shape_in),
                      "bias": jnp.zeros((h, hd), jnp.float32)},
            "key": {"kernel": proj(keys[1], shape_in),
                    "bias": jnp.zeros((h, hd), jnp.float32)},
            "value": {"kernel": proj(keys[2], shape_in),
                      "bias": jnp.zeros((h, hd), jnp.float32)},
            "out": {"kernel": proj(keys[3], (h * hd, self.d_model)
                                   ).reshape(h, hd, self.d_model),
                    "bias": jnp.zeros((self.d_model,), jnp.float32)},
        }
        return params, {}

    def out_shape(self, in_shape):
        return tuple(in_shape[:-1]) + (self.d_model,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return attention_core(params, x, mask=mask,
                              dropout_rate=self.dropout_rate, rng=rng,
                              train=train), state
