"""Pallas TPU kernels for the hot ops.

The reference has no native kernels of its own — its compute lowers to the
C++/Eigen/cuDNN kernels inside the pinned ``tensorflow==1.4.0`` wheel
(reference requirements.txt:6).  This package is the TPU-native analogue:
hand-written Mosaic/Pallas kernels for the ops where XLA's automatic
fusion leaves performance on the table, dispatched behind the same
signatures as the pure-XLA implementations in ``ops``.

Every kernel runs in Pallas interpret mode off-TPU so the whole test suite
exercises the real kernel code paths on the virtual CPU mesh.
"""
from .flash_attention import flash_attention, make_flash_attention_fn
from .fused import (fused_adam_update, fused_layernorm, fused_rmsnorm,
                    resolve_fused_ln)
from .paged_attention import (MIN_PAGE_SIZE, page_size_kernel_ok,
                              paged_decode_attention,
                              paged_window_attention)

__all__ = [
    "flash_attention",
    "make_flash_attention_fn",
    "fused_adam_update",
    "fused_layernorm",
    "fused_rmsnorm",
    "resolve_fused_ln",
    "MIN_PAGE_SIZE",
    "page_size_kernel_ok",
    "paged_decode_attention",
    "paged_window_attention",
]
