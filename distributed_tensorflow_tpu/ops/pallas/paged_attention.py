"""Fused Pallas paged attention: walk the page table inside the kernel.

The paged serve tier (serve/pages.py) stores K/V as fixed-size pages in
one pool per leaf — ``[L, num_pages, page_size, kv_heads, head_dim]`` —
with a per-slot page-table row mapping logical columns to pool pages.
The XLA read path (``models/gpt.py _paged_layer_kv``) gathers each row's
pages into a contiguous operand before attention runs; the measured
``vs_lockstep_paged`` ≈ 0.75 smoke cost is exactly that gather (the
ROADMAP item PR 13 closes).  This kernel consumes the page table directly: the table
rides the grid as a SCALAR-PREFETCH operand
(``pltpu.PrefetchScalarGridSpec``), and the k/v BlockSpec index maps
read it to pick the pool page for every grid step — no contiguous view
is ever materialized, on-device or in the jaxpr (statically checkable:
this module's DT4xx graph entry carries an HBM budget sized to the pool
+ operands, with no room for a gathered copy).

Two variants share ONE kernel body (``_make_paged_kernel``):

* **decode** (``paged_decode_attention``): s=1 per slot row, grid
  ``(slots, pages_per_slot)`` with the page walk minormost, flash-style
  online softmax across the row's pages; validity (the
  start_col/write_col window plus the row's own just-written column)
  arrives as a per-page mask plane, so only valid pages contribute and
  retired rows' trash-page mapping is harmless — every trash column is
  masked and its exp underflows to exactly 0.0.
* **prefill window** (``paged_window_attention``): query block ×
  page-walk for one row's chunked-prefill window, causal against the
  TRACED window origin (``pos`` rides the scalar-prefetch tuple so the
  mask is computed in-kernel, never materialized at ``view_len``).

Both mirror ``_paged_layer_kv`` + ``ops.attention.dot_product_attention``
semantics: f32 logits, additive finite ``NEG_INF`` masks (matching
``ops.attention.NEG_INF``), GQA by head-group reshape (the kv heads are
never broadcast in memory), int8 KV dequantized at the operand from the
pool's scale planes.  Masked columns underflow to exactly 0.0 in the
exp, so the online softmax agrees with the reference full softmax to
float round-off and greedy token streams are bit-identical
(tests/test_pages.py pins kernel == gather == contiguous == generate).

Off-TPU the kernel runs in Pallas interpret mode (ops/pallas/common.py),
so the tier-1 suite executes THIS kernel code on CPU; Mosaic compilation
(interpret=False) is certified on hardware by
scripts/validate_paged_tpu.py.  Mosaic's sublane tiling constrains
``page_size`` to multiples of :data:`MIN_PAGE_SIZE` — enforced at
``SlotScheduler`` construction (serve/scheduler.py) so an incompatible
layout is a clear ValueError or a logged gather fallback, never a Mosaic
error from inside the kernel.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import use_interpret

__all__ = ["MIN_PAGE_SIZE", "page_size_kernel_ok", "paged_decode_attention",
           "paged_window_attention"]

# Mirrors ops.attention.NEG_INF (kept literal: ops.attention imports this
# package for the dispatch gate, so the constant cannot flow the other
# way without a cycle).  Finite on purpose — the reference softmax adds
# -1e9, never -inf, and exp(-1e9 - m) underflows to exactly 0.0 in f32,
# which is what makes kernel-vs-gather agreement testable.
NEG_INF = -1e9

# Mosaic sublane tile: a k/v page block's second-minor dims tile in
# units of 8, so the kernel requires page_size % 8 == 0 (and >= 8).
# serve/scheduler.py validates this at construction; serve/pages.py
# ``auto_page_size(multiple_of=...)`` prefers compatible sizes.
MIN_PAGE_SIZE = 8


def page_size_kernel_ok(page_size: int) -> bool:
    """True iff the paged-attention kernel can consume pages of this
    size (lane-tileable: a multiple of :data:`MIN_PAGE_SIZE`)."""
    return page_size >= MIN_PAGE_SIZE and page_size % MIN_PAGE_SIZE == 0


def _make_paged_kernel(*, scale, group, page_size, window_causal,
                       quantized):
    """One body for both variants.  Ref order (after the 3 scalar-
    prefetch refs) matches the in_specs built in ``_paged_attention``:
    q, k, v, [k_scale, v_scale,] valid, out, then acc/m/l scratch."""

    def kernel(layer_ref, tab_ref, pos_ref, q_ref, k_ref, v_ref, *rest):
        del layer_ref, tab_ref  # consumed by the BlockSpec index maps
        if quantized:
            ks_ref, vs_ref, valid_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            valid_ref, o_ref, acc_ref, m_ref, l_ref = rest
        # program_id must be read at kernel top level (the HLO
        # interpreter cannot lower it inside pl.when).
        pi = pl.program_id(1)
        npages = pl.num_programs(1)

        @pl.when(pi == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        sq, h, hd = q_ref.shape[1:]
        kvh = k_ref.shape[3]
        # GQA: q head ih reads kv head ih // group — a reshape, never a
        # materialized broadcast of the kv heads.
        q = q_ref[0].astype(jnp.float32).reshape(sq, kvh, group, hd)
        k = k_ref[0, 0].astype(jnp.float32)   # [page_size, kvh, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            # dequant-at-the-operand from the pool's scale planes,
            # mirroring quant.dequantize_tensor in _paged_layer_kv.
            k = k * ks_ref[0, 0]              # [page_size, kvh, 1] f32
            v = v * vs_ref[0, 0]

        # [kvh, sq, group, page_size] — batch over kv heads.
        logits = jax.lax.dot_general(
            q, k, (((3,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32) * scale
        pvalid = valid_ref[0, 0, 0]           # [page_size] f32 plane
        logits = logits + jnp.where(pvalid > 0.5, 0.0, NEG_INF)
        if window_causal:
            # logical column of lane t in this page vs window row j:
            # attend iff col <= pos + j (prefix + causal-in-window),
            # matching decode_window's positional mask.
            col = pi * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (1, sq, 1, page_size), 3)
            row = jax.lax.broadcasted_iota(
                jnp.int32, (1, sq, 1, page_size), 1)
            logits = logits + jnp.where(col <= pos_ref[0] + row,
                                        0.0, NEG_INF)

        # Online softmax (flash scaffold): masks are FINITE, so only the
        # -inf init needs the isfinite guard.
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - shift)
        alpha = jnp.where(jnp.isfinite(m_prev),
                          jnp.exp(m_prev - shift), 0.0)
        l_new = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((3,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new
        l_ref[...] = l_new

        @pl.when(pi == npages - 1)
        def _finalize():
            l = l_ref[...]
            out = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
            out = out.transpose(1, 0, 2, 3).reshape(sq, kvh * group, hd)
            o_ref[0] = out.astype(o_ref.dtype)

    return kernel


def _paged_attention(q, kv, layer, page_tab, valid_plane, pos, *,
                     window_causal, scale=None, interpret=None):
    """Shared pallas_call builder.

    q [B, sq, h, hd]; kv pool dict (k/v [L, num_pages, page_size, kvh,
    hd], optional k_scale/v_scale [..., 1]); layer traced int32 scalar;
    page_tab [B, P] int32; valid_plane [B, P, 1, page_size] f32; pos
    traced window origin (ignored unless window_causal).
    Returns [B, sq, h, hd] in q.dtype.
    """
    B, sq, h, hd = q.shape
    _, _, page_size, kvh, _ = kv["k"].shape
    P = page_tab.shape[1]
    group = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = use_interpret()
    quantized = "k_scale" in kv

    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    pos_arr = jnp.asarray(0 if pos is None else pos, jnp.int32).reshape(1)
    tab = page_tab.astype(jnp.int32)

    # Index maps receive the grid indices then the scalar-prefetch refs
    # (layer, table, pos); the k/v maps are the page walk itself.
    def q_map(b, p, lr, tb, ps):
        return (b, 0, 0, 0)

    def kv_map(b, p, lr, tb, ps):
        return (lr[0], tb[b, p], 0, 0, 0)

    def valid_map(b, p, lr, tb, ps):
        return (b, p, 0, 0)

    in_specs = [
        pl.BlockSpec((1, sq, h, hd), q_map),
        pl.BlockSpec((1, 1, page_size, kvh, hd), kv_map),
        pl.BlockSpec((1, 1, page_size, kvh, hd), kv_map),
    ]
    inputs = [q, kv["k"], kv["v"]]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, page_size, kvh, 1), kv_map)] * 2
        inputs += [kv["k_scale"], kv["v_scale"]]
    in_specs.append(pl.BlockSpec((1, 1, 1, page_size), valid_map))
    inputs.append(valid_plane)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, sq, h, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((kvh, sq, group, hd), jnp.float32),
            pltpu.VMEM((kvh, sq, group, 1), jnp.float32),
            pltpu.VMEM((kvh, sq, group, 1), jnp.float32),
        ],
    )
    kernel = _make_paged_kernel(scale=scale, group=group,
                                page_size=page_size,
                                window_causal=window_causal,
                                quantized=quantized)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, sq, h, hd), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return call(layer_arr, tab, pos_arr, *inputs)


def paged_decode_attention(q, kv, layer, page_tab, valid, *, scale=None,
                           interpret=None):
    """s=1 decode attention straight off the page pool.

    q [S, 1, h, hd]; kv pool subtree (serve/pages.py leaves); layer
    traced layer index; page_tab [S, pages_per_slot]; valid
    [S, view_len] bool (the kv-valid window OR the row's own column —
    exactly the mask ``decode_step_slots_paged`` hands the gather path).
    Returns the attention context [S, 1, h, hd].
    """
    S, sq, _, _ = q.shape
    page_size = kv["k"].shape[2]
    P = page_tab.shape[1]
    valid_plane = valid.reshape(S, P, 1, page_size).astype(jnp.float32)
    return _paged_attention(q, kv, layer, page_tab, valid_plane, None,
                            window_causal=False, scale=scale,
                            interpret=interpret)


def paged_window_attention(q, kv, layer, page_row, pos, *, scale=None,
                           interpret=None):
    """Prefill-window attention for ONE row through its page walk.

    q [1, s, h, hd] (the window's queries); page_row [pages_per_row];
    pos: traced logical column of the window's first token.  Row j
    attends columns <= pos + j (prefix + causal within the window) —
    the positional mask ``decode_window`` applies, computed in-kernel
    from ``pos`` so no [s, view_len] mask is ever built.
    Returns [1, s, h, hd].
    """
    page_size = kv["k"].shape[2]
    P = page_row.shape[0]
    ones = jnp.ones((1, P, 1, page_size), jnp.float32)
    return _paged_attention(q, kv, layer, page_row[None, :], ones, pos,
                            window_causal=True, scale=scale,
                            interpret=interpret)


# --- dtlint graph tier registration (docs/ANALYSIS.md) ----------------
# Budget: the tiny-entry pool (2 layers x 9 pages x 8 x 2 x 16 f32 x 2
# leaves ~= 36 KiB) + operands, with NO headroom for a gathered
# [S, view_len, kvh, hd] copy at real scale — DT404 is the static proof
# that the gather never came back.
from ...analysis import graph as _graph_lib  # noqa: E402


@_graph_lib.trace_entry("paged_attention", hbm_budget=1 << 20)
def _graph_entries():
    """Both kernel variants at tiny pool shapes, traced abstractly on
    CPU (interpret-mode pallas_call has an abstract eval, so the graph
    tier sees the real call signature without touching a device)."""
    S, P, PG, KVH, GROUP, HD, L, NP = 2, 4, 8, 2, 2, 16, 2, 9
    h = KVH * GROUP
    sds = jax.ShapeDtypeStruct
    kv = {"k": sds((L, NP, PG, KVH, HD), jnp.float32),
          "v": sds((L, NP, PG, KVH, HD), jnp.float32)}
    return [
        _graph_lib.Target(
            "decode",
            lambda q, kv, layer, tab, valid: paged_decode_attention(
                q, kv, layer, tab, valid),
            args=(sds((S, 1, h, HD), jnp.float32), kv,
                  sds((), jnp.int32), sds((S, P), jnp.int32),
                  sds((S, P * PG), jnp.bool_))),
        _graph_lib.Target(
            "prefill_window",
            lambda q, kv, layer, row, pos: paged_window_attention(
                q, kv, layer, row, pos),
            args=(sds((1, PG, h, HD), jnp.float32), kv,
                  sds((), jnp.int32), sds((P,), jnp.int32),
                  sds((), jnp.int32))),
    ]
