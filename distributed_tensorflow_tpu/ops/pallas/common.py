"""Shared helpers for the Pallas kernel package."""
from __future__ import annotations

import jax


def use_interpret() -> bool:
    """Off-TPU (CPU test mesh, debugging) kernels run in interpret mode so
    the same kernel code executes everywhere."""
    return jax.default_backend() != "tpu"
