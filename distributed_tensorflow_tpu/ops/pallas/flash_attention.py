"""Fused flash-attention forward kernel (Pallas / Mosaic-TPU).

Replaces the O(seq²)-memory ``ops.attention.dot_product_attention`` hot path
with a blockwise online-softmax kernel: Q stays resident in VMEM per block
row while K/V blocks stream through, so the full logits matrix never
materialises in HBM.  The MXU sees [block_q, head_dim] x [head_dim, block_k]
matmuls with float32 accumulation; inputs may be bfloat16.

Grid layout: ``(batch, heads, q_blocks, k_blocks)`` with the K dimension
minormost — Pallas executes the grid sequentially on a TPU core, so the
float32 accumulator / running-max / running-sum scratch carried across the
k iterations implements the streaming softmax without HBM round-trips.

The backward pass recomputes attention with the pure-XLA reference
implementation under ``jax.vjp`` (flash forward + rematerialised backward);
a fused Pallas backward is a later optimisation — the forward is where the
memory ceiling was.

Reference parity note: the reference repo has no attention at all (its model
is an MLP, reference example.py:149-155); this kernel serves the BERT/GPT
model families the driver's baseline configs require.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import use_interpret as _use_interpret

__all__ = ["flash_attention", "make_flash_attention_fn"]

NEG_INF = float("-inf")


def _flash_kernel(q_ref, k_ref, v_ref, valid_ref, o_ref,
                  acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool,
                  block_q: int, block_k: int):
    """One (batch, head, q_block, k_block) grid step.

    Refs: q [1,1,bq,d], k/v [1,1,bk,d], valid [1,1,bk] float (1=real key;
    the singleton middle axis keeps the block's trailing-2 shape (1, bk)
    equal-or-tiled against Mosaic's (8, 128) rule), o [1,1,bq,d]; scratch
    acc [bq,d] f32, m/l [bq,1] f32.
    """
    # program_id must be read at kernel top level: the HLO interpreter used
    # off-TPU cannot lower it from inside a pl.when body.
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        valid = valid_ref[0, 0, :] > 0.5                # [bk]
        logits = jnp.where(valid[None, :], logits, NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

        m_prev = m_ref[:, 0]                            # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        # Rows with every key masked so far keep m == -inf; shift by 0 there
        # so exp() stays finite and contributes nothing.
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        probs = jnp.exp(logits - shift[:, None])        # masked -> exp(-inf)=0
        correction = jnp.where(jnp.isfinite(m_prev),
                               jnp.exp(m_prev - shift), 0.0)

        l_ref[:, 0] = l_ref[:, 0] * correction + jnp.sum(probs, axis=-1)
        acc_ref[:] = (acc_ref[:] * correction[:, None] +
                      jax.lax.dot_general(
                          probs, v_ref[0, 0].astype(jnp.float32),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_ref[:, 0] = m_new

    if causal:
        # Blocks strictly above the diagonal contribute nothing: no query
        # row in this block can attend to any key column in it.
        @pl.when((qi + 1) * block_q > ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        out = acc_ref[:] / jnp.where(l > 0.0, l, 1.0)[:, None]
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, valid, scale, causal, block_q, block_k,
                   interpret):
    """q,k,v: [b, h, s, d]; valid: [b, s_k] float32.  Returns [b, h, s, d]."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)

    q = _pad_to(q, 2, bq)
    k = _pad_to(k, 2, bk)
    v = _pad_to(v, 2, bk)
    valid = _pad_to(valid, 1, bk)          # padded keys arrive masked
    valid = valid[:, None, :]              # [b, 1, sk]: Mosaic-tileable
    sq_p, sk_p = q.shape[2], k.shape[2]
    grid = (b, h, sq_p // bq, sk_p // bk)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, iq, ik: (ib, 0, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, valid)
    return out[:, :, :sq, :]


def _reference(q, k, v, valid, scale, causal):
    """Pure-XLA parity implementation; also the rematerialised backward."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(valid[:, None, None, :] > 0.5, logits, NEG_INF)
    if causal:
        sq, sk = logits.shape[-2:]
        mask = (jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :])
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    # Fully-masked rows: softmax of all -inf — zero the output instead.
    row_any = jnp.any(logits > NEG_INF, axis=-1, keepdims=True)
    weights = jax.nn.softmax(jnp.where(row_any, logits, 0.0), axis=-1)
    weights = jnp.where(row_any, weights, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, valid, scale, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, valid, scale, causal, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, valid, scale, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, valid, scale, causal, block_q, block_k,
                         interpret)
    return out, (q, k, v, valid)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, valid = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference(q_, k_, v_, valid, scale, causal),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(valid)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    kv_valid: Optional[jnp.ndarray] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused attention.  q,k,v: [batch, seq, heads, head_dim] (the
    framework-wide head layout, see ops.attention); kv_valid: optional
    [batch, seq_k] mask, 1 = real key.  Returns [batch, seq, heads, head_dim].

    Off-TPU the kernel runs in Pallas interpret mode, so CPU tests cover the
    identical kernel code.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _use_interpret()
    if kv_valid is None:
        valid = jnp.ones((k.shape[0], k.shape[1]), jnp.float32)
    else:
        valid = kv_valid.astype(jnp.float32)

    # [b, s, h, d] -> [b, h, s, d] for per-(batch, head) grid blocking.
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, valid, float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return jnp.swapaxes(out, 1, 2)


def make_flash_attention_fn(causal: bool = False, block_q: int = 128,
                            block_k: int = 128):
    """Adapter matching the ``attention_fn(q, k, v, mask=...)`` slot of
    ``ops.attention.attention_core``.

    Accepts ``mask=None`` or a *padding* mask shaped [b, 1, 1, s_k] (the
    output of ``ops.attention.padding_mask``); arbitrary additive masks
    don't map onto the fused kernel and raise.
    """
    def fn(q, k, v, mask=None, scale=None):
        kv_valid = None
        if mask is not None:
            if mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1:
                raise ValueError(
                    "flash attention accepts only padding masks "
                    f"[b,1,1,s]; got {mask.shape}")
            kv_valid = (mask[:, 0, 0, :] >= 0.0)
        return flash_attention(q, k, v, kv_valid=kv_valid, causal=causal,
                               scale=scale, block_q=block_q, block_k=block_k)
    return fn
