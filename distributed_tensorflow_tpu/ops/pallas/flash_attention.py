"""Fused flash attention (Pallas / Mosaic-TPU): forward AND backward.

Replaces the O(seq²)-memory ``ops.attention.dot_product_attention`` hot path
with a blockwise online-softmax kernel: Q stays resident in VMEM per block
row while K/V blocks stream through, so the full logits matrix never
materialises in HBM.  The MXU sees [block_q, head_dim] x [head_dim, block_k]
matmuls with float32 accumulation; inputs may be bfloat16.

Forward grid: ``(batch, heads, q_blocks, k_blocks)`` with the K dimension
minormost — Pallas executes the grid sequentially on a TPU core, so the
float32 accumulator / running-max / running-sum scratch carried across the
k iterations implements the streaming softmax without HBM round-trips.  The
kernel also emits the row logsumexp (``lse``), which the backward consumes.

Backward (the standard two-kernel flash split, residuals = (q,k,v,out,lse)
— O(seq) extra memory, logits recomputed blockwise):
  * ``dkv`` kernel, grid ``(b, h, k_blocks, q_blocks)`` (q minormost):
    each k block accumulates dK/dV while the q blocks stream through;
  * ``dq`` kernel, grid ``(b, h, q_blocks, k_blocks)`` (k minormost):
    each q block accumulates dQ while the k blocks stream;
  * the row term ``D = rowsum(dO * O)`` is a cheap elementwise reduce done
    in plain XLA before both kernels.

Off-TPU the kernels run in Pallas interpret mode so CPU tests execute the
identical code; NOTE interpret mode has hidden Mosaic tiling violations
before (docs/PERF.md) — hardware validation is required before claiming a
measured win.

Default blocks (512, 1024), clamped to seq, come from the 2026-07-31
hardware sweep (scripts/sweep_flash_blocks.py): the 128x128 blocks the
kernel started with spend ~33us of per-grid-step overhead on thousands of
tiny sequential steps, losing to XLA everywhere; 4x-fatter blocks win
1.6x at seq 2048 and ~3x at 4096 (docs/PERF.md has the full table).

Reference parity note: the reference repo has no attention at all (its model
is an MLP, reference example.py:149-155); this kernel serves the BERT/GPT
model families the driver's baseline configs require.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import use_interpret as _use_interpret

__all__ = ["flash_attention", "make_flash_attention_fn"]

NEG_INF = float("-inf")


def _flash_kernel(q_ref, k_ref, v_ref, valid_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool,
                  block_q: int, block_k: int):
    """One (batch, head, q_block, k_block) grid step.

    Refs: q [1,1,bq,d], k/v [1,1,bk,d], valid [1,1,bk] float (1=real key;
    the singleton middle axis keeps the block's trailing-2 shape (1, bk)
    equal-or-tiled against Mosaic's (8, 128) rule), o [1,1,bq,d],
    lse [1,1,bq,1] f32 row logsumexp (backward residual; the trailing
    singleton makes the block's trailing-2 shape (bq, 1) — bq tiles by 8,
    1 equals the array dim — the same Mosaic rule the valid mask needed);
    scratch acc [bq,d] f32, m/l [bq,1] f32.
    """
    # program_id must be read at kernel top level: the HLO interpreter used
    # off-TPU cannot lower it from inside a pl.when body.
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        valid = valid_ref[0, 0, :] > 0.5                # [bk]
        logits = jnp.where(valid[None, :], logits, NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

        m_prev = m_ref[:, 0]                            # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        # Rows with every key masked so far keep m == -inf; shift by 0 there
        # so exp() stays finite and contributes nothing.
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        probs = jnp.exp(logits - shift[:, None])        # masked -> exp(-inf)=0
        correction = jnp.where(jnp.isfinite(m_prev),
                               jnp.exp(m_prev - shift), 0.0)

        l_ref[:, 0] = l_ref[:, 0] * correction + jnp.sum(probs, axis=-1)
        acc_ref[:] = (acc_ref[:] * correction[:, None] +
                      jax.lax.dot_general(
                          probs, v_ref[0, 0].astype(jnp.float32),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_ref[:, 0] = m_new

    if causal:
        # Blocks strictly above the diagonal contribute nothing: no query
        # row in this block can attend to any key column in it.
        @pl.when((qi + 1) * block_q > ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        out = acc_ref[:] / jnp.where(l > 0.0, l, 1.0)[:, None]
        o_ref[0, 0] = out.astype(o_ref.dtype)
        # row logsumexp: the running max (shift) + log of the running sum;
        # fully-masked rows (l == 0) get -inf so the backward's
        # exp(s - lse) reproduces their zero probabilities
        m = m_ref[:, 0]
        shift = jnp.where(jnp.isfinite(m), m, 0.0)
        lse = jnp.where(l > 0.0, shift + jnp.log(
            jnp.where(l > 0.0, l, 1.0)), NEG_INF)
        lse_ref[0, 0] = lse[:, None]          # 2-D store: [bq, 1]


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, valid, scale, causal, block_q, block_k,
                   interpret):
    """q: [b, h, s, d]; k, v: [b, hk, s, d] with h % hk == 0 (GQA/MQA:
    each kv head serves h//hk query heads, selected by block-index
    mapping — the broadcast never materialises); valid: [b, s_k] float32.
    Returns (out [b, h, s, d], lse [b, h, s] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    group = h // k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)

    q = _pad_to(q, 2, bq)
    k = _pad_to(k, 2, bk)
    v = _pad_to(v, 2, bk)
    valid = _pad_to(valid, 1, bk)          # padded keys arrive masked
    valid = valid[:, None, :]              # [b, 1, sk]: Mosaic-tileable
    sq_p, sk_p = q.shape[2], k.shape[2]
    grid = (b, h, sq_p // bq, sk_p // bk)

    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, sq_p, 1), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda ib, ih, iq, ik: (ib, 0, ik)),
        ],
        out_specs=[pl.BlockSpec((1, 1, bq, d),
                                lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
                   pl.BlockSpec((1, 1, bq, 1),
                                lambda ib, ih, iq, ik: (ib, ih, iq, 0))],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, valid)
    return out[:, :, :sq, :], lse[:, :, :sq, 0]


def _bwd_block_terms(q, k, v, do, lse, dvec, valid, qi, ki, scale, causal,
                     block_q, block_k):
    """Shared per-block backward math: returns (p, ds), both [bq, bk] f32.

    ``p`` re-derives the forward probabilities from the saved row logsumexp
    (exp(s - lse)); ``ds = p * (dp - D) * scale`` is the logits cotangent.
    """
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(valid[None, :] > 0.5, s, NEG_INF)
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    # masked s = -inf -> p = 0; fully-masked rows have lse = -inf, guard the
    # subtraction so exp sees -inf, not (-inf) - (-inf) = nan
    p = jnp.exp(s - jnp.where(jnp.isfinite(lse), lse, 0.0)[:, None])
    p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - dvec[:, None]) * scale
    return p, ds


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref, valid_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *,
                      scale: float, causal: bool,
                      block_q: int, block_k: int):
    """dK/dV: grid (b, h, k_blocks, q_blocks), q minormost.  Each k block
    holds f32 accumulators while every q block streams through."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        p, ds = _bwd_block_terms(
            q, k, v, do, lse_ref[0, 0, :, 0], d_ref[0, 0, :, 0],
            valid_ref[0, 0, :], qi, ki, scale, causal, block_q, block_k)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # p^T @ dO [bk, d]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # ds^T @ Q [bk, d]

    if causal:
        # q blocks entirely above the diagonal contribute nothing to this
        # k block
        @pl.when((qi + 1) * block_q > ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref, valid_ref,
                     dq_ref, dq_acc, *,
                     scale: float, causal: bool,
                     block_q: int, block_k: int):
    """dQ: grid (b, h, q_blocks, k_blocks), k minormost — the forward's
    layout, accumulating dq while k blocks stream."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        _, ds = _bwd_block_terms(
            q, k, v, do, lse_ref[0, 0, :, 0], d_ref[0, 0, :, 0],
            valid_ref[0, 0, :], qi, ki, scale, causal, block_q, block_k)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # ds @ K [bq, d]

    if causal:
        @pl.when((qi + 1) * block_q > ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, valid, out, lse, do, scale, causal,
                    block_q, block_k, interpret, dvec=None):
    """Fused backward: (dq, dk, dv) with logits recomputed blockwise.

    GQA: k/v may have hk < h heads.  The kernels consume them through the
    same ``ih // group`` index mapping as the forward and emit PER-Q-HEAD
    dk/dv ([b, h, sk, d]); the group reduction to [b, hk, sk, d] is one
    cheap XLA sum afterwards (costs group x transient dk/dv memory — still
    O(seq), the kernels' point).

    ``dvec``: optionally the precomputed D = rowsum(dO·O) [b, h, sq] —
    ring-flash calls this once per K/V block with identical q/do/out, so
    it hoists the reduce out of its loop.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    hk = k.shape[1]
    group = h // hk
    bq = min(block_q, sq)
    bk = min(block_k, sk)

    if dvec is None:
        # D = rowsum(dO * O): cheap elementwise reduce, plain XLA
        dvec = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                       axis=-1)

    q_p = _pad_to(q, 2, bq)
    do_p = _pad_to(do, 2, bq)                 # zero dO rows: no contribution
    # pad lse with 0 (any finite value): padded q rows have dO = 0 and
    # D = 0, so their p never reaches an accumulator.  Both ride with a
    # trailing singleton axis so their blocks' trailing-2 shape (bq, 1)
    # satisfies Mosaic's (8, 128) tiling rule (see _flash_kernel docstring).
    lse_p = _pad_to(lse, 2, bq)[..., None]    # [b, h, sq_p, 1]
    d_p = _pad_to(dvec, 2, bq)[..., None]     # [b, h, sq_p, 1]
    k_p = _pad_to(k, 2, bk)
    v_p = _pad_to(v, 2, bk)
    valid_p = _pad_to(valid, 1, bk)[:, None, :]   # [b, 1, sk_p]
    sq_p, sk_p = q_p.shape[2], k_p.shape[2]

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d), v.dtype)],
        grid=(b, h, sk_p // bk, sq_p // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, ik, iq: (ib, ih, iq, 0)),   # q
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ik, iq: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ik, iq: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, ik, iq: (ib, ih, iq, 0)),   # do
            pl.BlockSpec((1, 1, bq, 1),
                         lambda ib, ih, ik, iq: (ib, ih, iq, 0)),   # lse
            pl.BlockSpec((1, 1, bq, 1),
                         lambda ib, ih, ik, iq: (ib, ih, iq, 0)),   # D
            pl.BlockSpec((1, 1, bk),
                         lambda ib, ih, ik, iq: (ib, 0, ik)),       # valid
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ik, iq: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, ik, iq: (ib, ih, ik, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse_p, d_p, valid_p)

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        grid=(b, h, sq_p // bq, sk_p // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),   # q
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),   # do
            pl.BlockSpec((1, 1, bq, 1),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),   # lse
            pl.BlockSpec((1, 1, bq, 1),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),   # D
            pl.BlockSpec((1, 1, bk),
                         lambda ib, ih, iq, ik: (ib, 0, ik)),       # valid
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse_p, d_p, valid_p)

    if group > 1:
        sk_pad = dk.shape[2]
        dk = dk.astype(jnp.float32).reshape(
            b, hk, group, sk_pad, d).sum(2).astype(k.dtype)
        dv = dv.astype(jnp.float32).reshape(
            b, hk, group, sk_pad, d).sum(2).astype(v.dtype)
    return dq[:, :, :sq, :], dk[:, :, :sk, :], dv[:, :, :sk, :]


def _reference(q, k, v, valid, scale, causal):
    """Pure-XLA parity implementation; also the rematerialised backward."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(valid[:, None, None, :] > 0.5, logits, NEG_INF)
    if causal:
        sq, sk = logits.shape[-2:]
        mask = (jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :])
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    # Fully-masked rows: softmax of all -inf — zero the output instead.
    row_any = jnp.any(logits > NEG_INF, axis=-1, keepdims=True)
    weights = jax.nn.softmax(jnp.where(row_any, logits, 0.0), axis=-1)
    weights = jnp.where(row_any, weights, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, valid, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, valid, scale, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd(q, k, v, valid, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, valid, scale, causal, block_q,
                              block_k, interpret)
    return out, (q, k, v, valid, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, valid, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, valid, out, lse, g, scale, causal,
                                 block_q, block_k, interpret)
    return dq, dk, dv, jnp.zeros_like(valid)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    kv_valid: Optional[jnp.ndarray] = None,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused attention.  q: [batch, seq, heads, head_dim] (the
    framework-wide head layout, see ops.attention); k, v:
    [batch, seq_k, kv_heads, head_dim] where heads % kv_heads == 0 —
    GQA/MQA kv heads are shared across their query group by block-index
    mapping, never materialised; kv_valid: optional [batch, seq_k] mask,
    1 = real key.  Returns [batch, seq, heads, head_dim].

    Off-TPU the kernel runs in Pallas interpret mode, so CPU tests cover the
    identical kernel code.
    """
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(
            f"flash_attention requires the q head count to be a multiple "
            f"of the kv head count; got {q.shape[2]} vs {k.shape[2]}")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _use_interpret()
    if kv_valid is None:
        valid = jnp.ones((k.shape[0], k.shape[1]), jnp.float32)
    else:
        valid = kv_valid.astype(jnp.float32)

    # [b, s, h, d] -> [b, h, s, d] for per-(batch, head) grid blocking.
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, valid, float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return jnp.swapaxes(out, 1, 2)


def make_flash_attention_fn(causal: bool = False, block_q: int = 512,
                            block_k: int = 1024):
    """Adapter matching the ``attention_fn(q, k, v, mask=...)`` slot of
    ``ops.attention.attention_core``.

    Accepts ``mask=None`` or a *padding* mask shaped [b, 1, 1, s_k] (the
    output of ``ops.attention.padding_mask``); arbitrary additive masks
    don't map onto the fused kernel and raise.
    """
    def fn(q, k, v, mask=None, scale=None):
        kv_valid = None
        if mask is not None:
            if mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1:
                raise ValueError(
                    "flash attention accepts only padding masks "
                    f"[b,1,1,s]; got {mask.shape}")
            kv_valid = (mask[:, 0, 0, :] >= 0.0)
        return flash_attention(q, k, v, kv_valid=kv_valid, causal=causal,
                               scale=scale, block_q=block_q, block_k=block_k)
    fn.supports_gqa = True   # attention_core: skip the kv-head broadcast
    return fn
