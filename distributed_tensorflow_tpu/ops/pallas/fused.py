"""Fused elementwise Pallas kernels: Adam update, LayerNorm, RMSNorm.

The reference's optimizer/normalisation math runs as individual C++/Eigen
ops inside TF 1.4 (reference example.py:168-170); here the whole update is
one VMEM-resident kernel per block — one HBM read and one HBM write per
tensor element instead of one per intermediate.

XLA already fuses most elementwise chains; these kernels exist for the two
places fusion boundaries bite on TPU: the optimizer update (param + grad +
two moment buffers = 4 HBM streams XLA sometimes splits across fusions)
and LayerNorm's mean/var reductions feeding an elementwise epilogue.
Off-TPU they run in Pallas interpret mode so CPU tests execute the same
kernel code.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adam_update", "fused_layernorm", "fused_rmsnorm",
           "resolve_fused_ln"]


def resolve_fused_ln(flag) -> bool:
    """Model-config gate for ``fused_layernorm``: True/False pass through;
    "auto" means the Pallas kernel on TPU only (off-TPU it would run in
    slow interpret mode)."""
    if flag == "auto":
        import jax
        return jax.default_backend() == "tpu"
    return bool(flag)

_LANES = 128
_BLOCK_ROWS = 256        # 256 x 128 f32 = 128 KiB per stream, well under VMEM


from .common import use_interpret as _use_interpret


# ---------------------------------------------------------------------------
# Fused Adam
# ---------------------------------------------------------------------------

def _adam_kernel(scalars_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, wd, delta):
    """scalars: [1, 3] SMEM = (lr_t, eps_t, lr) with bias correction folded
    into lr_t/eps_t; plain lr drives the decoupled weight-decay term.
    ``delta``: emit (new_p - p) instead of new_p — free in-kernel (p is
    already in VMEM) and lets optimizer wrappers report exact updates."""
    lr_t = scalars_ref[0, 0]
    eps_t = scalars_ref[0, 1]
    lr = scalars_ref[0, 2]
    g = g_ref[:]
    p = p_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    step_term = -lr_t * (m / (jnp.sqrt(v) + eps_t))
    if wd:
        step_term = step_term - lr * wd * p
    po_ref[:] = step_term if delta else p + step_term
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_update(params: jnp.ndarray, grads: jnp.ndarray,
                      m: jnp.ndarray, v: jnp.ndarray, step: jnp.ndarray,
                      lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
                      eps: float = 1e-8, weight_decay: float = 0.0,
                      tf14_eps: bool = False,
                      return_delta: bool = False,
                      interpret: Optional[bool] = None
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One exact Adam(W) step for a single tensor, fused into one kernel.

    ``step`` is the 1-based step count (traced scalar is fine).  Bias
    correction is folded into scalar prefactors outside the kernel:
    ``p -= lr*sqrt(1-b2^t)/(1-b1^t) * m / (sqrt(v) + eps*sqrt(1-b2^t))``,
    algebraically identical to the m_hat/v_hat form.  ``tf14_eps=True``
    instead applies eps UN-scaled (``sqrt(v) + eps`` on raw v) — the TF-1.4
    rule ``optim.adam`` documents; the two differ when eps matters.
    ``return_delta=True`` returns ``new_p - p`` (f32) in slot 0 instead of
    new params, for optimizer wrappers that report updates.  Returns
    ``(new_params_or_delta, new_m, new_v)``.
    """
    if interpret is None:
        interpret = _use_interpret()
    orig_shape, orig_dtype = params.shape, params.dtype
    t = step.astype(jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.float32(b1), t)
    bc2 = 1.0 - jnp.power(jnp.float32(b2), t)
    lr_t = lr * jnp.sqrt(bc2) / bc1
    eps_t = jnp.float32(eps) if tf14_eps else eps * jnp.sqrt(bc2)
    scalars = jnp.stack([lr_t, eps_t, jnp.float32(lr)]
                        ).reshape(1, 3).astype(jnp.float32)

    def flat2d(x):
        x = x.reshape(-1).astype(jnp.float32)
        pad = (-x.shape[0]) % _LANES
        x = jnp.pad(x, (0, pad))
        return x.reshape(-1, _LANES)

    p2, g2, m2, v2 = map(flat2d, (params, grads, m, v))
    rows = p2.shape[0]
    br = min(_BLOCK_ROWS, rows)
    pad_rows = (-rows) % br
    if pad_rows:
        p2, g2, m2, v2 = (jnp.pad(x, ((0, pad_rows), (0, 0)))
                          for x in (p2, g2, m2, v2))
    grid = (p2.shape[0] // br,)

    tensor_spec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct(p2.shape, jnp.float32)
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, wd=weight_decay,
                          delta=return_delta),
        out_shape=(shape, shape, shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 3), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            tensor_spec, tensor_spec, tensor_spec, tensor_spec,
        ],
        out_specs=(tensor_spec, tensor_spec, tensor_spec),
        interpret=interpret,
    )(scalars, p2, g2, m2, v2)

    n = math.prod(orig_shape) if orig_shape else 1
    def unflat(x, dtype):
        return x.reshape(-1)[:n].reshape(orig_shape).astype(dtype)
    out_dtype = jnp.float32 if return_delta else orig_dtype
    return (unflat(new_p, out_dtype), unflat(new_m, jnp.float32),
            unflat(new_v, jnp.float32))


# ---------------------------------------------------------------------------
# Fused LayerNorm
# ---------------------------------------------------------------------------

def _layernorm_kernel(x_ref, gamma_ref, beta_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                       # [br, d]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centred = x - mean
    var = jnp.mean(centred * centred, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = centred * inv * gamma_ref[:].astype(jnp.float32) + \
        beta_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _layernorm_forward(x2, gamma, beta, eps, interpret):
    rows, d = x2.shape
    br = min(_BLOCK_ROWS, rows)
    pad = (-rows) % br
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    out = pl.pallas_call(
        functools.partial(_layernorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        grid=(xp.shape[0] // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        interpret=interpret,
    )(xp, gamma.reshape(1, d), beta.reshape(1, d))
    return out[:rows]


def _layernorm_reference(x, gamma, beta, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) +
            beta.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layernorm(x2, gamma, beta, eps, interpret):
    return _layernorm_forward(x2, gamma, beta, eps, interpret)


def _layernorm_fwd(x2, gamma, beta, eps, interpret):
    return _layernorm_forward(x2, gamma, beta, eps, interpret), \
        (x2, gamma, beta)


def _layernorm_bwd(eps, interpret, res, g):
    x2, gamma, beta = res
    _, vjp = jax.vjp(
        lambda x_, g_, b_: _layernorm_reference(x_, g_, b_, eps),
        x2, gamma, beta)
    return vjp(g)


_layernorm.defvjp(_layernorm_fwd, _layernorm_bwd)


def fused_layernorm(x: jnp.ndarray, gamma: jnp.ndarray, beta: jnp.ndarray,
                    eps: float = 1e-6,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """LayerNorm over the last axis as a single fused kernel.

    ``x``: [..., d]; ``gamma``/``beta``: [d].  Statistics in float32
    regardless of input dtype; backward rematerialises via the XLA
    reference under ``jax.vjp``.
    """
    if interpret is None:
        interpret = _use_interpret()
    d = x.shape[-1]
    lead = x.shape[:-1]
    out2 = _layernorm(x.reshape(-1, d), gamma, beta, float(eps),
                      bool(interpret))
    return out2.reshape(*lead, d)


# ---------------------------------------------------------------------------
# Fused RMSNorm (the Llama block norm: f32 rms, gamma scale, no centering)
# ---------------------------------------------------------------------------

def _rmsnorm_kernel(x_ref, gamma_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                       # [br, d]
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * inv * gamma_ref[:].astype(jnp.float32)
                ).astype(o_ref.dtype)


def _rmsnorm_forward(x2, gamma, eps, interpret):
    rows, d = x2.shape
    br = min(_BLOCK_ROWS, rows)
    pad = (-rows) % br
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        grid=(xp.shape[0] // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        interpret=interpret,
    )(xp, gamma.reshape(1, d))
    return out[:rows]


def _rmsnorm_reference(x, gamma, eps):
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv * gamma.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rmsnorm(x2, gamma, eps, interpret):
    return _rmsnorm_forward(x2, gamma, eps, interpret)


def _rmsnorm_fwd(x2, gamma, eps, interpret):
    return _rmsnorm_forward(x2, gamma, eps, interpret), (x2, gamma)


def _rmsnorm_bwd(eps, interpret, res, g):
    x2, gamma = res
    _, vjp = jax.vjp(lambda x_, g_: _rmsnorm_reference(x_, g_, eps),
                     x2, gamma)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def fused_rmsnorm(x: jnp.ndarray, gamma: jnp.ndarray, eps: float = 1e-6,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """RMSNorm over the last axis as a single fused kernel.

    ``x``: [..., d]; ``gamma``: [d].  Same structure as
    ``fused_layernorm`` (f32 statistics, padded row blocks, XLA-reference
    backward under ``jax.vjp``) minus the centering and bias — matches
    the model's HF-LlamaRMSNorm numerics (models/gpt.py ``_norm``).
    """
    if interpret is None:
        interpret = _use_interpret()
    d = x.shape[-1]
    lead = x.shape[:-1]
    out2 = _rmsnorm(x.reshape(-1, d), gamma, float(eps), bool(interpret))
    return out2.reshape(*lead, d)
