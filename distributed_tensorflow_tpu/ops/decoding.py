"""Shared beam-search machinery for the autoregressive model families.

GPT (KV-cache) and seq2seq (cache-free) drive different decoders but the
beam bookkeeping is identical; keeping it here means a scoring/freeze fix
lands in one place (same rationale as ``attention_core``/``ffn_core``).
All functions are jit-friendly (static shapes, no Python branching on
traced values).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["init_beam_scores", "freeze_finished", "expand_beams",
           "rank_beams", "filtered_logits", "sample_logits",
           "resolve_pad", "finish_step", "decode_loop",
           "ragged_prompt_masks"]


def ragged_prompt_masks(prompt_valid, prompt_shape: Tuple[int, int],
                        max_len: int):
    """Validate a LEFT-padded ``prompt_valid`` mask and derive the decode
    quantities both ``generate`` and ``beam_search`` need:
    ``pad_len`` [b] (per-row pad count, for position shifting) and
    ``kv_valid`` [b, max_len] (pad slots False, generated slots True).

    The left-padded contract is validated on CONCRETE masks only — a
    tracer can't be inspected, so under jit a right-padded mask silently
    produces wrong positions and attention masks.  Callers who jit
    ``generate``/``beam_search`` with ``prompt_valid`` must guarantee
    left-padding themselves (e.g. validate before tracing)."""
    b, plen = prompt_shape
    if prompt_valid.shape != (b, plen):
        raise ValueError(f"prompt_valid shape {prompt_valid.shape} "
                         f"!= prompt shape {(b, plen)}")
    pv = prompt_valid.astype(bool)
    # only checkable on concrete masks; under jit the caller owns it
    if not isinstance(pv, jax.core.Tracer) and not bool(jnp.all(pv[:, -1])):
        raise ValueError("prompt_valid must be LEFT-padded: the last "
                         "prompt column must be all valid")
    pad_len = plen - jnp.sum(pv, axis=1).astype(jnp.int32)
    kv_valid = jnp.concatenate(
        [pv, jnp.ones((b, max_len - plen), bool)], axis=1)
    return pad_len, kv_valid


def resolve_pad(eos_id: Optional[int], pad_id: Optional[int]) -> Optional[int]:
    """Shared generate() argument contract: ``pad_id`` defaults to
    ``eos_id`` and is meaningless without one."""
    if pad_id is not None and eos_id is None:
        raise ValueError("pad_id requires eos_id (nothing finishes "
                         "without an EOS to detect)")
    return eos_id if pad_id is None else pad_id


def finish_step(nxt: jnp.ndarray, finished: jnp.ndarray, eos_id: int,
                pad: int, eligible=None):
    """One sampling step's finished-row bookkeeping: rows already finished
    emit ``pad``; rows emitting ``eos_id`` (while ``eligible`` — e.g. past
    the prompt) join the finished set.  Returns (next_tokens, finished)."""
    nxt = jnp.where(finished, pad, nxt)
    newly = nxt == eos_id
    if eligible is not None:
        newly = newly & eligible
    return nxt, finished | newly


def decode_loop(advance, carry, n_steps: int, start: int = 0):
    """Early-exit autoregressive driver: ``carry = advance(carry, i)`` for
    ``i`` in [start, n_steps), stopping as soon as every row has finished.
    ``carry[-1]`` must be the finished mask [b].  Returns
    (final carry, last index) — the shared while_loop half of
    GPT/seq2seq ``generate(eos_id=...)``.  ``start`` > 0 resumes after a
    batched prefill already consumed the first positions.
    """
    def cond(state):
        carry, i = state
        return (i < n_steps) & ~jnp.all(carry[-1])

    def body(state):
        carry, i = state
        return advance(carry, i), i + 1

    return lax.while_loop(cond, body, (carry, jnp.int32(start)))


def filtered_logits(logits: jnp.ndarray, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None) -> jnp.ndarray:
    """[b, V] logits after temperature scaling + top-k + nucleus
    filtering — exactly the distribution ``sample_logits`` draws from,
    exposed for consumers that need the probabilities themselves
    (speculative decoding's acceptance rule).  Dropped tokens are -inf
    (zero probability after softmax).  Requires ``temperature > 0``.
    """
    logits = logits / temperature
    neg = jnp.asarray(-jnp.inf, logits.dtype)
    need_k = top_k is not None and top_k < logits.shape[-1]
    need_p = top_p is not None and top_p < 1.0
    if need_p:
        b, vocab = logits.shape  # nucleus scatter-back needs [b, V] here
        # One full sort serves both filters: positions >= k are exactly the
        # tokens a top-k threshold would drop, so the k filter is a
        # positional mask on the sorted array, applied BEFORE the softmax so
        # the nucleus mass is measured on the k-renormalized distribution
        # (the documented k-then-p composition).
        sorted_logits, sorted_idx = lax.top_k(logits, vocab)
        if need_k:
            sorted_logits = jnp.where(jnp.arange(vocab)[None, :] < top_k,
                                      sorted_logits, neg)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # keep while the EXCLUSIVE prefix mass is < p; the top token stays
        # unconditionally (top_p <= 0 must degrade to greedy, not to an
        # all--inf row that categorical() collapses to id 0)
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        keep = keep.at[..., 0].set(True)
        filtered = jnp.where(keep, sorted_logits, neg)
        logits = jnp.full_like(logits, neg).at[
            jnp.arange(b)[:, None], sorted_idx].set(filtered)
    elif need_k:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    return logits


def sample_logits(rng, logits: jnp.ndarray, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """Next-token selection from [b, V] logits (shared by every generate).

    ``temperature <= 0`` is greedy argmax.  ``top_k`` keeps the k highest
    logits; ``top_p`` (nucleus) keeps the smallest prefix of the sorted
    distribution whose cumulative probability reaches p (always at least
    the top token).  Filters compose (k first, then p).  Static config —
    jit recompiles per setting, as with temperature.
    """
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, filtered_logits(logits, temperature, top_k, top_p)
    ).astype(jnp.int32)


def init_beam_scores(batch: int, beam: int) -> jnp.ndarray:
    """[b, k] scores with only beam 0 alive — identical start beams would
    otherwise collapse the search to k copies of one hypothesis."""
    return jnp.where(jnp.arange(beam)[None, :] == 0, 0.0,
                     -jnp.inf) * jnp.ones((batch, 1))


def freeze_finished(logp: jnp.ndarray, finished: jnp.ndarray,
                    eos_id: Optional[int]) -> jnp.ndarray:
    """Finished beams may only extend with EOS, at zero added cost —
    their score is frozen while still competing in the top-k."""
    if eos_id is None:
        return logp
    vocab = logp.shape[-1]
    frozen = jnp.full((vocab,), -jnp.inf).at[eos_id].set(0.0)
    return jnp.where(finished[:, :, None], frozen[None, None], logp)


def expand_beams(scores: jnp.ndarray, logp: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One beam expansion: ``scores`` [b, k] + ``logp`` [b, k, V] ->
    (new_scores [b, k], source beam [b, k], token [b, k] int32)."""
    b, k, vocab = logp.shape
    top, idx = lax.top_k((scores[:, :, None] + logp).reshape(b, k * vocab),
                         k)
    return top, idx // vocab, (idx % vocab).astype(jnp.int32)


def rank_beams(scores: jnp.ndarray, generated: jnp.ndarray,
               eos_id: Optional[int], max_new_tokens: int,
               length_penalty: float) -> jnp.ndarray:
    """Best beam index per batch row (GNMT ``score / len^alpha``; length =
    position of the first EOS in ``generated`` [b, k, T], else T)."""
    b, k = scores.shape
    if eos_id is not None:
        is_eos = generated == eos_id
        lengths = jnp.where(is_eos.any(-1), jnp.argmax(is_eos, -1) + 1,
                            max_new_tokens)
    else:
        lengths = jnp.full((b, k), max_new_tokens)
    ranked = scores / jnp.power(lengths.astype(jnp.float32), length_penalty)
    return jnp.argmax(ranked, axis=1)
