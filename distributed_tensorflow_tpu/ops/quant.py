"""Weight-only int8 quantization for inference.

The reference era had no quantization story; on TPU the serving win is
HBM bandwidth: weights stored int8 are a 4x smaller read per forward pass
(and a 4x smaller checkpoint), dequantized to the activation dtype right
at the matmul operand, where XLA fuses the scale multiply into the fused
matmul prologue.  This is deliberately WEIGHT-ONLY (activations stay
bf16/f32): no calibration data needed, exactness is a per-leaf rounding
error bounded by scale/2, and every model family's ``apply`` works
unchanged on ``dequantize_tree`` output.

Symmetric per-channel scheme: ``q = round(w / scale)`` with
``scale = max|w| / 127``.  The default reduction keeps the FIRST and LAST
axes of >=3-D kernels (and the last axis of matrices): the last axis is
the output channel, and the first axis of the scanned model families'
kernels is the ``[L, ...]`` layer-stacking dim — one stack-wide scale
would let the widest layer set the range for all L, inflating everyone
else's rounding error, so each layer slice keeps its own scale (at
O(L x out_channels) extra floats, negligible).  Scale granularity never
affects correctness (dequantize is elementwise); it only tightens the
per-slice error bound.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "quantize_tensor", "dequantize_tensor",
           "quantize_tree", "dequantize_tree", "quantized_bytes"]


class QTensor(NamedTuple):
    """int8 values + broadcastable f32 scale (a pytree node, so QTensor
    trees checkpoint/shard through the existing machinery)."""
    q: jnp.ndarray          # int8, same shape as the original weight
    scale: jnp.ndarray      # f32, broadcastable against q


def _auto_reduce_axes(ndim: int) -> Optional[tuple]:
    """Keep first+last axes of >=3-D kernels ([L, ...] stacks, output
    channels); matrices keep only the output channel; scalars/vectors get
    one whole-tensor scale (per-element scales would be larger than the
    f32 input)."""
    if ndim <= 1:
        return None
    if ndim == 2:
        return (0,)
    return tuple(range(1, ndim - 1))


def quantize_tensor(w: jnp.ndarray, reduce_axes="auto") -> QTensor:
    """Symmetric int8 quantization.  ``reduce_axes``: axes the scale's
    max-reduction runs over — every other axis keeps a per-slice scale.
    ``"auto"`` (default) applies the module's first+last-keep rule;
    ``None`` = one scale for the whole tensor."""
    wf = w.astype(jnp.float32)
    if reduce_axes == "auto":
        reduce_axes = _auto_reduce_axes(wf.ndim)
    if reduce_axes is None:
        amax = jnp.max(jnp.abs(wf))
        scale = jnp.maximum(amax / 127.0, 1e-12)
    else:
        axes = tuple(a % wf.ndim for a in reduce_axes)
        amax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
        scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale.astype(jnp.float32))


def dequantize_tensor(t: QTensor, dtype=jnp.float32) -> jnp.ndarray:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def _is_quantizable(leaf, min_size: int) -> bool:
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and leaf.size >= min_size)


def quantize_tree(params: Any, min_size: int = 1024,
                  reduce_axes="auto") -> Any:
    """Quantize every float matrix/conv kernel leaf with >= ``min_size``
    elements (biases, norm scales, and tiny tensors stay full precision —
    they are O(channels) and carry the model's calibration-sensitive
    parts).  Structure is preserved: quantized leaves become ``QTensor``
    nodes in place."""
    def visit(leaf):
        if isinstance(leaf, QTensor):   # idempotent on re-quantization
            return leaf
        if _is_quantizable(leaf, min_size):
            return quantize_tensor(leaf, reduce_axes=reduce_axes)
        return leaf
    return jax.tree.map(visit, params,
                        is_leaf=lambda l: isinstance(l, QTensor))


def dequantize_tree(qparams: Any, dtype=jnp.float32) -> Any:
    """Inverse of ``quantize_tree``: a params pytree any model ``apply``
    accepts.  Under jit, XLA keeps the int8 arrays as the HBM-resident
    operands and fuses the widen+scale into their consumers."""
    return jax.tree.map(
        lambda leaf: (dequantize_tensor(leaf, dtype)
                      if isinstance(leaf, QTensor) else leaf),
        qparams, is_leaf=lambda l: isinstance(l, QTensor))


def quantized_bytes(tree: Any) -> int:
    """Total parameter bytes of a (possibly partially) quantized tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += leaf.size * leaf.dtype.itemsize
    return total
