"""Parameter initializers.

The reference gets initialization implicitly from Keras 2.0.8 layer defaults
(glorot_uniform kernels, zero biases — invoked at reference example.py:149-155
via ``Dense(...)``).  Here they are explicit, PRNG-keyed, and dtype-aware so
params can be created directly in bfloat16 on TPU when requested.
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["zeros", "ones", "constant", "normal", "truncated_normal",
           "uniform", "glorot_uniform", "glorot_normal", "he_normal",
           "he_uniform", "lecun_normal", "orthogonal", "get"]


def zeros(key, shape, dtype=jnp.float32):
    del key
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype=jnp.float32):
    del key
    return jnp.ones(shape, dtype)


def constant(value):
    def init(key, shape, dtype=jnp.float32):
        del key
        return jnp.full(shape, value, dtype)
    return init


def normal(stddev=0.01):
    def init(key, shape, dtype=jnp.float32):
        return (stddev * jax.random.normal(key, shape)).astype(dtype)
    return init


def truncated_normal(stddev=0.02):
    def init(key, shape, dtype=jnp.float32):
        return (stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape)
                ).astype(dtype)
    return init


def uniform(scale=0.05):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, minval=-scale, maxval=scale
                                  ).astype(dtype)
    return init


def _fans(shape: Sequence[int]):
    """fan_in/fan_out for dense ([in, out]) and conv ([h, w, in, out])."""
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = math.prod(shape[:-2]) if len(shape) > 2 else 1
    return shape[-2] * receptive, shape[-1] * receptive


def _variance_scaling(scale: float, mode: str, distribution: str):
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        if mode == "fan_in":
            denom = max(1, fan_in)
        elif mode == "fan_out":
            denom = max(1, fan_out)
        else:
            denom = max(1.0, (fan_in + fan_out) / 2.0)
        variance = scale / denom
        if distribution == "uniform":
            limit = math.sqrt(3.0 * variance)
            out = jax.random.uniform(key, shape, minval=-limit, maxval=limit)
        else:
            stddev = math.sqrt(variance)
            if distribution == "truncated_normal":
                # correction so post-truncation stddev is as requested
                stddev = stddev / 0.87962566103423978
                out = stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape)
            else:
                out = stddev * jax.random.normal(key, shape)
        return out.astype(dtype)
    return init


def glorot_uniform():
    return _variance_scaling(1.0, "fan_avg", "uniform")


def glorot_normal():
    return _variance_scaling(1.0, "fan_avg", "truncated_normal")


def he_normal():
    return _variance_scaling(2.0, "fan_in", "truncated_normal")


def he_uniform():
    return _variance_scaling(2.0, "fan_in", "uniform")


def lecun_normal():
    return _variance_scaling(1.0, "fan_in", "truncated_normal")


def orthogonal(scale: float = 1.0):
    """Orthogonal init via QR of a normal matrix (Keras recurrent-kernel
    default — keeps recurrent spectra near 1 so long scans don't explode).
    QR of the (max, min) rectangle, not (max, max): same distribution,
    min/max-fold cheaper for the wide recurrent kernels this serves."""
    def init(key, shape, dtype=jnp.float32):
        if len(shape) < 2:
            raise ValueError(f"orthogonal needs >= 2 dims, got {shape}")
        rows = math.prod(shape[:-1])
        cols = shape[-1]
        big, small = max(rows, cols), min(rows, cols)
        a = jax.random.normal(key, (big, small))
        q, r = jnp.linalg.qr(a)          # q: [big, small], orthonormal cols
        # sign-correct so the distribution is uniform over orthogonal mats
        q = q * jnp.sign(jnp.diagonal(r))[None, :]
        if rows < cols:
            q = q.T
        return (scale * q).reshape(shape).astype(dtype)
    return init


_REGISTRY = {
    "zeros": zeros,
    "ones": ones,
    "glorot_uniform": glorot_uniform(),
    "glorot_normal": glorot_normal(),
    "he_normal": he_normal(),
    "he_uniform": he_uniform(),
    "lecun_normal": lecun_normal(),
    "orthogonal": orthogonal(),
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown initializer {name_or_fn!r}; "
                         f"known: {sorted(_REGISTRY)}") from None
