"""Loss functions.

The reference uses mean-squared error via ``keras.losses.mean_squared_error``
wrapped in a ``reduce_mean`` (reference example.py:162-163) and the string
``'mean_squared_error'`` in ``compile`` (reference example2.py:165).  The
classification configs (MNIST/CIFAR/BERT in BASELINE.md) need cross-entropy.

All losses reduce in float32 regardless of input dtype (bf16-safe) and return
a scalar mean over all leading dims — under data-parallel sharding the global
mean is exactly what makes XLA's gradient all-reduce a mean over replicas.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["mean_squared_error", "binary_cross_entropy",
           "softmax_cross_entropy", "softmax_cross_entropy_with_integer_labels",
           "smoothed_cross_entropy", "mean_absolute_error",
           "mean_absolute_percentage_error", "mean_squared_logarithmic_error",
           "hinge", "squared_hinge", "kullback_leibler_divergence", "poisson",
           "cosine_proximity", "huber", "class_weighted", "get"]


def mean_squared_error(preds, targets):
    diff = preds.astype(jnp.float32) - targets.astype(jnp.float32)
    return jnp.mean(jnp.square(diff))


def binary_cross_entropy(preds, targets, epsilon: float = 1e-7):
    """BCE over sigmoid outputs (probabilities), like Keras binary_crossentropy."""
    p = jnp.clip(preds.astype(jnp.float32), epsilon, 1.0 - epsilon)
    t = targets.astype(jnp.float32)
    return -jnp.mean(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))


def softmax_cross_entropy(logits, onehot_targets):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(onehot_targets * logp, axis=-1))


def softmax_cross_entropy_with_integer_labels(logits, labels, where=None):
    """XE with int labels; optional ``where`` weights/mask (BERT MLM
    masked positions, class_weighted's per-sample weights).  The epsilon
    floor only guards the all-masked case (0/eps = 0); fractional weight
    sums below 1 divide exactly (a 1.0 floor would silently shrink
    small-weight batches)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if where is None:
        return jnp.mean(nll)
    w = where.astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1e-9)


def mean_absolute_error(preds, targets):
    return jnp.mean(jnp.abs(preds.astype(jnp.float32)
                            - targets.astype(jnp.float32)))


def mean_absolute_percentage_error(preds, targets, epsilon: float = 1e-7):
    """Keras MAPE: 100 * mean(|t - p| / max(|t|, eps))."""
    t = targets.astype(jnp.float32)
    diff = jnp.abs(t - preds.astype(jnp.float32))
    return 100.0 * jnp.mean(diff / jnp.maximum(jnp.abs(t), epsilon))


def mean_squared_logarithmic_error(preds, targets):
    """Keras MSLE over non-negative predictions/targets."""
    p = jnp.log1p(jnp.maximum(preds.astype(jnp.float32), 0.0))
    t = jnp.log1p(jnp.maximum(targets.astype(jnp.float32), 0.0))
    return jnp.mean(jnp.square(p - t))


def hinge(preds, targets):
    """Targets in {-1, +1} (Keras hinge convention)."""
    return jnp.mean(jnp.maximum(
        1.0 - targets.astype(jnp.float32) * preds.astype(jnp.float32), 0.0))


def squared_hinge(preds, targets):
    return jnp.mean(jnp.square(jnp.maximum(
        1.0 - targets.astype(jnp.float32) * preds.astype(jnp.float32), 0.0)))


def kullback_leibler_divergence(preds, targets, epsilon: float = 1e-7):
    """KL(targets || preds) over probability rows, summed across the last
    axis then averaged (Keras kld)."""
    p = jnp.clip(preds.astype(jnp.float32), epsilon, 1.0)
    t = jnp.clip(targets.astype(jnp.float32), epsilon, 1.0)
    return jnp.mean(jnp.sum(t * jnp.log(t / p), axis=-1))


def poisson(preds, targets, epsilon: float = 1e-7):
    p = preds.astype(jnp.float32)
    return jnp.mean(p - targets.astype(jnp.float32)
                    * jnp.log(p + epsilon))


def cosine_proximity(preds, targets, epsilon: float = 1e-12):
    """Negative mean cosine similarity along the last axis (minimizing it
    aligns predictions with targets — Keras 2.0 sign convention)."""
    p = preds.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    p = p / jnp.maximum(jnp.linalg.norm(p, axis=-1, keepdims=True), epsilon)
    t = t / jnp.maximum(jnp.linalg.norm(t, axis=-1, keepdims=True), epsilon)
    return -jnp.mean(jnp.sum(p * t, axis=-1))


def huber(delta: float = 1.0):
    """Factory: quadratic within ``delta``, linear outside (robust MSE)."""
    d = float(delta)

    def loss(preds, targets):
        err = jnp.abs(preds.astype(jnp.float32)
                      - targets.astype(jnp.float32))
        quad = jnp.minimum(err, d)
        return jnp.mean(0.5 * jnp.square(quad) + d * (err - quad))

    loss.__name__ = f"huber_{d}"
    return loss


def smoothed_cross_entropy(smoothing: float = 0.1):
    """Factory: XE with label smoothing (the ResNet/ImageNet recipe).

    Targets become ``(1 - s)`` on the true class and ``s / C`` elsewhere —
    equivalently ``(1-s)·NLL + s·mean(-logp)``, which is how it's computed
    (no one-hot materialization).
    """
    s = float(smoothing)

    def loss(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        uniform = -jnp.mean(logp, axis=-1)
        return jnp.mean((1.0 - s) * nll + s * uniform)

    loss.__name__ = f"smoothed_cross_entropy_{s}"
    return loss


_REGISTRY = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "binary_crossentropy": binary_cross_entropy,
    "categorical_crossentropy": softmax_cross_entropy,
    "sparse_categorical_crossentropy":
        softmax_cross_entropy_with_integer_labels,
    # factories by name use their standard settings; call for custom ones
    "smoothed_cross_entropy": smoothed_cross_entropy(0.1),
    "huber": huber(1.0),
}


def class_weighted(base: str, class_weight):
    """Weighted variant of a classification loss for ``fit(class_weight=)``
    (Keras semantics: per-sample weights looked up from the label's class,
    weighted-mean reduction so the loss scale is weight-invariant when all
    weights are equal).

    Supported bases: ``sparse_categorical_crossentropy`` (integer labels)
    and ``binary_crossentropy`` (0/1 targets, elementwise).  Classes
    absent from the dict weigh 1.0.
    """
    names = {"sparse_categorical_crossentropy", "binary_crossentropy"}
    if base not in names:
        raise ValueError(f"class_weight supports {sorted(names)}; "
                         f"got loss {base!r}")
    if not class_weight:
        return get(base)            # Keras: empty dict is a no-op
    if any(int(k) < 0 for k in class_weight):
        raise ValueError(f"class_weight keys must be >= 0 class ids; "
                         f"got {sorted(class_weight)}")
    n = max(int(k) for k in class_weight) + 1
    lut = [1.0] * n
    for k, v in class_weight.items():
        lut[int(k)] = float(v)
    lut_arr = jnp.asarray(lut, jnp.float32)

    def weight_of(labels):
        """Class id -> weight; ids past the dict's range weigh 1.0 (NOT
        the last entry — clipping would silently reuse the largest
        specified class's weight, e.g. class_weight={1: 10} skewing
        every class >= 2)."""
        ids = labels.astype(jnp.int32)
        return jnp.where(ids < n,
                         jnp.take(lut_arr, jnp.clip(ids, 0, n - 1)), 1.0)

    if base == "sparse_categorical_crossentropy":
        def loss(logits, labels):
            # the shared XE path's masked-mean reduction IS the weighted
            # mean when handed float weights
            return softmax_cross_entropy_with_integer_labels(
                logits, labels, where=weight_of(labels))
    else:
        def loss(preds, targets, epsilon: float = 1e-7):
            p = jnp.clip(preds.astype(jnp.float32), epsilon, 1.0 - epsilon)
            t = targets.astype(jnp.float32)
            bce = -(t * jnp.log(p) + (1.0 - t) * jnp.log1p(-p))
            # Soft/label-smoothed targets (e.g. 0.9) round to the nearest
            # class for the weight lookup — a bare int cast would floor
            # them all to class 0's weight.
            w = weight_of(t > 0.5)
            return jnp.sum(bce * w) / jnp.maximum(jnp.sum(w), 1e-9)

    loss.__name__ = f"class_weighted_{base}"
    return loss


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown loss {name_or_fn!r}; "
                         f"known: {sorted(_REGISTRY)}") from None
