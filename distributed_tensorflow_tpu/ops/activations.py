"""Activation functions (XLA fuses these into adjacent matmuls on TPU).

The reference uses ``relu`` and ``sigmoid`` as Keras layer kwargs
(reference example.py:149-155).  Registry lookup keeps that string-based
API; everything is a plain jnp function so it traces into one fused HLO.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["relu", "sigmoid", "hard_sigmoid", "tanh", "gelu", "silu",
           "softmax", "log_softmax", "identity", "get"]

relu = jax.nn.relu
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
gelu = jax.nn.gelu
silu = jax.nn.silu
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax


def identity(x):
    return x


def hard_sigmoid(x):
    """Keras-2 hard_sigmoid: clip(0.2x + 0.5, 0, 1) — the piecewise-linear
    gate activation the reference-era LSTM/GRU default to."""
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_REGISTRY = {
    "relu": relu,
    "sigmoid": sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "tanh": tanh,
    "gelu": gelu,
    "silu": silu,
    "swish": silu,
    "softmax": softmax,
    "log_softmax": log_softmax,
    "linear": identity,
    "identity": identity,
    None: identity,
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown activation {name_or_fn!r}; "
                         f"known: {sorted(k for k in _REGISTRY if k)}") from None
