"""Metrics.

``bitwise_accuracy`` reproduces the reference's accuracy graph exactly:
mean(round(preds) == round(labels)) element-wise over the 32 output bits
(reference example.py:157-160).  ``accuracy`` is argmax accuracy for the
classification baseline configs.  The reference's broken ``xor_metric``
(example2.py:158-163 — no return statement, truthiness on arrays) is
intentionally not reproduced (SURVEY.md §2a #15).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["bitwise_accuracy", "accuracy", "top_k_accuracy", "get"]


def bitwise_accuracy(preds, targets):
    match = jnp.round(preds.astype(jnp.float32)) == jnp.round(
        targets.astype(jnp.float32))
    return jnp.mean(match.astype(jnp.float32))


def accuracy(logits, labels):
    """Argmax accuracy; labels may be int class ids or one-hot."""
    if labels.ndim == logits.ndim:
        labels = jnp.argmax(labels, axis=-1)
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def top_k_accuracy(k: int):
    def metric(logits, labels):
        if labels.ndim == logits.ndim:
            labels = jnp.argmax(labels, axis=-1)
        top = jnp.argsort(logits, axis=-1)[..., -k:]
        hit = jnp.any(top == labels[..., None], axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    metric.__name__ = f"top_{k}_accuracy"
    return metric


_REGISTRY = {
    "accuracy": accuracy,
    "bitwise_accuracy": bitwise_accuracy,
    "top_5_accuracy": top_k_accuracy(5),
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown metric {name_or_fn!r}; "
                         f"known: {sorted(_REGISTRY)}") from None
