"""Metrics.

``bitwise_accuracy`` reproduces the reference's accuracy graph exactly:
mean(round(preds) == round(labels)) element-wise over the 32 output bits
(reference example.py:157-160).  ``accuracy`` is argmax accuracy for the
classification baseline configs.  The reference's broken ``xor_metric``
(example2.py:158-163 — no return statement, truthiness on arrays) is
intentionally not reproduced (SURVEY.md §2a #15).
"""
from __future__ import annotations

import jax.numpy as jnp

from .losses import mean_absolute_error  # one definition serves both tables

__all__ = ["bitwise_accuracy", "accuracy", "top_k_accuracy",
           "binary_accuracy", "mean_absolute_error", "precision", "recall",
           "f1_score", "get"]


def bitwise_accuracy(preds, targets):
    match = jnp.round(preds.astype(jnp.float32)) == jnp.round(
        targets.astype(jnp.float32))
    return jnp.mean(match.astype(jnp.float32))


def accuracy(logits, labels):
    """Argmax accuracy; labels may be int class ids or one-hot."""
    if labels.ndim == logits.ndim:
        labels = jnp.argmax(labels, axis=-1)
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def top_k_accuracy(k: int):
    def metric(logits, labels):
        if labels.ndim == logits.ndim:
            labels = jnp.argmax(labels, axis=-1)
        top = jnp.argsort(logits, axis=-1)[..., -k:]
        hit = jnp.any(top == labels[..., None], axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    metric.__name__ = f"top_{k}_accuracy"
    return metric


def binary_accuracy(preds, targets, threshold: float = 0.5):
    """Keras binary_accuracy: thresholded sigmoid outputs vs 0/1 targets."""
    hits = (preds.astype(jnp.float32) > threshold) == (
        targets.astype(jnp.float32) > threshold)
    return jnp.mean(hits.astype(jnp.float32))


def _binary_counts(preds, targets, threshold: float):
    p = (preds.astype(jnp.float32) > threshold).astype(jnp.float32)
    t = (targets.astype(jnp.float32) > threshold).astype(jnp.float32)
    tp = jnp.sum(p * t)
    return tp, jnp.sum(p), jnp.sum(t)


def precision(preds, targets, threshold: float = 0.5, epsilon: float = 1e-7):
    """Batch precision over thresholded binary outputs (per-batch, the
    jit-friendly form; exact dataset-level values need streamed counts)."""
    tp, pred_pos, _ = _binary_counts(preds, targets, threshold)
    return tp / jnp.maximum(pred_pos, epsilon)


def recall(preds, targets, threshold: float = 0.5, epsilon: float = 1e-7):
    tp, _, actual_pos = _binary_counts(preds, targets, threshold)
    return tp / jnp.maximum(actual_pos, epsilon)


def f1_score(preds, targets, threshold: float = 0.5, epsilon: float = 1e-7):
    tp, pred_pos, actual_pos = _binary_counts(preds, targets, threshold)
    return 2.0 * tp / jnp.maximum(pred_pos + actual_pos, epsilon)


_REGISTRY = {
    "accuracy": accuracy,
    "categorical_accuracy": accuracy,
    "sparse_categorical_accuracy": accuracy,
    "binary_accuracy": binary_accuracy,
    "bitwise_accuracy": bitwise_accuracy,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "precision": precision,
    "recall": recall,
    "f1": f1_score,
    "f1_score": f1_score,
    "top_5_accuracy": top_k_accuracy(5),
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"unknown metric {name_or_fn!r}; "
                         f"known: {sorted(_REGISTRY)}") from None
