"""Functional layer library (the L6 module tier).

TPU-native re-design of the layer surface the reference pulls from Keras
2.0.8: ``Dense(128, activation='relu')``, ``Dropout(0.3)`` applied to
placeholders (reference example.py:149-155) and the same stack inside a
``Sequential`` (reference example2.py:151-156).  Plus the conv/norm/embedding
layers needed by the driver's CNN / ResNet-50 / BERT baseline configs.

Conventions
-----------
* A ``Layer`` is a lightweight config object; all tensors live in explicit
  pytrees.  ``init(key, in_shape) -> (params, state)`` where ``in_shape`` is
  the per-example feature shape (no batch dim).  ``params`` is trainable;
  ``state`` holds non-trainable stats (BatchNorm running moments) so
  optimizers never have to mask anything.
* ``apply(params, state, x, *, train=False, rng=None) -> (y, new_state)``.
  ``train``/``rng`` replace the reference's global Keras learning-phase feed
  (``K.learning_phase()`` at example.py:213,225) with explicit arguments —
  a requirement for jit-traceability (two traces: train=True / train=False),
  and Dropout randomness becomes explicit key-threading (SURVEY.md §7).
* Mixed precision: params are stored in ``param_dtype`` (default f32) and
  cast to the input's dtype at apply time, so feeding bf16 activations runs
  the matmul on the MXU in bf16 with f32 master weights.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from . import activations as act_lib
from . import initializers as init_lib

__all__ = ["Layer", "layer_spec",
           "Dense", "Dropout", "Flatten", "Activation", "Conv2D",
           "Conv1D", "DepthwiseConv2D", "SeparableConv2D",
           "MaxPool2D", "AvgPool2D", "GlobalAvgPool", "BatchNorm",
           "LayerNorm", "Embedding", "LSTM", "GRU", "serial", "Stack"]

Params = Dict[str, Any]
State = Dict[str, Any]
Shape = Tuple[int, ...]


def _pair(v: Union[int, Sequence[int]]) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    a, b = v
    return (int(a), int(b))


def _by_name(value, what: str, layer: "Layer"):
    """Serialization guard: config entries must be registry names, not
    callables (a callable can't round-trip through JSON)."""
    if value is None or isinstance(value, str):
        return value
    raise ValueError(
        f"{type(layer).__name__} {layer.name!r} was constructed with a "
        f"callable {what}; pass it by registry name to serialize the model")


def _dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


def layer_spec(layer: "Layer") -> Dict[str, Any]:
    """The one {class_name, config} serialization spec shape — shared by
    Stack.get_config and models.saving.model_to_config."""
    return {"class_name": type(layer).__name__, "config": layer.get_config()}


def _conv_out(size: int, k: int, s: int, padding: str) -> int:
    """Spatial output extent for SAME/VALID — the one formula every conv
    and pool variant shares (== Keras floor((t-k)/s)+1 for VALID)."""
    if padding == "SAME":
        return -(-size // s)
    return -(-(size - k + 1) // s)


class Layer:
    """Base layer: stateless identity."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__.lower()

    def get_config(self) -> Dict[str, Any]:
        """JSON-able constructor kwargs; ``type(self)(**config)`` rebuilds
        the layer (Keras ``get_config``/``from_config`` convention, the
        serialization half of ``model.save``)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement get_config; the "
            "model can't be serialized with this layer")

    def init(self, key, in_shape: Shape) -> Tuple[Params, State]:
        del key, in_shape
        return {}, {}

    def out_shape(self, in_shape: Shape) -> Shape:
        return tuple(in_shape)

    def apply(self, params: Params, state: State, x, *, train: bool = False,
              rng=None):
        del params, train, rng
        return x, state

    def __repr__(self):
        return f"{type(self).__name__}()"


class Dense(Layer):
    """y = act(x @ W + b).  Keras-parity default init (glorot_uniform/zeros).

    Replaces ``keras.layers.Dense`` as invoked at reference example.py:149-155.
    The kernel is stored ``[in, out]`` so ``pjit`` tensor-parallel sharding
    specs can target the output dim with ``P(None, 'tensor')``.
    """

    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_init="glorot_uniform", bias_init="zeros",
                 param_dtype=jnp.float32, name: Optional[str] = None):
        super().__init__(name)
        self.units = int(units)
        self.activation = act_lib.get(activation)
        self.use_bias = use_bias
        self.kernel_init = init_lib.get(kernel_init)
        self.bias_init = init_lib.get(bias_init)
        self.param_dtype = param_dtype
        self._raw = dict(activation=activation, kernel_init=kernel_init,
                         bias_init=bias_init)

    def get_config(self):
        return dict(units=self.units,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    use_bias=self.use_bias,
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    bias_init=_by_name(self._raw["bias_init"],
                                       "bias_init", self),
                    param_dtype=_dtype_name(self.param_dtype),
                    name=self.name)

    def init(self, key, in_shape):
        in_dim = in_shape[-1]
        k_kernel, k_bias = jax.random.split(key)
        params = {"kernel": self.kernel_init(
            k_kernel, (in_dim, self.units), self.param_dtype)}
        if self.use_bias:
            params["bias"] = self.bias_init(
                k_bias, (self.units,), self.param_dtype)
        return params, {}

    def out_shape(self, in_shape):
        return tuple(in_shape[:-1]) + (self.units,)

    def apply(self, params, state, x, *, train=False, rng=None):
        kernel = params["kernel"].astype(x.dtype)
        y = x @ kernel
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y), state

    def __repr__(self):
        return f"Dense({self.units})"


class Dropout(Layer):
    """Inverted dropout; active only when ``train`` and ``rng`` provided.

    Replaces ``keras.layers.Dropout(0.3)`` + the learning-phase feed
    (reference example.py:151,153,213,225): phase is the ``train`` kwarg and
    randomness is an explicit PRNG key (split per step/layer by callers).
    """

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        if not 0.0 <= rate < 1.0:
            raise ValueError(f"dropout rate must be in [0, 1), got {rate}")
        self.rate = float(rate)

    def get_config(self):
        return dict(rate=self.rate, name=self.name)

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout.apply(train=True) requires an rng key")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x)), state

    def __repr__(self):
        return f"Dropout({self.rate})"


class Flatten(Layer):
    def get_config(self):
        return dict(name=self.name)

    def out_shape(self, in_shape):
        return (math.prod(in_shape),)

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], -1), state


class Activation(Layer):
    def __init__(self, fn, name: Optional[str] = None):
        super().__init__(name)
        self.fn = act_lib.get(fn)
        self._raw_fn = fn

    def get_config(self):
        return dict(fn=_by_name(self._raw_fn, "fn", self), name=self.name)

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.fn(x), state


class Conv2D(Layer):
    """NHWC conv via ``lax.conv_general_dilated`` (lowers to the MXU).

    Kernel layout HWIO so TP specs can shard the output-channel dim.
    """

    def __init__(self, filters: int, kernel_size, strides=1, padding="SAME",
                 activation=None, use_bias: bool = True,
                 kernel_init="he_normal", bias_init="zeros",
                 param_dtype=jnp.float32, name: Optional[str] = None):
        super().__init__(name)
        self.filters = int(filters)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding
        self.activation = act_lib.get(activation)
        self.use_bias = use_bias
        self.kernel_init = init_lib.get(kernel_init)
        self.bias_init = init_lib.get(bias_init)
        self.param_dtype = param_dtype
        self._raw = dict(activation=activation, kernel_init=kernel_init,
                         bias_init=bias_init)

    def get_config(self):
        return dict(filters=self.filters,
                    kernel_size=list(self.kernel_size),
                    strides=list(self.strides), padding=self.padding,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    use_bias=self.use_bias,
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    bias_init=_by_name(self._raw["bias_init"],
                                       "bias_init", self),
                    param_dtype=_dtype_name(self.param_dtype),
                    name=self.name)

    def init(self, key, in_shape):
        h, w, c = in_shape
        del h, w
        k_kernel, k_bias = jax.random.split(key)
        kh, kw = self.kernel_size
        params = {"kernel": self.kernel_init(
            k_kernel, (kh, kw, c, self.filters), self.param_dtype)}
        if self.use_bias:
            params["bias"] = self.bias_init(
                k_bias, (self.filters,), self.param_dtype)
        return params, {}

    def out_shape(self, in_shape):
        h, w, _ = in_shape
        (kh, kw), (sh, sw) = self.kernel_size, self.strides
        return (_conv_out(h, kh, sh, self.padding),
                _conv_out(w, kw, sw, self.padding), self.filters)

    def apply(self, params, state, x, *, train=False, rng=None):
        kernel = params["kernel"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, kernel, window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y), state

    def __repr__(self):
        return f"Conv2D({self.filters}, {self.kernel_size})"


class Conv1D(Layer):
    """NWC 1-D conv (sequence/temporal features) via the same
    ``conv_general_dilated`` lowering as Conv2D."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "SAME", activation=None,
                 use_bias: bool = True, kernel_init="he_normal",
                 bias_init="zeros", param_dtype=jnp.float32,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = int(filters)
        self.kernel_size = int(kernel_size)
        self.strides = int(strides)
        self.padding = padding
        self.activation = act_lib.get(activation)
        self.use_bias = use_bias
        self.kernel_init = init_lib.get(kernel_init)
        self.bias_init = init_lib.get(bias_init)
        self.param_dtype = param_dtype
        self._raw = dict(activation=activation, kernel_init=kernel_init,
                         bias_init=bias_init)

    def get_config(self):
        return dict(filters=self.filters, kernel_size=self.kernel_size,
                    strides=self.strides, padding=self.padding,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    use_bias=self.use_bias,
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    bias_init=_by_name(self._raw["bias_init"],
                                       "bias_init", self),
                    param_dtype=_dtype_name(self.param_dtype),
                    name=self.name)

    def init(self, key, in_shape):
        t, c = in_shape
        del t
        k_kernel, k_bias = jax.random.split(key)
        params = {"kernel": self.kernel_init(
            k_kernel, (self.kernel_size, c, self.filters), self.param_dtype)}
        if self.use_bias:
            params["bias"] = self.bias_init(
                k_bias, (self.filters,), self.param_dtype)
        return params, {}

    def out_shape(self, in_shape):
        t, _ = in_shape
        return (_conv_out(t, self.kernel_size, self.strides, self.padding),
                self.filters)

    def apply(self, params, state, x, *, train=False, rng=None):
        kernel = params["kernel"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, kernel, window_strides=(self.strides,), padding=self.padding,
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y), state

    def __repr__(self):
        return f"Conv1D({self.filters}, {self.kernel_size})"


class DepthwiseConv2D(Layer):
    """Per-channel spatial conv (``feature_group_count = channels``) —
    the depthwise half of separable convs (MobileNet-style)."""

    def __init__(self, kernel_size, strides=1, padding="SAME",
                 depth_multiplier: int = 1, activation=None,
                 use_bias: bool = True, kernel_init="he_normal",
                 bias_init="zeros", param_dtype=jnp.float32,
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding
        self.depth_multiplier = int(depth_multiplier)
        self.activation = act_lib.get(activation)
        self.use_bias = use_bias
        self.kernel_init = init_lib.get(kernel_init)
        self.bias_init = init_lib.get(bias_init)
        self.param_dtype = param_dtype
        self._raw = dict(activation=activation, kernel_init=kernel_init,
                         bias_init=bias_init)

    def get_config(self):
        return dict(kernel_size=list(self.kernel_size),
                    strides=list(self.strides), padding=self.padding,
                    depth_multiplier=self.depth_multiplier,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    use_bias=self.use_bias,
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    bias_init=_by_name(self._raw["bias_init"],
                                       "bias_init", self),
                    param_dtype=_dtype_name(self.param_dtype),
                    name=self.name)

    def init(self, key, in_shape):
        _, _, c = in_shape
        k_kernel, k_bias = jax.random.split(key)
        kh, kw = self.kernel_size
        out = c * self.depth_multiplier
        params = {"kernel": self.kernel_init(
            k_kernel, (kh, kw, 1, out), self.param_dtype)}
        if self.use_bias:
            params["bias"] = self.bias_init(k_bias, (out,), self.param_dtype)
        return params, {}

    def out_shape(self, in_shape):
        h, w, c = in_shape
        (kh, kw), (sh, sw) = self.kernel_size, self.strides
        return (_conv_out(h, kh, sh, self.padding),
                _conv_out(w, kw, sw, self.padding),
                c * self.depth_multiplier)

    def apply(self, params, state, x, *, train=False, rng=None):
        kernel = params["kernel"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, kernel, window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y), state

    def __repr__(self):
        return f"DepthwiseConv2D({self.kernel_size})"


class SeparableConv2D(Layer):
    """Depthwise + pointwise factorized conv (Keras SeparableConv2D):
    ~k^2/filters of the FLOPs of a full conv at similar accuracy."""

    def __init__(self, filters: int, kernel_size, strides=1, padding="SAME",
                 depth_multiplier: int = 1, activation=None,
                 use_bias: bool = True, kernel_init="he_normal",
                 bias_init="zeros", param_dtype=jnp.float32,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = int(filters)
        self.depthwise = DepthwiseConv2D(
            kernel_size, strides=strides, padding=padding,
            depth_multiplier=depth_multiplier, use_bias=False,
            kernel_init=kernel_init, param_dtype=param_dtype)
        self.activation = act_lib.get(activation)
        self.use_bias = use_bias
        self.kernel_init = init_lib.get(kernel_init)
        self.bias_init = init_lib.get(bias_init)
        self.param_dtype = param_dtype
        self._raw = dict(activation=activation, kernel_init=kernel_init,
                         bias_init=bias_init)

    def get_config(self):
        d = self.depthwise
        return dict(filters=self.filters,
                    kernel_size=list(d.kernel_size),
                    strides=list(d.strides), padding=d.padding,
                    depth_multiplier=d.depth_multiplier,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    use_bias=self.use_bias,
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    bias_init=_by_name(self._raw["bias_init"],
                                       "bias_init", self),
                    param_dtype=_dtype_name(self.param_dtype),
                    name=self.name)

    def init(self, key, in_shape):
        k_dw, k_pw, k_bias = jax.random.split(key, 3)
        dw_params, _ = self.depthwise.init(k_dw, in_shape)
        mid = in_shape[-1] * self.depthwise.depth_multiplier
        params = {"depthwise": dw_params,
                  "pointwise": {"kernel": self.kernel_init(
                      k_pw, (1, 1, mid, self.filters), self.param_dtype)}}
        if self.use_bias:
            params["bias"] = self.bias_init(
                k_bias, (self.filters,), self.param_dtype)
        return params, {}

    def out_shape(self, in_shape):
        h, w, _ = self.depthwise.out_shape(in_shape)
        return (h, w, self.filters)

    def apply(self, params, state, x, *, train=False, rng=None):
        y, _ = self.depthwise.apply(params["depthwise"], {}, x)
        y = lax.conv_general_dilated(
            y, params["pointwise"]["kernel"].astype(y.dtype),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), state

    def __repr__(self):
        return f"SeparableConv2D({self.filters})"


class _Pool2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding="VALID",
                 name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = padding

    def get_config(self):
        return dict(pool_size=list(self.pool_size),
                    strides=list(self.strides), padding=self.padding,
                    name=self.name)

    def out_shape(self, in_shape):
        h, w, c = in_shape
        (kh, kw), (sh, sw) = self.pool_size, self.strides
        return (_conv_out(h, kh, sh, self.padding),
                _conv_out(w, kw, sw, self.padding), c)

    def _reduce(self, x, init, op):
        return lax.reduce_window(
            x, init, op,
            window_dimensions=(1,) + self.pool_size + (1,),
            window_strides=(1,) + self.strides + (1,),
            padding=self.padding)


class MaxPool2D(_Pool2D):
    def apply(self, params, state, x, *, train=False, rng=None):
        return self._reduce(x, -jnp.inf, lax.max), state


class AvgPool2D(_Pool2D):
    def apply(self, params, state, x, *, train=False, rng=None):
        total = self._reduce(x, 0.0, lax.add)
        if self.padding == "SAME":
            # Average over the *valid* elements per window (Keras/TF
            # semantics): edge windows divide by their true coverage.
            count = self._reduce(jnp.ones((1,) + x.shape[1:3] + (1,),
                                          x.dtype), 0.0, lax.add)
            return total / count, state
        return total / math.prod(self.pool_size), state


class GlobalAvgPool(Layer):
    """NHWC -> NC mean over spatial dims."""

    def get_config(self):
        return dict(name=self.name)

    def out_shape(self, in_shape):
        return (in_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state


class BatchNorm(Layer):
    """Batch normalization with running-moment state.

    ``axis_name`` makes the batch statistics *cross-replica* when the layer
    runs under ``shard_map``/``pmap`` with that mesh axis bound — the sync-DP
    analogue of per-worker-local stats in the reference's PS world.  Under
    plain ``jit`` over a sharded batch, XLA's global-mean semantics already
    give cross-device stats, so leave it None there.
    """

    def __init__(self, momentum: float = 0.9, epsilon: float = 1e-5,
                 scale: bool = True, center: bool = True,
                 axis_name: Optional[str] = None, name: Optional[str] = None):
        super().__init__(name)
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.scale = scale
        self.center = center
        self.axis_name = axis_name

    def get_config(self):
        return dict(momentum=self.momentum, epsilon=self.epsilon,
                    scale=self.scale, center=self.center,
                    axis_name=self.axis_name, name=self.name)

    def init(self, key, in_shape):
        del key
        dim = in_shape[-1]
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((dim,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((dim,), jnp.float32)
        state = {"mean": jnp.zeros((dim,), jnp.float32),
                 "var": jnp.ones((dim,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean_sq = lax.pmean(mean_sq, self.axis_name)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.epsilon)
        if self.scale:
            inv = inv * params["gamma"]
        y = (x.astype(jnp.float32) - mean) * inv
        if self.center:
            y = y + params["beta"]
        return y.astype(x.dtype), new_state


class LayerNorm(Layer):
    """Layer normalization over the trailing dim (transformer workhorse).

    ``fused=True`` runs the Pallas TPU kernel (``ops.pallas.fused_layernorm``,
    one HBM pass; interpret mode off-TPU); ``fused="auto"`` uses the
    kernel on TPU only (same switch as the BERT/GPT configs) — requires
    both scale and center.
    """

    def __init__(self, epsilon: float = 1e-6, scale: bool = True,
                 center: bool = True, fused=False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = float(epsilon)
        self.scale = scale
        self.center = center
        if fused and not (scale and center):
            raise ValueError("LayerNorm(fused=True) requires scale and "
                             "center (the kernel applies gamma and beta)")
        self.fused = fused

    def get_config(self):
        return dict(epsilon=self.epsilon, scale=self.scale,
                    center=self.center, fused=self.fused, name=self.name)

    def init(self, key, in_shape):
        del key
        dim = in_shape[-1]
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((dim,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((dim,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        from .pallas import resolve_fused_ln
        if resolve_fused_ln(self.fused):
            from .pallas import fused_layernorm
            return fused_layernorm(x, params["gamma"], params["beta"],
                                   eps=self.epsilon), state
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        if self.scale:
            y = y * params["gamma"]
        if self.center:
            y = y + params["beta"]
        return y.astype(x.dtype), state


class Embedding(Layer):
    """Token embedding table [vocab, dim]; shardable over 'tensor'."""

    def __init__(self, vocab_size: int, dim: int,
                 embedding_init=init_lib.normal(0.02),
                 name: Optional[str] = None):
        super().__init__(name)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.embedding_init = init_lib.get(embedding_init)
        self._raw_init = embedding_init

    def get_config(self):
        cfg = dict(vocab_size=self.vocab_size, dim=self.dim, name=self.name)
        # the class default is a callable created at def time; omitting it
        # from the config round-trips to the same default
        if self._raw_init is not Embedding.__init__.__defaults__[0]:
            cfg["embedding_init"] = _by_name(self._raw_init,
                                             "embedding_init", self)
        return cfg

    def init(self, key, in_shape):
        del in_shape
        return {"embedding": self.embedding_init(
            key, (self.vocab_size, self.dim), jnp.float32)}, {}

    def out_shape(self, in_shape):
        return tuple(in_shape) + (self.dim,)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.take(params["embedding"], x, axis=0), state

    def attend(self, params, x):
        """Tied-softmax logits: x @ E^T (BERT MLM head)."""
        return x @ params["embedding"].T.astype(x.dtype)


class _Recurrent(Layer):
    """Shared recurrent machinery: [b, t, f] -> [b, t, u] or [b, u].

    The time loop is ONE ``lax.scan`` (compiled once, O(1) trace in t);
    per-step math is a single [b, f+u] x [f+u, gates*u] matmul so the MXU
    sees one large GEMM per step.  All recurrent arithmetic runs in f32
    regardless of input dtype (carry stability).  Transformers are the
    TPU-preferred sequence architecture — these exist for Keras-2 API
    parity (keras.layers.LSTM/GRU) and small-model workloads.
    """

    gates = 1

    def __init__(self, units: int, return_sequences: bool = False,
                 activation="tanh", recurrent_activation="hard_sigmoid",
                 kernel_init="glorot_uniform",
                 recurrent_init="orthogonal",
                 name: Optional[str] = None):
        super().__init__(name)
        self.units = int(units)
        self.return_sequences = bool(return_sequences)
        # Keras-2 defaults: tanh candidate/output, hard_sigmoid gates —
        # weights ported from the reference-era stack reproduce exactly.
        self.act = act_lib.get(activation)
        self.rec_act = act_lib.get(recurrent_activation)
        self.kernel_init = init_lib.get(kernel_init)
        self.recurrent_init = init_lib.get(recurrent_init)
        self._raw = dict(activation=activation,
                         recurrent_activation=recurrent_activation,
                         kernel_init=kernel_init,
                         recurrent_init=recurrent_init)

    def get_config(self):
        return dict(units=self.units,
                    return_sequences=self.return_sequences,
                    activation=_by_name(self._raw["activation"],
                                        "activation", self),
                    recurrent_activation=_by_name(
                        self._raw["recurrent_activation"],
                        "recurrent_activation", self),
                    kernel_init=_by_name(self._raw["kernel_init"],
                                         "kernel_init", self),
                    recurrent_init=_by_name(self._raw["recurrent_init"],
                                            "recurrent_init", self),
                    name=self.name)

    def init(self, key, in_shape):
        t, f = in_shape
        del t
        k1, k2 = jax.random.split(key)
        g = self.gates
        params = {
            "kernel": self.kernel_init(k1, (f, g * self.units), jnp.float32),
            "recurrent_kernel": self.recurrent_init(
                k2, (self.units, g * self.units), jnp.float32),
            "bias": self._bias_init(),
        }
        return params, {}

    def _bias_init(self):
        return jnp.zeros((self.gates * self.units,), jnp.float32)

    def out_shape(self, in_shape):
        t, _ = in_shape
        return (t, self.units) if self.return_sequences else (self.units,)

    def apply(self, params, state, x, *, train=False, rng=None):
        u = self.units
        xf = x.astype(jnp.float32)
        # Precompute the input projections for ALL steps as one big GEMM
        # ([b*t, f] @ [f, g*u]) — the scan then only does the [b,u]x[u,g*u]
        # recurrent matmul per step.
        xin = xf @ params["kernel"] + params["bias"]
        xin = jnp.swapaxes(xin, 0, 1)                   # [t, b, g*u]
        b = x.shape[0]
        carry0 = self._carry0(b, u)

        def step(carry, x_t):
            return self._step(params, carry, x_t, u)

        carry, ys = jax.lax.scan(step, carry0, xin)
        if self.return_sequences:
            return jnp.swapaxes(ys, 0, 1).astype(x.dtype), state
        return self._last(carry).astype(x.dtype), state


class LSTM(_Recurrent):
    """Keras-2 LSTM (gate order i, f, c, o; forget bias 1.0)."""

    gates = 4

    def _bias_init(self):
        u = self.units
        return jnp.zeros((4 * u,), jnp.float32).at[u:2 * u].set(1.0)

    def _carry0(self, b, u):
        return (jnp.zeros((b, u), jnp.float32),
                jnp.zeros((b, u), jnp.float32))

    def _step(self, params, carry, x_t, u):
        h, c = carry
        z = x_t + h @ params["recurrent_kernel"]
        i = self.rec_act(z[:, :u])
        f = self.rec_act(z[:, u:2 * u])
        g = self.act(z[:, 2 * u:3 * u])
        o = self.rec_act(z[:, 3 * u:])
        c = f * c + i * g
        h = o * self.act(c)
        return (h, c), h

    def _last(self, carry):
        return carry[0]

    def __repr__(self):
        return f"LSTM({self.units})"


class GRU(_Recurrent):
    """Keras-2 GRU (gate order z, r, h; reset gate applied to the
    recurrent contribution before the candidate, reset_after=False)."""

    gates = 3

    def _carry0(self, b, u):
        return jnp.zeros((b, u), jnp.float32)

    def _step(self, params, carry, x_t, u):
        h = carry
        rk = params["recurrent_kernel"]
        rec_zr = h @ rk[:, :2 * u]
        z = self.rec_act(x_t[:, :u] + rec_zr[:, :u])
        r = self.rec_act(x_t[:, u:2 * u] + rec_zr[:, u:])
        hh = self.act(x_t[:, 2 * u:] + (r * h) @ rk[:, 2 * u:])
        h = z * h + (1.0 - z) * hh
        return h, h

    def _last(self, carry):
        return carry

    def __repr__(self):
        return f"GRU({self.units})"


class Stack(Layer):
    """Serial composition of layers; params/state are name-keyed dicts."""

    def __init__(self, layers: Sequence[Layer], name: Optional[str] = None):
        super().__init__(name)
        self.layers = list(layers)
        # Unique name per layer: "dense", "dense_1", ...
        counts: Dict[str, int] = {}
        self.keys = []
        for layer in self.layers:
            base = layer.name
            n = counts.get(base, 0)
            counts[base] = n + 1
            self.keys.append(base if n == 0 else f"{base}_{n}")

    def get_config(self):
        return dict(layers=[layer_spec(l) for l in self.layers],
                    name=self.name)

    def init(self, key, in_shape):
        params, state = {}, {}
        shape = tuple(in_shape)
        subkeys = jax.random.split(key, max(1, len(self.layers)))
        for sub, name, layer in zip(subkeys, self.keys, self.layers):
            p, s = layer.init(sub, shape)
            if p:
                params[name] = p
            if s:
                state[name] = s
            shape = layer.out_shape(shape)
        return params, state

    def out_shape(self, in_shape):
        shape = tuple(in_shape)
        for layer in self.layers:
            shape = layer.out_shape(shape)
        return shape

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        rngs = (jax.random.split(rng, max(1, len(self.layers)))
                if rng is not None else [None] * len(self.layers))
        for sub_rng, name, layer in zip(rngs, self.keys, self.layers):
            x, s = layer.apply(params.get(name, {}), state.get(name, {}), x,
                               train=train, rng=sub_rng)
            if s:
                new_state[name] = s
        return x, new_state

    def __repr__(self):
        return "Stack(" + ", ".join(repr(l) for l in self.layers) + ")"


def serial(*layers: Layer) -> Stack:
    """stax-style combinator: ``serial(Dense(128, 'relu'), Dropout(0.3), ...)``."""
    return Stack(layers)
