"""Goodput accounting: where every second of a supervised run went.

The reference paper's throughput story totals wall-clock; a distributed
run's wall-clock is only credible *decomposed* — how much was productive
step time vs compile, checkpoint traffic, restart backoff, and the input
pipeline starving the device.  ``GoodputAccountant`` attributes run time
into named buckets at the sites the repo already hooks (the session's
dispatch/checkpoint spans, the supervisor's backoff sleep, the prefetch
handoff, RetraceGuard's trace events) and renders the split three ways:

* ``dttpu_goodput_seconds_total{bucket=...}`` counters on the metrics
  registry (scrape ``rate()`` for a live goodput fraction),
* a Chrome-trace **counter lane** (``ph: "C"``) on the active tracer, so
  the Perfetto timeline shows the cumulative split as a stacked area
  next to the spans it summarizes,
* a per-run :meth:`report` — wall seconds, per-bucket seconds,
  ``goodput_pct = step / wall`` — that bench rows and chaos tests
  assert against.

**Exclusive time.**  Buckets nest (a retrace fires *inside* a step; a
checkpoint restore happens *inside* fault recovery) and naive interval
sums would double-count.  Accounting is a per-thread stack: entering a
nested bucket pauses the enclosing frame's accrual, so each wall-clock
second lands in exactly one bucket and the measured buckets plus the
derived ``other`` remainder sum to wall by construction.

Pure stdlib, same contract as ``obs.trace``: a module-level *active
accountant* (``activate``/``deactivate``/``account``) serves code that
cannot thread a handle through its API (the prefetch generator, the
RetraceGuard patch); with nothing active, ``account()`` returns a cached
no-op context manager — one module-global ``None`` check on the hot
path.  Measured overhead of an active frame is two ``perf_counter``
reads and one lock acquire (~1 µs; docs/OBSERVABILITY.md §Goodput).
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Optional

from . import trace as trace_lib

__all__ = ["BUCKETS", "GoodputAccountant", "activate", "deactivate",
           "active", "activated", "account"]

# The attribution vocabulary.  "other" is derived (wall minus the
# measured buckets), never accrued directly — it is where untracked time
# (hook bodies, host-side glue, Python overhead) shows up, which keeps
# the split honest instead of silently inflating a named bucket.
BUCKETS = ("step", "compile", "checkpoint_save", "checkpoint_restore",
           "restart_backoff", "data_stall", "fault_recovery", "other")

_MEASURED = tuple(b for b in BUCKETS if b != "other")


class GoodputAccountant:
    """Attributes wall-clock into exclusive named buckets.

    Args:
      registry: an ``obs.metrics.Registry`` to export
        ``dttpu_goodput_seconds_total{bucket=}`` counters into
        (``None`` = in-process report only).
      trace_counters: mirror every accrual onto the *active* tracer as a
        Chrome ``"C"`` counter event (no-op when no tracer is active).
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, registry=None, trace_counters: bool = True,
                 clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {b: 0.0 for b in _MEASURED}
        self._tls = threading.local()
        self._started_at: Optional[float] = None
        self._stopped_at: Optional[float] = None
        self.trace_counters = trace_counters
        self._counters = None
        if registry is not None:
            self._counters = {
                b: registry.counter(
                    "dttpu_goodput_seconds_total",
                    "Wall-clock seconds attributed to each goodput "
                    "bucket (exclusive; see docs/OBSERVABILITY.md "
                    "Goodput section).", labels={"bucket": b})
                for b in _MEASURED}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "GoodputAccountant":
        """Stamp the wall-clock origin (idempotent)."""
        if self._started_at is None:
            self._started_at = self._clock()
        return self

    def stop(self) -> "GoodputAccountant":
        """Stamp the wall-clock end; frames still open keep accruing into
        their buckets but the report's wall stops here."""
        if self._stopped_at is None:
            self._stopped_at = self._clock()
        return self

    def __enter__(self) -> "GoodputAccountant":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ------------------------------------------------------------ accrual

    def _stack(self):
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _accrue(self, bucket: str, seconds: float) -> None:
        if seconds <= 0.0:
            return
        with self._lock:
            self._totals[bucket] += seconds
            if self.trace_counters:
                lane = dict(self._totals)
            else:
                lane = None
        if self._counters is not None:
            self._counters[bucket].inc(seconds)
        if lane is not None:
            tracer = trace_lib.active_tracer()
            if tracer is not None and tracer.enabled:
                tracer.add_event({"name": "goodput_seconds", "ph": "C",
                                  "ts": trace_lib.now_us(),
                                  "cat": "goodput", "args": lane})

    def account(self, bucket: str):
        """Context manager attributing its body's wall time to ``bucket``
        (exclusively: an enclosing frame is paused for the duration)."""
        if bucket not in _MEASURED:
            raise ValueError(f"unknown goodput bucket {bucket!r}; "
                             f"choices: {_MEASURED}")
        return _Frame(self, bucket)

    def accrue(self, bucket: str, seconds: float) -> None:
        """Attribute an already-measured duration (no pause semantics —
        for durations measured outside any frame)."""
        if bucket not in _MEASURED:
            raise ValueError(f"unknown goodput bucket {bucket!r}; "
                             f"choices: {_MEASURED}")
        self._accrue(bucket, float(seconds))

    # ------------------------------------------------------------ report

    def wall_seconds(self) -> float:
        if self._started_at is None:
            return 0.0
        end = self._stopped_at if self._stopped_at is not None \
            else self._clock()
        return max(0.0, end - self._started_at)

    def snapshot(self) -> Dict[str, float]:
        """Per-bucket seconds including the derived ``other`` remainder.
        Open frames' in-flight time is NOT included (it accrues on frame
        exit) — call between frames, or after :meth:`stop`."""
        with self._lock:
            out = dict(self._totals)
        wall = self.wall_seconds()
        attributed = sum(out.values())
        out["other"] = max(0.0, wall - attributed)
        return out

    def report(self) -> Dict[str, Any]:
        """The per-run goodput document bench rows embed: wall seconds,
        the bucket split, ``goodput_pct`` (= step/wall), and
        ``coverage_pct`` (measured buckets / wall — how much of the run
        the instrumentation saw; the chaos acceptance asserts the split
        sums to wall within 1%, which holds by construction because
        ``other`` is the remainder)."""
        buckets = self.snapshot()
        wall = self.wall_seconds()
        attributed = sum(v for b, v in buckets.items() if b != "other")
        return {
            "wall_s": round(wall, 6),
            "buckets_s": {b: round(buckets[b], 6) for b in BUCKETS},
            "goodput_pct": round(100.0 * buckets["step"] / wall, 3)
            if wall > 0 else 0.0,
            "coverage_pct": round(100.0 * min(attributed, wall) / wall, 3)
            if wall > 0 else 0.0,
        }


class _Frame:
    """One accounting frame: pauses the enclosing frame on entry, accrues
    its own exclusive time on exit, resumes the parent."""

    __slots__ = ("_acct", "_bucket", "_t0")

    def __init__(self, acct: GoodputAccountant, bucket: str):
        self._acct = acct
        self._bucket = bucket
        self._t0 = 0.0

    def __enter__(self) -> "_Frame":
        acct = self._acct
        now = acct._clock()
        stack = acct._stack()
        if stack:
            parent = stack[-1]
            acct._accrue(parent._bucket, now - parent._t0)
        stack.append(self)
        self._t0 = now
        return self

    def __exit__(self, *exc) -> bool:
        acct = self._acct
        now = acct._clock()
        stack = acct._stack()
        acct._accrue(self._bucket, now - self._t0)
        # tolerate misnested exits (a generator frame GC'd out of order):
        # drop everything above this frame rather than corrupt the stack
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        if stack:
            stack[-1]._t0 = now          # resume the parent's accrual
        return False


class _NullFrame:
    """Cached no-op for the inactive fast path (mirrors trace._NullSpan)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_FRAME = _NullFrame()

# ---------------------------------------------------------------------------
# Active accountant: the process-wide sink for code without a handle
# (data/pipeline.py's prefetch wait, RetraceGuard's trace-time hook).

_ACTIVE: Optional[GoodputAccountant] = None
_ACTIVE_LOCK = threading.Lock()


def activate(acct: GoodputAccountant) -> GoodputAccountant:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = acct
    return acct


def deactivate(acct: Optional[GoodputAccountant] = None) -> None:
    """Clear the active accountant (only if it is ``acct``, when given)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if acct is None or _ACTIVE is acct:
            _ACTIVE = None


def active() -> Optional[GoodputAccountant]:
    return _ACTIVE


def account(bucket: str):
    """Module-level frame: routes to the active accountant, cached no-op
    when nothing is active (one global read on the disabled path)."""
    a = _ACTIVE
    if a is None:
        return _NULL_FRAME
    return a.account(bucket)


@contextlib.contextmanager
def activated(acct: GoodputAccountant):
    """Scoped activation (tests, bench): starts/stops the accountant and
    restores the previously active one."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, acct
    acct.start()
    try:
        yield acct
    finally:
        acct.stop()
        with _ACTIVE_LOCK:
            _ACTIVE = prev
