"""Prometheus-style metrics: counters, gauges, histograms + exposition.

A minimal, thread-safe, pure-stdlib registry whose ``expose()`` renders
the Prometheus text exposition format (version 0.0.4) — what a scraper
expects at ``/metrics`` (served by ``obs.http.MetricsServer``).  No
client library dependency: the format is a dozen lines of spec and the
image must not grow pip packages.

Metric semantics follow Prometheus conventions:

* ``Counter`` — monotonically increasing (``inc``); rates are the
  scraper's job (``rate(dttpu_steps_total[1m])``).
* ``Gauge`` — a value that goes both ways (``set``/``inc``).
* ``Histogram`` — cumulative buckets + ``_sum``/``_count`` samples, so
  quantiles are computable server-side (``histogram_quantile``).

Labels are *static per instance*: ``registry.counter(name, help,
labels={"path": "greedy"})`` — one time series per (name, labels) pair,
get-or-create so independent call sites share the series.  Dynamic
label cardinality is deliberately unsupported (it is also the #1
Prometheus operational foot-gun).

``parse_exposition`` is the inverse (used by the round-trip tests and
by anything that wants to scrape programmatically).
"""
from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "parse_exposition", "render_exposition"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default buckets sized for step/checkpoint durations in seconds.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _format_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    type_name = "untyped"

    def __init__(self, name: str, help_text: str,
                 labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._lock = threading.Lock()

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        raise NotImplementedError


class Counter(_Metric):
    type_name = "counter"

    def __init__(self, name, help_text, labels=()):
        super().__init__(name, help_text, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self):
        with self._lock:
            return [(self.name, self.labels, self._value)]


class Gauge(_Metric):
    type_name = "gauge"

    def __init__(self, name, help_text, labels=()):
        super().__init__(name, help_text, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self):
        with self._lock:
            return [(self.name, self.labels, self._value)]


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(self, name, help_text, labels=(),
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(bs)
        self._counts = [0] * (len(self.buckets) + 1)  # trailing +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Upper bucket bound holding the q-quantile (the same estimate a
        Prometheus ``histogram_quantile`` makes, minus interpolation) —
        handy for in-process reporting without a scraper."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        with self._lock:
            total = self._count
            if total == 0:
                return float("nan")
            rank = q * total
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank and c:
                    return (self.buckets[i] if i < len(self.buckets)
                            else float("inf"))
        return float("inf")

    def samples(self):
        # snapshot under the lock: a concurrent observe() between the
        # bucket walk and the _count read would render an exposition
        # where the +Inf bucket and _count disagree (torn scrape)
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        out = []
        cum = 0
        for i, bound in enumerate(self.buckets):
            cum += counts[i]
            out.append((self.name + "_bucket",
                        self.labels + (("le", _format_value(bound)),),
                        float(cum)))
        cum += counts[-1]
        out.append((self.name + "_bucket", self.labels + (("le", "+Inf"),),
                    float(cum)))
        out.append((self.name + "_sum", self.labels, total_sum))
        out.append((self.name + "_count", self.labels, float(total_count)))
        return out


def _freeze_labels(labels: Optional[Dict[str, str]]
                   ) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Registry:
    """Get-or-create metric registry with text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        # (name, labels) -> metric; name -> (type, help) for consistency
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}
        self._families: Dict[str, Tuple[type, str]] = {}

    def _get_or_create(self, cls, name, help_text, labels, **kw) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        frozen = _freeze_labels(labels)
        key = (name, frozen)
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"{name} already registered as "
                        f"{existing.type_name}, not {cls.type_name}")
                return existing
            fam = self._families.get(name)
            if fam is not None and fam[0] is not cls:
                raise ValueError(f"{name} already registered with type "
                                 f"{fam[0].__name__}")
            metric = cls(name, help_text, frozen, **kw)
            self._metrics[key] = metric
            self._families.setdefault(name, (cls, help_text))
            return metric

    def counter(self, name, help_text="", labels=None) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name, help_text="", labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name, help_text="", labels=None,
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets)

    def get(self, name: str, labels=None) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get((name, _freeze_labels(labels)))

    def expose(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            families: Dict[str, List[_Metric]] = {}
            for (name, _), metric in sorted(self._metrics.items()):
                families.setdefault(name, []).append(metric)
            meta = {name: self._families[name] for name in families}
        lines: List[str] = []
        for name in families:
            cls, help_text = meta[name]
            if help_text:
                lines.append(f"# HELP {name} " +
                             help_text.replace("\\", "\\\\")
                             .replace("\n", "\\n"))
            lines.append(f"# TYPE {name} {cls.type_name}")
            for metric in families[name]:
                for sample_name, labels, value in metric.samples():
                    lines.append(f"{sample_name}{_format_labels(labels)} "
                                 f"{_format_value(value)}")
        return "\n".join(lines) + ("\n" if lines else "")


# The process-default registry (train hooks / serve / bench share it when
# no explicit registry is passed to Telemetry).
REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Exposition parser (round-trip tests, programmatic scraping)

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return float("inf")
    if text == "-Inf":
        return float("-inf")
    return float(text)


# The escape alphabet of the exposition format: label values escape
# backslash, double-quote, and newline; HELP text escapes backslash and
# newline.  Decoding must walk the string ONCE — sequential .replace()
# passes corrupt adjacent escapes (a literal backslash followed by a
# literal n renders as ``\\n`` and a ``\\n -> newline`` pass would eat
# the backslash it just decoded).
_ESCAPE_MAP = {"\\": "\\", '"': '"', "n": "\n"}


def _unescape(value: str) -> str:
    if "\\" not in value:
        return value
    out: List[str] = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            out.append(_ESCAPE_MAP.get(value[i + 1], "\\" + value[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_exposition(text: str) -> Dict[str, Dict]:
    """Parse exposition text into ``{family: {"type", "help", "samples"}}``
    where samples maps ``(sample_name, labels_tuple) -> value``."""
    out: Dict[str, Dict] = {}

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suffix)] if sample_name.endswith(
                suffix) else None
            if base and base in out and out[base]["type"] == "histogram":
                return base
        return sample_name

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            out.setdefault(name, {"type": "untyped", "help": "",
                                  "samples": {}})["help"] = _unescape(
                                      help_text)
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_name = rest.partition(" ")
            out.setdefault(name, {"type": "untyped", "help": "",
                                  "samples": {}})["type"] = type_name.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparsable exposition line: {line!r}")
        labels = tuple(
            (k, _unescape(v))
            for k, v in _LABEL_PAIR_RE.findall(m.group("labels") or ""))
        fam = family_of(m.group("name"))
        entry = out.setdefault(fam, {"type": "untyped", "help": "",
                                     "samples": {}})
        entry["samples"][(m.group("name"), labels)] = _parse_value(
            m.group("value"))
    return out


def render_exposition(families: Dict[str, Dict],
                      extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Inverse of :func:`parse_exposition`: render parsed families back
    to exposition text, optionally stamping ``extra_labels`` onto every
    sample (the federation relabel: ``host=``/``replica=``).  An extra
    label whose key a sample already carries overrides it in place, so
    re-federating an already-labelled exposition stays idempotent.
    ``parse_exposition(render_exposition(parse_exposition(t)))`` equals
    ``parse_exposition(t)`` exactly — including histogram ``+Inf``
    buckets and escaped label values, which is what lets the federation
    endpoint proxy peer registries losslessly."""
    extra = tuple((k, str(v)) for k, v in (extra_labels or {}).items())
    for k, _ in extra:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    lines: List[str] = []
    for fam in families:
        entry = families[fam]
        if entry.get("help"):
            lines.append(f"# HELP {fam} " +
                         entry["help"].replace("\\", "\\\\")
                         .replace("\n", "\\n"))
        lines.append(f"# TYPE {fam} {entry.get('type') or 'untyped'}")
        for (sample_name, labels), value in entry["samples"].items():
            if extra:
                keep = tuple((k, v) for k, v in labels
                             if k not in dict(extra))
                labels = keep + extra
            lines.append(f"{sample_name}{_format_labels(labels)} "
                         f"{_format_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")
