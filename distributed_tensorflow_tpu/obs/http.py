"""Telemetry HTTP endpoint: ``/metrics`` + ``/healthz`` on a daemon thread.

Stdlib ``http.server`` only — the serving tier must not grow
dependencies, and a metrics endpoint that needs a web framework defeats
its own purpose.  ``ThreadingHTTPServer`` so a slow scraper cannot block
a liveness probe; the thread is a daemon so a training process never
hangs on exit because a scraper holds a connection.

* ``GET /metrics`` — Prometheus text exposition from the registry.
* ``GET /healthz`` — JSON health document from ``health_fn`` (default
  ``{"status": "ok"}``); a ``health_fn`` raising marks the replica
  unhealthy (HTTP 503) instead of crashing the server.
* ``GET /statusz`` — JSON *debug* snapshot for a human with ``curl``
  and a wedged process: the active goodput split (``obs.goodput``),
  active-tracer event counts, request-trace ring occupancy, plus
  whatever ``statusz_fn`` contributes (a serving replica passes
  ``Engine.stats()`` through here).  Unlike ``/metrics`` it needs no
  exposition parser, and unlike ``/healthz`` it is allowed to be big.

``port=0`` binds an ephemeral port (tests, multiple replicas per host);
the bound port is ``server.port`` after ``start()``.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .metrics import Registry

__all__ = ["MetricsServer", "default_statusz"]


def default_statusz() -> dict:
    """The process-wide debug snapshot ``/statusz`` serves: whatever the
    module-level obs sinks are currently tracking.  Lazy imports keep
    http importable standalone; every section degrades to absence, so
    the endpoint always answers."""
    from . import critpath as critpath_lib
    from . import goodput as goodput_lib
    from . import reqtrace
    from . import trace as trace_lib
    doc: dict = {}
    acct = goodput_lib.active()
    if acct is not None:
        doc["goodput"] = acct.report()
    led = critpath_lib.active()
    if led is not None:
        # headline interference ratio + the top-K slow-request table
        # (docs/OBSERVABILITY.md §Critical path)
        doc["critpath"] = led.statusz()
    tracer = trace_lib.active_tracer()
    if tracer is not None and tracer.enabled:
        doc["trace"] = {"events": len(tracer.events()),
                        "instant_counts": dict(tracer.instant_counts)}
    doc["reqtrace"] = {"enabled": reqtrace.enabled(),
                       "live": len(reqtrace.live_ids()),
                       "completed_ring": len(reqtrace.completed()),
                       "forensics": len(reqtrace.forensics_log())}
    return doc

log = logging.getLogger(__name__)

_EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background HTTP server exposing a metrics registry + health."""

    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], dict]] = None,
                 statusz_fn: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.host = host
        self.requested_port = int(port)
        self.health_fn = health_fn or (lambda: {"status": "ok"})
        # extra /statusz fields merged OVER the default snapshot (a
        # serving replica contributes Engine.stats() through this)
        self.statusz_fn = statusz_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ server

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stdout noise per scrape
                log.debug("metrics-http: " + fmt, *args)

            def _send(self, code: int, content_type: str,
                      body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.registry.expose().encode("utf-8")
                    self._send(200, _EXPOSITION_CONTENT_TYPE, body)
                elif path == "/healthz":
                    try:
                        doc, code = dict(server.health_fn()), 200
                        if doc.get("status") not in (None, "ok"):
                            code = 503
                    except Exception as e:  # unhealthy, not crashed
                        doc, code = {"status": "error", "error": str(e)}, 503
                    self._send(code, "application/json",
                               json.dumps(doc).encode("utf-8"))
                elif path == "/statusz":
                    try:
                        doc = default_statusz()
                        if server.statusz_fn is not None:
                            doc.update(server.statusz_fn())
                        code = 200
                    except Exception as e:  # debuggable, not crashed
                        doc, code = {"error": str(e)}, 500
                    self._send(code, "application/json",
                               json.dumps(doc, default=str)
                               .encode("utf-8"))
                else:
                    self._send(404, "text/plain; charset=utf-8",
                               b"not found (try /metrics, /healthz, "
                               b"or /statusz)\n")

        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dttpu-metrics-http",
                                        daemon=True)
        self._thread.start()
        log.info("telemetry endpoint at %s (/metrics, /healthz, "
                 "/statusz)", self.url)
        return self

    @property
    def port(self) -> int:
        if self._httpd is None:
            return self.requested_port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None
