"""Perf ledger: an append-only, versioned JSONL history of bench rows.

The repo's performance record used to be loose ``BENCH_r0x.json`` driver
blobs compared by filename convention.  The ledger replaces that with a
durable, queryable file: every ``bench.py`` config appends exactly one
schema-checked row carrying its identity (``run_id``, ``git_sha``, the
backend/mesh fingerprint), the config knobs it ran under, the measured
numbers, the matching ``analytical_*`` statics from the DT4xx cost
model, and the goodput split — so "did tokens/s regress since the sharding
change" is a two-row :func:`delta`, not archaeology.  The committed
``ledger/baseline.jsonl`` carries the CPU-smoke reference points the CI
perf gate (``scripts/perf_gate.py`` + ``obs.sentinel``) checks fresh
rows against.

Durability contract (what the race-harness tests pin):

* **append** is a single ``os.write`` of one complete ``\\n``-terminated
  line on an ``O_APPEND`` fd — concurrent appenders from threads or
  processes never interleave bytes mid-row, so every row parses whole;
* **load** tolerates a torn/corrupt trailing line (a crash mid-append on
  a non-O_APPEND copy, a truncated download): it is skipped with a loud
  warning, never a crash;
* **schema skew** (a row written by a different ``SCHEMA_VERSION``) is
  skipped loudly too — old ledgers stay readable forever, unknown future
  rows never crash an old reader.

Pure stdlib, like everything in ``obs``.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SCHEMA_VERSION", "LedgerSchemaError", "PerfLedger",
           "row_from_bench", "row_field"]

log = logging.getLogger(__name__)

SCHEMA_VERSION = 1

# Required row fields and their types — the append-side contract.  The
# nested dicts (fingerprint / measured / analytical / knobs / goodput)
# stay open-schema: configs measure different things, and the sentinel
# classifies fields by name instead of a closed list.
_REQUIRED = {
    "schema_version": int,
    "run_id": str,
    "git_sha": str,
    "config": str,
    "timestamp": float,
    "fingerprint": dict,
    "measured": dict,
}
_OPTIONAL_DICTS = ("analytical", "knobs", "goodput")


class LedgerSchemaError(ValueError):
    """An append was handed a row that violates the schema."""


def validate_row(row: Dict[str, Any]) -> None:
    """Raise :class:`LedgerSchemaError` if ``row`` is not appendable."""
    if not isinstance(row, dict):
        raise LedgerSchemaError(f"row must be a dict, got {type(row)}")
    for key, typ in _REQUIRED.items():
        if key not in row:
            raise LedgerSchemaError(f"row missing required field {key!r}")
        val = row[key]
        if typ is float and isinstance(val, int):
            continue      # ints are fine where floats are expected
        if not isinstance(val, typ):
            raise LedgerSchemaError(
                f"row field {key!r} must be {typ.__name__}, "
                f"got {type(val).__name__}")
    for key in _OPTIONAL_DICTS:
        if key in row and row[key] is not None \
                and not isinstance(row[key], dict):
            raise LedgerSchemaError(f"row field {key!r} must be a dict "
                                    "when present")
    for key, val in row["measured"].items():
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            raise LedgerSchemaError(
                f"measured[{key!r}] must be a number, "
                f"got {type(val).__name__}")


def row_field(row: Dict[str, Any], field: str) -> Optional[float]:
    """Resolve a numeric field by name: ``measured`` first, then
    ``analytical``, then ``goodput`` (where ``goodput.buckets_s`` keys
    are reachable as ``goodput_<bucket>_s``), then the row top level.
    Returns ``None`` when absent or non-numeric."""
    for section in ("measured", "analytical"):
        d = row.get(section) or {}
        if field in d:
            return _num(d[field])
    gp = row.get("goodput") or {}
    if field in gp:
        return _num(gp[field])
    if field.startswith("goodput_") and field.endswith("_s"):
        bucket = field[len("goodput_"):-len("_s")]
        buckets = gp.get("buckets_s") or {}
        if bucket in buckets:
            return _num(buckets[bucket])
    if field in row:
        return _num(row[field])
    return None


def _num(v: Any) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


class PerfLedger:
    """One JSONL ledger file with atomic appends and tolerant loads."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self.skipped_lines = 0       # load-side diagnostics, last rows()
        self.skipped_versions = 0

    # ------------------------------------------------------------ append

    def append(self, row: Dict[str, Any]) -> Dict[str, Any]:
        """Validate and append one row; returns the row as written
        (with ``schema_version``/``timestamp`` stamped if absent).

        One ``os.write`` on an ``O_APPEND`` fd: POSIX serializes the
        offset update with the write, so concurrent appenders (threads
        or processes) produce whole interleaved LINES, never interleaved
        bytes — the property the race-harness test pins."""
        if not isinstance(row, dict):
            raise LedgerSchemaError(f"row must be a dict, got {type(row)}")
        row = dict(row)
        row.setdefault("schema_version", SCHEMA_VERSION)
        row.setdefault("timestamp", time.time())
        validate_row(row)
        data = (json.dumps(row, sort_keys=True,
                           separators=(",", ":")) + "\n").encode("utf-8")
        if "\n" in data[:-1].decode("utf-8"):
            raise LedgerSchemaError("row serialized to multiple lines")
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd = os.open(self.path,
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        return row

    # -------------------------------------------------------------- load

    def rows(self) -> List[Dict[str, Any]]:
        """All readable rows of this reader's schema version, oldest
        first.  Corrupt lines (torn trailing write, truncation) and rows
        from a different ``schema_version`` are skipped with a warning —
        loudly, never a crash (counts land in ``skipped_lines`` /
        ``skipped_versions``)."""
        skipped_lines = skipped_versions = 0
        out: List[Dict[str, Any]] = []
        if os.path.exists(self.path):
            with open(self.path, "r", encoding="utf-8",
                      errors="replace") as f:
                for lineno, line in enumerate(f, start=1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                        if not isinstance(row, dict):
                            raise ValueError("row is not an object")
                    except ValueError as e:
                        skipped_lines += 1
                        log.warning("ledger %s:%d: skipping corrupt line "
                                    "(%s)", self.path, lineno, e)
                        continue
                    if row.get("schema_version") != SCHEMA_VERSION:
                        skipped_versions += 1
                        log.warning(
                            "ledger %s:%d: skipping row with schema_"
                            "version=%r (this reader speaks %d)",
                            self.path, lineno,
                            row.get("schema_version"), SCHEMA_VERSION)
                        continue
                    out.append(row)
        with self._lock:
            self.skipped_lines = skipped_lines
            self.skipped_versions = skipped_versions
        return out

    # ------------------------------------------------------------- query

    def latest(self, config: str,
               backend: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Newest row for ``config`` (and ``backend``, when given —
        matched against ``fingerprint.backend``)."""
        best: Optional[Dict[str, Any]] = None
        for row in self.rows():
            if row.get("config") != config:
                continue
            if backend is not None and \
                    (row.get("fingerprint") or {}).get("backend") != backend:
                continue
            if best is None or row.get("timestamp", 0) >= \
                    best.get("timestamp", 0):
                best = row
        return best

    def series(self, field: str, config: Optional[str] = None,
               backend: Optional[str] = None
               ) -> List[Tuple[float, float]]:
        """``(timestamp, value)`` points for one field across history —
        the trajectory plot ROADMAP item 3's autotuner reads."""
        out: List[Tuple[float, float]] = []
        for row in self.rows():
            if config is not None and row.get("config") != config:
                continue
            if backend is not None and \
                    (row.get("fingerprint") or {}).get("backend") != backend:
                continue
            v = row_field(row, field)
            if v is not None:
                out.append((float(row.get("timestamp", 0.0)), v))
        out.sort(key=lambda tv: tv[0])
        return out

    @staticmethod
    def delta(row: Dict[str, Any], baseline: Dict[str, Any]
              ) -> Dict[str, Dict[str, float]]:
        """Per-field comparison of two rows over their shared measured
        fields: ``{field: {"measured", "baseline", "ratio"}}`` (ratio
        measured/baseline; baseline 0 yields ``inf``/``nan`` honestly)."""
        out: Dict[str, Dict[str, float]] = {}
        m = row.get("measured") or {}
        for fieldname in sorted(m):
            a = _num(m[fieldname])
            b = row_field(baseline, fieldname)
            if a is None or b is None:
                continue
            ratio = a / b if b else (float("inf") if a > 0 else
                                     float("nan"))
            out[fieldname] = {"measured": a, "baseline": b,
                              "ratio": ratio}
        return out


# ---------------------------------------------------------------------------
# bench.py integration: one stamped result line -> one ledger row.

# bench result fields that are identity/bookkeeping, not measurements
_NON_MEASURED = {"schema_version", "run_id", "git_sha", "timestamp",
                 "config", "fingerprint", "goodput"}


def row_from_bench(result: Dict[str, Any],
                   knobs: Optional[Dict[str, str]] = None
                   ) -> Dict[str, Any]:
    """Build a ledger row from a stamped ``bench.py`` result line:
    numeric fields split into ``measured`` vs ``analytical_*`` statics,
    identity fields lifted to the top level, ``DTTPU_*`` env knobs
    recorded (captured from the environment when not given)."""
    measured: Dict[str, float] = {}
    analytical: Dict[str, float] = {}
    for key, val in result.items():
        if key in _NON_MEASURED:
            continue
        n = _num(val)
        if n is None:
            continue
        (analytical if key.startswith("analytical_") else
         measured)[key] = n
    if knobs is None:
        knobs = {k: v for k, v in sorted(os.environ.items())
                 if k.startswith("DTTPU_")}
    return {
        "schema_version": int(result.get("schema_version",
                                         SCHEMA_VERSION)),
        "run_id": str(result.get("run_id", "")),
        "git_sha": str(result.get("git_sha", "")),
        "config": str(result.get("config", result.get("metric", ""))),
        "timestamp": float(result.get("timestamp", time.time())),
        "fingerprint": dict(result.get("fingerprint") or {}),
        "measured": measured,
        "analytical": analytical,
        "knobs": dict(knobs),
        "goodput": result.get("goodput"),
    }
