"""obs — the unified telemetry layer (tracing, metrics, device health).

One subsystem shared by training, serving, and bench, three pillars:

* **tracing** (``obs.trace``) — a Chrome-trace-event/Perfetto JSON span
  recorder for the host timeline: ``span("data_load")`` /
  ``span("dispatch")`` / ``span("checkpoint")`` plus instant events for
  jit compiles and retraces (``analysis.sanitizer.RetraceGuard`` emits
  them into the active tracer, arg-diff included).
* **metrics** (``obs.metrics`` + ``obs.http``) — a Prometheus-style
  counter/gauge/histogram registry with text exposition, served at
  ``/metrics`` (+ ``/healthz``) by a daemon-thread stdlib HTTP server.
* **device health** (``obs.device``) — in-graph grad-norm/nonfinite
  accumulators that ride the step's existing metrics dict (no extra
  device->host syncs), and host-side ``jax.live_arrays`` byte totals.

``Telemetry`` is the façade that wires the pillars together and plugs
into ``train.TrainSession(telemetry=...)`` with the ``TraceHook`` /
``MetricsExportHook`` pair (train/hooks.py)::

    from distributed_tensorflow_tpu import obs, train

    tele = obs.Telemetry(trace_dir=logdir, metrics_port=9100)
    with train.TrainSession(state, step, telemetry=tele,
                            hooks=[train.TraceHook(tele),
                                   train.MetricsExportHook(tele),
                                   train.StopAtStepHook(1000)]) as sess:
        ...
    tele.close()     # writes trace-host0.json, stops the endpoint

Everything here is pure stdlib (``obs.device`` imports JAX lazily
inside its functions); disabled telemetry costs one attribute check per
step.  See docs/OBSERVABILITY.md for span names, the metric catalog,
and measured overhead.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from . import (critpath, device, federate, goodput, http, ledger, metrics,
               reqtrace, sentinel, trace)
from .critpath import CritpathLedger
from .federate import FederatedMetrics, RemoteAffinity
from .goodput import GoodputAccountant
from .http import MetricsServer
from .ledger import PerfLedger
from .metrics import (Counter, Gauge, Histogram, Registry, REGISTRY,
                      parse_exposition, render_exposition)
from .sentinel import Sentinel
from .trace import Tracer

__all__ = ["Telemetry", "Tracer", "MetricsServer", "Registry", "REGISTRY",
           "Counter", "Gauge", "Histogram", "CritpathLedger",
           "FederatedMetrics", "GoodputAccountant", "PerfLedger",
           "RemoteAffinity",
           "Sentinel", "parse_exposition", "render_exposition",
           "critpath", "device", "federate", "goodput", "http", "ledger",
           "metrics", "reqtrace", "sentinel", "trace"]


class Telemetry:
    """Bundle of one Tracer + one metrics Registry + one HTTP endpoint.

    Args:
      trace_dir: where to write the per-host Chrome trace JSON
        (``trace-host{i}.json``); ``None`` disables tracing (the tracer
        stays wired but records nothing).
      metrics_port: serve ``/metrics`` + ``/healthz`` on this port
        (``0`` = ephemeral, read ``telemetry.server.port`` after
        ``start()``); ``None`` disables the endpoint (the registry still
        collects — bench reads it in-process).
      registry: share an existing Registry (default: a fresh one, so two
        Telemetry objects in one process never mix series).
      host_index: the multi-host process index used as the trace "pid"
        and the trace filename suffix.  Default reads the ``PROCESS_ID``
        env var (the cluster-bootstrap convention, parallel/cluster.py)
        — deliberately NOT ``jax.process_index()``, which would
        force-initialize the backend at telemetry construction; pass it
        explicitly after ``jax.distributed`` init when you have it.
      service: label reported by ``/healthz`` ("train", "serve", ...).
      health_fn: extra health fields merged into the ``/healthz`` doc.
      statusz_fn: extra debug fields merged into the ``/statusz``
        snapshot (a serving replica passes ``Engine.stats()`` here).
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 registry: Optional[Registry] = None,
                 host_index: Optional[int] = None,
                 service: str = "train",
                 health_fn: Optional[Callable[[], Dict]] = None,
                 statusz_fn: Optional[Callable[[], Dict]] = None):
        if host_index is None:
            try:
                host_index = int(os.environ.get("PROCESS_ID", "0"))
            except ValueError:
                host_index = 0
        self.host_index = host_index
        self.trace_dir = trace_dir
        self.service = service
        self.health_fn = health_fn
        self.statusz_fn = statusz_fn
        self.tracer = Tracer(enabled=trace_dir is not None, pid=host_index)
        self.registry = registry if registry is not None else Registry()
        self.server: Optional[MetricsServer] = None
        if metrics_port is not None:
            self.server = MetricsServer(self.registry, port=metrics_port,
                                        health_fn=self._health,
                                        statusz_fn=self._statusz)
        self._started = False
        self._closed = False

    # ------------------------------------------------------------ health

    def _health(self) -> Dict:
        doc: Dict = {"status": "ok", "service": self.service,
                     "host_index": self.host_index}
        steps = self.registry.get("dttpu_steps_total")
        if steps is not None:
            doc["steps_total"] = steps.value
        if self.health_fn is not None:
            doc.update(self.health_fn())
        return doc

    def _statusz(self) -> Dict:
        """Identity fields + the caller's extras; merged over
        ``http.default_statusz()`` by the endpoint."""
        doc: Dict = {"service": self.service,
                     "host_index": self.host_index}
        if self.statusz_fn is not None:
            doc.update(self.statusz_fn())
        return doc

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Telemetry":
        """Idempotent: activate the tracer as the process-wide sink (so
        RetraceGuard retrace instants land here) and bring up the HTTP
        endpoint.  Hooks call this from ``begin`` — explicit calls are
        only needed outside a TrainSession."""
        if self._started:
            return self
        self._started = True
        if self.tracer.enabled:
            trace.activate(self.tracer)
        if self.server is not None:
            self.server.start()
        return self

    @property
    def trace_path(self) -> Optional[str]:
        if self.trace_dir is None:
            return None
        return os.path.join(self.trace_dir,
                            f"trace-host{self.host_index}.json")

    def save_trace(self) -> Optional[str]:
        """Write the trace file (call as often as you like; the file is
        rewritten whole each time)."""
        path = self.trace_path
        if path is None or not self.tracer.enabled:
            return None
        return self.tracer.save(path)

    def close(self) -> None:
        """Write the trace, deactivate the tracer, stop the endpoint."""
        if self._closed:
            return
        self._closed = True
        self.save_trace()
        trace.deactivate(self.tracer)
        if self.server is not None:
            self.server.stop()

    def __enter__(self) -> "Telemetry":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------- shared instruments

    def checkpoint_seconds(self) -> Histogram:
        return self.registry.histogram(
            "dttpu_checkpoint_save_seconds",
            "Wall-clock duration of TrainSession.save() calls.")

    def metrics_url(self) -> Optional[str]:
        if self.server is None:
            return None
        return self.server.url + "/metrics"
