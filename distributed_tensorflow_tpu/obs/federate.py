"""Fleet metrics federation: N registries, one ``/metrics``.

ROADMAP item 7(b): a 2-host fleet cannot be scraped from one dashboard
— each replica's ``Registry`` (and each host's ``MetricsServer``) is
its own scrape target, and nothing carries the ``host``/``replica``
identity a fleet-wide query needs.  :class:`FederatedMetrics` is that
missing aggregation point, with two kinds of source:

* **in-process registries** (``add_registry(reg, replica="3")``) — the
  per-replica registries the router wires up; read directly, no HTTP;
* **scraped peers** (``add_scrape(url, host="1")``) — other hosts'
  ``/metrics`` endpoints, fetched at expose time and decoded with
  ``obs.metrics.parse_exposition`` (whose escape/``+Inf`` round-trip
  exactness is what makes this proxying lossless).

``expose()`` merges every source into one exposition, stamping each
source's labels (``host=``/``replica=``) onto its samples — the
Prometheus federation convention — and appends the federation's OWN
series: per-tenant TTFT/TPOT percentile gauges and SLO attainment
(``dttpu_slo_*``, docs/OBSERVABILITY.md §Federation) fed from the
autoscaler pipeline's streaming verdicts via :meth:`ingest`.

Serve it with the stock endpoint — ``MetricsServer`` only needs an
object with ``expose()``:

    fed = FederatedMetrics()
    fed.add_registry(replica_reg, replica="0")
    fed.add_scrape("http://peer:9100/metrics", host="1")
    server = fed.serve(port=9100)       # one scrape target for the fleet

Thread-safe: sources and SLO state mutate under one lock; the scrape
fan-out runs OUTSIDE it, so a slow peer never blocks ``ingest`` (peers
get ``timeout_s`` each, and a failed scrape bumps
``dttpu_federation_scrape_errors_total`` instead of failing the whole
exposition).
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from . import metrics as metrics_lib
from .http import MetricsServer

__all__ = ["FederatedMetrics", "RemoteAffinity"]


@dataclasses.dataclass(frozen=True)
class RemoteAffinity:
    """One remote engine's prefix-affinity inputs, recovered from its
    federated metrics: the radix chain fingerprint (chain hash ->
    cached tokens) and the page size it chunks prompts by.  Shaped so
    ``fleet.router.expected_pages_reused(prompt, remote)`` scores it
    exactly like a local ``EngineStats`` — cross-HOST routers read
    affinity from the scrape plane instead of in-process stats."""
    page_size: int
    prefix_fingerprint: Dict[bytes, int]

# Streaming percentile state is a bounded reservoir per tenant: serving
# percentiles care about the recent tail, and an unbounded list on a
# million-request sim run is a leak, not a statistic.
_RESERVOIR = 4096


def _pct(xs: List[float], q: float) -> float:
    return xs[int(q * (len(xs) - 1))]


class FederatedMetrics:
    """See the module docstring."""

    def __init__(self, registry: Optional[metrics_lib.Registry] = None,
                 timeout_s: float = 2.0):
        self._lock = threading.Lock()
        self._registries: List[Tuple[Dict[str, str],
                                     metrics_lib.Registry]] = []
        self._scrapes: List[Tuple[Dict[str, str], str]] = []
        self.timeout_s = float(timeout_s)
        # the federation's own series (dttpu_slo_* + scrape health) live
        # in a normal Registry so they render/parse like everything else
        self.registry = (registry if registry is not None
                         else metrics_lib.Registry())
        self._slo: Dict[str, Dict[str, Any]] = {}
        self._g_sources = self.registry.gauge(
            "dttpu_federation_sources",
            "Registries plus scrape targets behind this federation "
            "endpoint.")
        self._c_scrape_errors = self.registry.counter(
            "dttpu_federation_scrape_errors_total",
            "Peer scrapes that failed (timeout, refused, unparsable) "
            "and were skipped in the merged exposition.")
        self._gauges: Dict[Tuple[str, str], metrics_lib.Gauge] = {}

    # ---------------------------------------------------------- sources

    def add_registry(self, registry: metrics_lib.Registry,
                     **labels: str) -> "FederatedMetrics":
        """Aggregate an in-process registry; ``labels`` (conventionally
        ``replica=``) stamp every one of its samples."""
        with self._lock:
            self._registries.append(
                ({k: str(v) for k, v in labels.items()}, registry))
        return self

    def add_scrape(self, url: str, **labels: str) -> "FederatedMetrics":
        """Aggregate a peer ``/metrics`` endpoint by URL; ``labels``
        (conventionally ``host=``) stamp its samples."""
        with self._lock:
            self._scrapes.append(
                ({k: str(v) for k, v in labels.items()}, url))
        return self

    def source_count(self) -> int:
        """Sources behind this endpoint: registries + scrape targets
        + the federation's own registry."""
        with self._lock:
            return len(self._registries) + len(self._scrapes) + 1

    # ------------------------------------------------------- SLO intake

    def ingest(self, tenant: str, ttft_s: Optional[float] = None,
               tpot_s: Optional[float] = None,
               ttft_ok: Optional[bool] = None,
               itl_ok: Optional[bool] = None) -> None:
        """One request's streaming SLO evidence, per tenant — the same
        verdicts the autoscaler's ``record`` consumes, plus the raw
        latencies the percentile gauges need.  ``fleet.sim.SimMetrics``
        forwards here when a federation is wired in."""
        with self._lock:
            st = self._slo.get(tenant)
            if st is None:
                st = {"ttft": collections.deque(maxlen=_RESERVOIR),
                      "tpot": collections.deque(maxlen=_RESERVOIR),
                      "ok": 0, "n": 0}
                self._slo[tenant] = st
            if ttft_s is not None:
                st["ttft"].append(float(ttft_s))
            if tpot_s is not None:
                st["tpot"].append(float(tpot_s))
            for verdict in (ttft_ok, itl_ok):
                if verdict is not None:
                    st["n"] += 1
                    if verdict:
                        st["ok"] += 1

    def _slo_gauge(self, name: str, help_text: str,
                   tenant: str) -> metrics_lib.Gauge:
        # under _lock: expose() and fleet_fingerprints() both land here
        key = (name, tenant)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self.registry.gauge(name, help_text,
                                        labels={"tenant": tenant})
                self._gauges[key] = g
        return g

    def _refresh_slo(self) -> None:
        with self._lock:
            snap = {t: (sorted(st["ttft"]), sorted(st["tpot"]),
                        st["ok"], st["n"])
                    for t, st in self._slo.items()}
        for tenant, (ttft, tpot, ok, n) in snap.items():
            if ttft:
                self._slo_gauge(
                    "dttpu_slo_ttft_p50_seconds",
                    "Per-tenant TTFT p50 over the federation's "
                    "streaming reservoir.", tenant).set(_pct(ttft, 0.50))
                self._slo_gauge(
                    "dttpu_slo_ttft_p99_seconds",
                    "Per-tenant TTFT p99 over the federation's "
                    "streaming reservoir.", tenant).set(_pct(ttft, 0.99))
            if tpot:
                self._slo_gauge(
                    "dttpu_slo_tpot_p50_seconds",
                    "Per-tenant mean inter-token gap p50 (per request) "
                    "over the streaming reservoir.",
                    tenant).set(_pct(tpot, 0.50))
                self._slo_gauge(
                    "dttpu_slo_tpot_p99_seconds",
                    "Per-tenant mean inter-token gap p99 (per request) "
                    "over the streaming reservoir.",
                    tenant).set(_pct(tpot, 0.99))
            if n:
                self._slo_gauge(
                    "dttpu_slo_attainment",
                    "Per-tenant fraction of SLO verdicts met (TTFT and "
                    "inter-token pooled).", tenant).set(ok / n)

    # ----------------------------------------------------------- expose

    def _fetch(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8")

    @staticmethod
    def _merge(merged: Dict[str, Dict], families: Dict[str, Dict],
               labels: Dict[str, str]) -> None:
        extra = tuple(labels.items())
        for name, fam in families.items():
            tgt = merged.setdefault(
                name, {"type": "untyped", "help": "", "samples": {}})
            if tgt["type"] == "untyped":
                tgt["type"] = fam["type"]
            if not tgt["help"]:
                tgt["help"] = fam["help"]
            for (sname, lbls), value in fam["samples"].items():
                if extra:
                    lbls = tuple((k, v) for k, v in lbls
                                 if k not in labels) + extra
                tgt["samples"][(sname, lbls)] = value

    def expose(self) -> str:
        """One exposition for the whole fleet: every source's families
        merged (source labels stamped per sample, one HELP/TYPE header
        per family) plus the federation's own ``dttpu_slo_*`` and
        scrape-health series.  Duck-types ``Registry.expose`` so
        ``MetricsServer`` serves it unmodified."""
        self._refresh_slo()
        with self._lock:
            registries = list(self._registries)
            scrapes = list(self._scrapes)
        self._g_sources.set(len(registries) + len(scrapes) + 1)
        merged: Dict[str, Dict] = {}
        for labels, reg in registries:
            self._merge(merged,
                        metrics_lib.parse_exposition(reg.expose()),
                        labels)
        for labels, url in scrapes:
            try:
                text = self._fetch(url)
                families = metrics_lib.parse_exposition(text)
            except Exception:
                self._c_scrape_errors.inc()
                continue
            self._merge(merged, families, labels)
        # own registry LAST: the scrape-health counters must reflect
        # THIS pass's failures, not lag one exposition behind
        self._merge(merged,
                    metrics_lib.parse_exposition(self.registry.expose()),
                    {})
        return metrics_lib.render_exposition(merged)

    def fleet_fingerprints(self) -> Dict[Tuple[Tuple[str, str], ...],
                                         RemoteAffinity]:
        """Recover every source engine's prefix fingerprint from the
        merged exposition: ``dttpu_serve_prefix_chain_tokens{chain=..}``
        samples grouped by their non-``chain`` labels (the source
        stamp — ``host=``/``replica=`` — plus any tenant labels), with
        ``dttpu_serve_page_size`` matched on the same key.  Returns
        ``{source label tuple: RemoteAffinity}``; chains rendered 0
        (evicted on the engine) are dropped, and sources publishing no
        page size (contiguous engines) score affinity 0 downstream.

        This is the cross-host half of prefix-affinity routing
        (fleet/router.py): the serve tier renders the pool fingerprint
        as labeled gauges (serve/engine.py ``ServeMetrics``), the
        federation merges them across hosts, and a router on ANY host
        scores placements from this one scrape surface."""
        families = metrics_lib.parse_exposition(self.expose())
        fps: Dict[Tuple[Tuple[str, str], ...], Dict[bytes, int]] = {}
        sizes: Dict[Tuple[Tuple[str, str], ...], int] = {}
        fam = families.get("dttpu_serve_prefix_chain_tokens")
        for (_sname, lbls), value in ((fam or {}).get("samples")
                                      or {}).items():
            chain_hex = dict(lbls).get("chain")
            if not chain_hex or value <= 0:
                continue          # evicted chain renders 0: not cached
            try:
                chain = bytes.fromhex(chain_hex)
            except ValueError:
                continue
            src = tuple(sorted((k, v) for k, v in lbls
                               if k != "chain"))
            fps.setdefault(src, {})[chain] = int(value)
        fam = families.get("dttpu_serve_page_size")
        for (_sname, lbls), value in ((fam or {}).get("samples")
                                      or {}).items():
            src = tuple(sorted(lbls))
            sizes[src] = int(value)
        return {src: RemoteAffinity(page_size=sizes.get(src, 0),
                                    prefix_fingerprint=fp)
                for src, fp in fps.items()}

    def serve(self, port: int = 0, host: str = "127.0.0.1",
              health_fn=None) -> MetricsServer:
        """Start a ``MetricsServer`` over this federation (``port=0``
        binds an ephemeral port; the caller owns ``stop()``)."""
        return MetricsServer(self, port=port, host=host,
                             health_fn=health_fn).start()
