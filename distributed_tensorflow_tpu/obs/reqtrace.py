"""Request-scoped distributed tracing: one async lane per request.

``obs.trace`` records *host* timelines — what each process did, when.
This module records *request* timelines: a serve request that queues on
one replica, migrates twice, and finishes on a third is one story, and
it should render as ONE lane in Perfetto, not three disconnected
fragments.  The Dapper-style recipe:

* a **trace id** is minted once, at the front door (``Router.submit`` /
  ``Engine.submit``), and carried on ``Request`` and — across live
  migration — ``RequestSnapshot``;
* the scheduler emits **lifecycle stages** as Chrome-trace async events
  (``ph: "b"/"n"/"e"``, ``cat: "request"``, ``id: <trace id>``):
  ``request`` (the whole lane) wrapping ``queued`` → ``prefill`` →
  ``decode`` stage spans, with ``admitted`` / ``prefill_window`` /
  ``first_token`` instants riding the lane (``"n"``).  Async events
  with one (cat, id) pair share a track, whatever pid emitted them —
  that is what stitches a migrated request back together;
* export → import is linked by **flow arrows** (``ph: "s"``/``"f"``,
  ``cat: "migration"``, same id), so the hop itself is an edge in the
  rendered graph;
* every completed request's span record lands in a **bounded ring**,
  and the tail-latency forensics hook (``forensic_dump``) — called by
  the fleet watchdog on quarantine and by the scheduler on deadline
  expiry — snapshots the victim's span tree while the evidence is
  still warm.

Emission routes through the module-level active tracer
(``obs.trace.activate``); with no tracer active, ``mint`` returns
``None`` and every carrier skips the calls entirely — the tracing-off
path costs one attribute check per request, not per event.  All state
lives behind one module lock; the per-event cost is a few dict/list
operations (the serve bench pins the measured overhead under 2%,
docs/OBSERVABILITY.md §Request tracing).

Timestamps default to the host tracer clock (``trace.now_us``) but
every function takes ``ts_us=`` so the fleet simulator can emit the
same vocabulary on *virtual* time (sampled; ``fleet/sim.py``).
"""
from __future__ import annotations

import collections
import os
import threading
from typing import Any, Dict, List, Optional

from . import trace as trace_lib

__all__ = ["mint", "enabled", "configure", "reset",
           "submitted", "stage", "mark", "exported", "imported",
           "retired", "tree", "lookup", "live_ids", "completed",
           "forensic_dump", "forensics_log",
           "CAT", "FLOW_CAT"]

CAT = "request"          # async-lane category: one track per trace id
FLOW_CAT = "migration"   # flow-arrow category: export -> import edges

_lock = threading.Lock()
_seq = 0
_enabled = True
_live: Dict[str, Dict[str, Any]] = {}
_ring: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=256)
_forensics: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=64)


def configure(enabled: Optional[bool] = None,
              ring: Optional[int] = None,
              forensics: Optional[int] = None) -> None:
    """Adjust the module switches: ``enabled`` gates minting (the bench
    uses it for the tracing-off arm), ``ring``/``forensics`` resize the
    bounded completed-trace and dump buffers (existing entries kept,
    newest-first, up to the new capacity)."""
    global _enabled, _ring, _forensics
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if ring is not None:
            _ring = collections.deque(_ring, maxlen=int(ring))
        if forensics is not None:
            _forensics = collections.deque(_forensics,
                                           maxlen=int(forensics))


def reset() -> None:
    """Drop all live records, the ring, the forensics log, and re-enable
    minting (test isolation)."""
    global _enabled, _seq
    with _lock:
        _enabled = True
        _seq = 0
        _live.clear()
        _ring.clear()
        _forensics.clear()


def enabled() -> bool:
    """True when minting is on AND a tracer is active — the condition
    under which carriers get trace ids at the front door."""
    return _enabled and trace_lib.active_tracer() is not None


def mint(prefix: str = "req") -> Optional[str]:
    """A fresh trace id, or None when tracing is off.  Ids embed the OS
    pid so two hosts' mints never collide in a merged trace."""
    global _seq
    if not enabled():
        return None
    with _lock:
        _seq += 1
        return f"{prefix}-{os.getpid():x}-{_seq:06x}"


# ------------------------------------------------------------------ emit

def _record(trace_id: str) -> Dict[str, Any]:
    rec = _live.get(trace_id)
    if rec is None:
        rec = {"trace_id": trace_id, "events": [], "open": [],
               "hops": 0, "status": None}
        _live[trace_id] = rec
    return rec


def _emit(trace_id: str, ph: str, name: str, cat: str,
          ts_us: Optional[float], args: Dict[str, Any]) -> None:
    ev: Dict[str, Any] = {
        "name": name, "ph": ph, "cat": cat, "id": trace_id,
        "ts": trace_lib.now_us() if ts_us is None else float(ts_us)}
    if ph == "s":
        # flow starts may outlive the emitting scope; bind at enclosing
        ev["bp"] = "e"
    if args:
        ev["args"] = args
    rec = _record(trace_id)
    rec["events"].append(ev)
    t = trace_lib.active_tracer()
    if t is not None:
        t.add_event(dict(ev))


def _close_open_stage(trace_id: str, ts_us: Optional[float]) -> None:
    rec = _record(trace_id)
    if rec["open"]:
        _emit(trace_id, "e", rec["open"].pop(), CAT, ts_us, {})


# ------------------------------------------------------- lifecycle spans

def submitted(trace_id: str, ts_us: Optional[float] = None,
              **args: Any) -> None:
    """Open the request lane (async ``b`` for ``request``) and its first
    stage, ``queued``.  Call once, where the request enters a scheduler
    for the first time; a migrated arrival goes through ``imported``."""
    with _lock:
        rec = _record(trace_id)
        _emit(trace_id, "b", "request", CAT, ts_us, args)
        _emit(trace_id, "b", "queued", CAT, ts_us, {})
        rec["open"].append("queued")


def stage(trace_id: str, name: str, ts_us: Optional[float] = None,
          **args: Any) -> None:
    """Close the currently open stage span and open ``name`` — the
    scheduler's queued→prefill→decode progression."""
    with _lock:
        _close_open_stage(trace_id, ts_us)
        _emit(trace_id, "b", name, CAT, ts_us, args)
        _record(trace_id)["open"].append(name)


def mark(trace_id: str, name: str, ts_us: Optional[float] = None,
         **args: Any) -> None:
    """An instant riding the request lane (async ``n``): ``admitted``,
    ``prefill_window``, ``first_token``."""
    with _lock:
        _emit(trace_id, "n", name, CAT, ts_us, args)


def exported(trace_id: str, ts_us: Optional[float] = None,
             **args: Any) -> None:
    """The request leaves this replica as a snapshot: close the open
    stage, mark the hop, and start a flow arrow (``s``) the importing
    side will finish."""
    with _lock:
        _close_open_stage(trace_id, ts_us)
        _emit(trace_id, "n", "exported", CAT, ts_us, args)
        _emit(trace_id, "s", "migrate", FLOW_CAT, ts_us, {})


def imported(trace_id: str, ts_us: Optional[float] = None,
             **args: Any) -> None:
    """The snapshot lands on a destination replica: finish the flow
    arrow (``f``), mark the hop, and re-open ``queued`` — the SAME
    async id, so Perfetto renders one contiguous lane."""
    with _lock:
        rec = _record(trace_id)
        rec["hops"] += 1
        _emit(trace_id, "f", "migrate", FLOW_CAT, ts_us, {})
        _emit(trace_id, "n", "imported", CAT, ts_us, args)
        _emit(trace_id, "b", "queued", CAT, ts_us, {})
        rec["open"].append("queued")


def retired(trace_id: str, status: str, ts_us: Optional[float] = None,
            **args: Any) -> None:
    """Terminal: close any open stage, end the request lane (``e``)
    with the retirement status, and move the record into the completed
    ring.  A ``migrated`` retirement is NOT terminal for the lane — the
    importing replica continues it — so only the stage closes."""
    with _lock:
        if status == "migrated":
            # exported() already closed the stage and started the flow
            return
        _close_open_stage(trace_id, ts_us)
        all_args = dict(args)
        all_args["status"] = status
        _emit(trace_id, "e", "request", CAT, ts_us, all_args)
        rec = _live.pop(trace_id, None)
        if rec is not None:
            rec["status"] = status
            _ring.append(rec)


# ------------------------------------------------------------ forensics

def lookup(trace_id: str) -> Optional[Dict[str, Any]]:
    """The raw span record for a live or ring-resident trace."""
    with _lock:
        rec = _live.get(trace_id)
        if rec is None:
            for r in reversed(_ring):
                if r["trace_id"] == trace_id:
                    rec = r
                    break
        return None if rec is None else {
            "trace_id": rec["trace_id"], "events": list(rec["events"]),
            "hops": rec["hops"], "status": rec["status"]}


def live_ids() -> List[str]:
    with _lock:
        return list(_live)


def completed() -> List[Dict[str, Any]]:
    """Snapshot of the bounded completed-trace ring, oldest first."""
    with _lock:
        return [{"trace_id": r["trace_id"], "events": list(r["events"]),
                 "hops": r["hops"], "status": r["status"]}
                for r in _ring]


def tree(trace_id: str) -> Optional[Dict[str, Any]]:
    """Fold a trace's async events into a nested span tree:
    ``{"trace_id", "status", "hops", "spans": [...]}`` where each span
    is ``{"name", "start_us", "end_us", "args", "marks", "children"}``.
    Spans still open (a live victim) carry ``end_us: None``."""
    rec = lookup(trace_id)
    if rec is None:
        return None
    roots: List[Dict[str, Any]] = []
    stack: List[Dict[str, Any]] = []
    for ev in rec["events"]:
        if ev.get("cat") != CAT:
            continue
        if ev["ph"] == "b":
            node = {"name": ev["name"], "start_us": ev["ts"],
                    "end_us": None, "args": ev.get("args", {}),
                    "marks": [], "children": []}
            (stack[-1]["children"] if stack else roots).append(node)
            stack.append(node)
        elif ev["ph"] == "e":
            for i in range(len(stack) - 1, -1, -1):
                if stack[i]["name"] == ev["name"]:
                    stack[i]["end_us"] = ev["ts"]
                    if ev.get("args"):
                        stack[i]["args"].update(ev["args"])
                    del stack[i:]
                    break
        elif ev["ph"] == "n":
            target = stack[-1] if stack else None
            entry = {"name": ev["name"], "ts_us": ev["ts"],
                     "args": ev.get("args", {})}
            if target is None:
                roots.append(dict(entry, marks=[], children=[],
                                  start_us=ev["ts"], end_us=ev["ts"]))
            else:
                target["marks"].append(entry)
    return {"trace_id": trace_id, "status": rec["status"],
            "hops": rec["hops"], "spans": roots}


def forensic_dump(trace_id: str, reason: str,
                  **context: Any) -> Optional[Dict[str, Any]]:
    """Snapshot a victim's span tree into the forensics log (bounded)
    and onto the host timeline as a ``forensics`` instant.  Returns the
    tree, or None for an unknown id.  Callers: the fleet watchdog at
    quarantine, the scheduler at deadline expiry."""
    t = tree(trace_id)
    if t is None:
        return None
    entry = dict(t, reason=reason, context=context)
    with _lock:
        _forensics.append(entry)
    tracer = trace_lib.active_tracer()
    if tracer is not None:
        tracer.instant("forensics", trace_id=trace_id, reason=reason,
                       **context)
    return entry


def forensics_log() -> List[Dict[str, Any]]:
    with _lock:
        return list(_forensics)
