"""Host-timeline tracing: a Chrome-trace-event / Perfetto JSON recorder.

The reference's observability story ends at ``tf.summary`` scalars; a
production run needs to answer "where did the step time go" without a
debugger.  This module records *host-side* spans — ``span("data_load")``,
``span("dispatch")``, ``span("checkpoint")`` — and instant events (jit
compiles/retraces, session lifecycle marks) into the Chrome trace-event
JSON format, so one step of a training run opens in ``chrome://tracing``
or https://ui.perfetto.dev as a timeline.

Pure stdlib, zero JAX dependency: spans time the HOST, which is exactly
the honest thing to time under async dispatch (a span around a jitted
call measures dispatch; the completion barrier is wherever the caller
fetches a value — see dtlint rule DT107 for the anti-pattern this
prevents).  Recording a span is two ``perf_counter_ns`` reads and a
``list.append`` under a lock (~1 µs); a disabled tracer's ``span()``
returns a cached no-op context manager.

Multi-host: every process writes its own file, but events carry the
JAX process index as the Chrome ``pid`` (plus a ``process_name``
metadata record naming the host and OS pid), so concatenating the
per-host ``traceEvents`` lists — or loading the files together in
Perfetto — merges the hosts into one timeline with one row group per
host.

Module-level *active tracer*: ``activate(tracer)`` makes a tracer the
process-wide sink for code that cannot thread a handle through its API
(``analysis.sanitizer.RetraceGuard`` emits retrace instants this way).
``instant(...)``/``span(...)`` module functions route to it and no-op
when nothing is active.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Tracer", "activate", "activated", "deactivate",
           "active_tracer", "span", "instant", "now_us"]

# perf_counter_ns is monotonic but has an arbitrary epoch; anchor it once
# so ts values are comparable across tracers in one process.
_EPOCH_NS = time.perf_counter_ns()


class _NullSpan:
    """Cached no-op context manager for the disabled-tracer fast path."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def now_us() -> float:
    """Microseconds on the tracer clock (monotonic, process-anchored) —
    for callers recording retroactive spans via ``Tracer.add_span``."""
    return (time.perf_counter_ns() - _EPOCH_NS) / 1e3


class Tracer:
    """Collects Chrome trace events in memory; ``save()`` writes JSON.

    Args:
      enabled: a disabled tracer's record methods are no-ops (cheap to
        leave wired in).
      pid: the Chrome "process" lane — conventionally the multi-host
        process index so per-host files merge into one timeline.
      host: human label for the process lane ("host0"); defaults to
        ``host{pid}``.
    """

    def __init__(self, enabled: bool = True, pid: int = 0,
                 host: Optional[str] = None):
        self.enabled = enabled
        self.pid = int(pid)
        self.host = host or f"host{self.pid}"
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self.instant_counts: Dict[str, int] = {}
        self._add_metadata()

    # ------------------------------------------------------------ record

    def _add_metadata(self) -> None:
        # ph "M" metadata records name the process lane; the OS pid rides
        # along so a merged multi-host timeline still identifies processes.
        self._events.append({
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": f"{self.host} (os pid {os.getpid()})"}})

    _now_us = staticmethod(now_us)

    def span(self, name: str, **args: Any):
        """Context manager recording a complete ("X") event around its body."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def add_span(self, name: str, start_us: float, end_us: float,
                 **args: Any) -> None:
        """Record an already-measured span (retroactive; TraceHook uses it
        for the inter-step host gap)."""
        if not self.enabled:
            return
        event = {"name": name, "ph": "X", "ts": start_us,
                 "dur": max(0.0, end_us - start_us), "pid": self.pid,
                 "tid": threading.get_ident() & 0xFFFFFFFF, "cat": "host"}
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    def add_event(self, event: Dict[str, Any]) -> None:
        """Record a raw Chrome trace event (async ``b``/``n``/``e``
        lifecycle phases, flow ``s``/``f`` arrows — shapes the typed
        helpers above don't cover; ``obs.reqtrace`` is the producer).
        The caller supplies ``ts``/``ph``/``cat``/``id``; ``pid`` and
        ``tid`` default to this tracer's lane and the calling thread."""
        if not self.enabled:
            return
        event.setdefault("pid", self.pid)
        event.setdefault("tid", threading.get_ident() & 0xFFFFFFFF)
        with self._lock:
            self._events.append(event)

    def instant(self, name: str, **args: Any) -> None:
        """Record an instant ("i") event — compiles, retraces, marks."""
        if not self.enabled:
            return
        event = {"name": name, "ph": "i", "s": "p", "ts": self._now_us(),
                 "pid": self.pid,
                 "tid": threading.get_ident() & 0xFFFFFFFF, "cat": "host"}
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)
            self.instant_counts[name] = self.instant_counts.get(name, 0) + 1

    # ------------------------------------------------------------ output

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_dict(self) -> Dict[str, Any]:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)
        return path


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: Tracer, name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = Tracer._now_us()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer.add_span(self._name, self._t0, Tracer._now_us(),
                              **self._args)
        return False


# ---------------------------------------------------------------------------
# Active tracer: the process-wide sink for code without a handle.

_ACTIVE: Optional[Tracer] = None
_ACTIVE_LOCK = threading.Lock()


def activate(tracer: Tracer) -> Tracer:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = tracer
    return tracer


def deactivate(tracer: Optional[Tracer] = None) -> None:
    """Clear the active tracer (only if it is ``tracer``, when given)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if tracer is None or _ACTIVE is tracer:
            _ACTIVE = None


def active_tracer() -> Optional[Tracer]:
    return _ACTIVE


def instant(name: str, **args: Any) -> None:
    t = _ACTIVE
    if t is not None:
        t.instant(name, **args)


def span(name: str, **args: Any):
    t = _ACTIVE
    if t is None:
        return _NULL_SPAN
    return t.span(name, **args)


@contextlib.contextmanager
def activated(tracer: Tracer):
    """Scoped activation (tests, bench): restores the previous tracer."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, tracer
    try:
        yield tracer
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = prev
