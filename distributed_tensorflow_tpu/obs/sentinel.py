"""Regression sentinel: judges a fresh bench row against history + roofline.

The join of the perf ledger (``obs.ledger``) and the DT4xx static cost
model: for one fresh bench row it runs two independent checks —

* **history drift** — every shared measured field (tokens/s, step
  p50/p95, TTFT, ...) is compared against the baseline row by ratio,
  with direction inferred from the field name (throughput-like fields
  regress by falling, latency-like fields by rising) and per-field
  tolerances generous enough for CI-runner jitter by default;
* **roofline drift** — measured MFU falling away from the program's own
  ``analytical_mfu`` ceiling flags a perf bug even with *no* history
  (a fresh config, a wiped ledger): the ceiling was computed from the
  same traced program the lint gate checks, so the gap is implementation
  quality, not model error.

The ``analytical_comm_*`` fields (the DT5xx static communication
ledger bench.py stamps per config) get a special, TIGHT tolerance:
they are computed, not measured, so they carry zero run-to-run jitter —
a config's static comm volume only moves when the *program* moves.
Growth past ``DEFAULT_COMM_MAX_RATIO`` reds the gate (an accidental
extra all-gather in a refactor), overridable per field like any other
tolerance.

Verdicts export as ``dttpu_sentinel_*`` metrics and render as a human
report; ``scripts/perf_gate.py`` turns them into an exit code, which is
what the CI perf-gate job runs.  Pure stdlib.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from . import ledger as ledger_lib

__all__ = ["Tolerance", "Verdict", "Sentinel", "classify_field",
           "parse_tolerance_overrides", "DEFAULT_MIN_RATIO",
           "DEFAULT_MAX_RATIO", "DEFAULT_COMM_MAX_RATIO",
           "DEFAULT_INTERFERENCE_MAX_RATIO",
           "DEFAULT_FLEET_HIT_RATE_MIN_RATIO",
           "DEFAULT_ROOFLINE_FLOOR"]

# CI-jitter-sized defaults: a shared runner's smoke bench wobbles tens
# of percent run-to-run, so the gate only fires on ~2x movements — the
# injected-regression test slows the hot path ~2.5x to clear this with
# margin (see ISSUE acceptance).  Per-field overrides tighten where a
# number is known-stable.
DEFAULT_MIN_RATIO = 0.5      # higher-is-better: fail below half baseline
DEFAULT_MAX_RATIO = 2.0      # lower-is-better: fail above twice baseline
DEFAULT_ROOFLINE_FLOOR = 0.01  # measured mfu / analytical_mfu floor

# Static (computed) fields don't jitter: the comm ledger may only grow
# past rounding noise when the traced program itself changed.  The 1.2
# slack tolerates a deliberately grown batch/seq in the same config row.
DEFAULT_COMM_MAX_RATIO = 1.2

# Prefix of the DT5xx static-communication fields bench.py stamps.
_COMM_PREFIX = "analytical_comm"

# Critical-path interference (obs/critpath.py): the fraction of a
# request's e2e spent stretched by OTHER requests' prefill windows —
# regression direction is UP (a scheduling change that worsens
# head-of-line blocking).  A share is a seeded-workload ratio, not a
# raw latency, so it jitters less than the wall-clock fields — the gate
# is tighter than DEFAULT_MAX_RATIO but looser than the computed comm
# ledger's, and per-field overridable like everything else.
_INTERFERENCE_TOKEN = "interference_share"
DEFAULT_INTERFERENCE_MAX_RATIO = 1.5

# Fleet-wide radix hit rate of the prefix-affinity ablation
# (bench.py fleet_sim): a DETERMINISTIC virtual-time number — same
# seeded trace, same placement replay — so run-to-run jitter is zero
# and the gate can sit tight.  Direction is higher-is-better
# ("hit_rate" already classifies so); the 0.9 floor only tolerates a
# deliberate retuning of the ablation trace, not a placement-policy
# regression (losing affinity drops the rate ~15%).
_FLEET_HIT_RATE_TOKEN = "fleet_prefix_hit_rate"
DEFAULT_FLEET_HIT_RATE_MIN_RATIO = 0.9

# Name-based direction inference: duration suffixes are matched at the
# END of the name (a bare "_s" substring would misread "single_step_*"),
# the rest by substring.  Unknown fields are SKIPPED, not guessed — a
# gate that misreads a direction flags improvements as regressions.
_LOWER_SUFFIXES = ("_ms", "_us", "_seconds", "_s")
_LOWER_TOKENS = ("latency", "ttft", "p50", "p95", "p99", "stall",
                 "retrace_warnings", "undercount")
_HIGHER_TOKENS = ("per_sec", "per_chip", "tokens_s", "throughput",
                  "mfu", "goodput", "accuracy", "value", "hit_rate")


def classify_field(field: str) -> Optional[str]:
    """``"higher"`` / ``"lower"`` (is better) / ``None`` = don't gate."""
    name = field.lower()
    if _COMM_PREFIX in name:     # static comm volume: growth is drift
        return "lower"
    for token in _LOWER_TOKENS:
        if token in name:
            return "lower"
    if any(name.endswith(suffix) for suffix in _LOWER_SUFFIXES):
        return "lower"
    for token in _HIGHER_TOKENS:
        if token in name:
            return "higher"
    return None


@dataclasses.dataclass(frozen=True)
class Tolerance:
    """Per-field gate bounds on the measured/reference ratio."""
    min_ratio: float = DEFAULT_MIN_RATIO
    max_ratio: float = DEFAULT_MAX_RATIO


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One field's judgement.  ``ok=False`` names the regression."""
    field: str
    kind: str                    # "history" | "roofline"
    measured: float
    reference: float
    ratio: float
    ok: bool
    detail: str

    @property
    def delta_pct(self) -> float:
        return 100.0 * (self.ratio - 1.0)


class Sentinel:
    """Stateless checker; construct with overrides, call :meth:`check`.

    Args:
      tolerances: per-field :class:`Tolerance` overrides (field name ->
        Tolerance), on top of the jitter-sized defaults.
      roofline_floor: minimum acceptable measured-mfu / analytical-mfu.
      registry: an ``obs.metrics.Registry`` to export verdict counts
        into (``None`` = report only).
    """

    def __init__(self,
                 tolerances: Optional[Dict[str, Tolerance]] = None,
                 roofline_floor: float = DEFAULT_ROOFLINE_FLOOR,
                 registry=None):
        self.tolerances = dict(tolerances or {})
        self.roofline_floor = float(roofline_floor)
        self._checks = self._regressions = None
        self._registry = registry
        if registry is not None:
            self._checks = registry.counter(
                "dttpu_sentinel_checks_total",
                "Fields the regression sentinel judged.")
            self._regressions = registry.counter(
                "dttpu_sentinel_regressions_total",
                "Fields the regression sentinel flagged as regressed.")

    def _tol(self, field: str) -> Tolerance:
        tol = self.tolerances.get(field)
        if tol is not None:
            return tol
        if _COMM_PREFIX in field.lower():
            return Tolerance(max_ratio=DEFAULT_COMM_MAX_RATIO)
        if _INTERFERENCE_TOKEN in field.lower():
            return Tolerance(max_ratio=DEFAULT_INTERFERENCE_MAX_RATIO)
        if _FLEET_HIT_RATE_TOKEN in field.lower():
            return Tolerance(
                min_ratio=DEFAULT_FLEET_HIT_RATE_MIN_RATIO)
        return Tolerance()

    # ------------------------------------------------------------- check

    def check(self, row: Dict[str, Any],
              baseline: Optional[Dict[str, Any]] = None
              ) -> List[Verdict]:
        """Judge one ledger row: history drift vs ``baseline`` (when
        given) + roofline drift from the row's own statics.  Returns
        every verdict, regressions first."""
        verdicts: List[Verdict] = []
        if baseline is not None:
            verdicts.extend(self._check_history(row, baseline))
            verdicts.extend(self._check_comm(row, baseline))
        verdicts.extend(self._check_roofline(row))
        verdicts.sort(key=lambda v: v.ok)
        if self._checks is not None:
            self._checks.inc(len(verdicts))
            bad = sum(1 for v in verdicts if not v.ok)
            if bad:
                self._regressions.inc(bad)
        if self._registry is not None:
            self._registry.gauge(
                "dttpu_sentinel_verdict",
                "1 when the last sentinel check passed, 0 when it "
                "flagged a regression.",
                labels={"config": str(row.get("config", ""))}).set(
                    0.0 if any(not v.ok for v in verdicts) else 1.0)
        return verdicts

    def _check_history(self, row, baseline) -> List[Verdict]:
        out: List[Verdict] = []
        for field, d in ledger_lib.PerfLedger.delta(row, baseline).items():
            direction = classify_field(field)
            if direction is None:
                continue
            measured, ref, ratio = d["measured"], d["baseline"], d["ratio"]
            tol = self._tol(field)
            if direction == "higher":
                ok = ratio >= tol.min_ratio
                bound = (f"min_ratio {tol.min_ratio:g}")
            else:
                # a zero-latency baseline gates nothing: any positive
                # measurement would be an infinite-ratio false alarm
                ok = (ratio <= tol.max_ratio) or ref == 0
                bound = (f"max_ratio {tol.max_ratio:g}")
            out.append(Verdict(
                field=field, kind="history", measured=measured,
                reference=ref, ratio=ratio, ok=ok,
                detail=(f"{field}: {measured:g} vs baseline {ref:g} "
                        f"({100 * (ratio - 1):+.1f}%, {direction} is "
                        f"better, {bound})")))
        return out

    def _check_comm(self, row, baseline) -> List[Verdict]:
        """Static comm drift: the ``analytical_comm_*`` fields live in
        the row's *analytical* section (``PerfLedger.delta`` only walks
        measured fields), so they get their own pass — same ratio gate,
        but against the tight comm tolerance, because a computed number
        that moved means the traced program's collectives moved."""
        out: List[Verdict] = []
        a = row.get("analytical") or {}
        for field in sorted(a):
            if _COMM_PREFIX not in field.lower():
                continue
            measured = ledger_lib.row_field(row, field)
            ref = ledger_lib.row_field(baseline, field)
            if measured is None or ref is None:
                continue
            ratio = (measured / ref if ref
                     else (float("inf") if measured > 0 else 1.0))
            tol = self._tol(field)
            ok = (ratio <= tol.max_ratio) or ref == 0
            out.append(Verdict(
                field=field, kind="comm", measured=measured,
                reference=ref, ratio=ratio, ok=ok,
                detail=(f"{field}: static {measured:g} vs baseline "
                        f"{ref:g} ({100 * (ratio - 1):+.1f}%, computed "
                        f"— program changed if this moved, max_ratio "
                        f"{tol.max_ratio:g})")))
        return out

    def _check_roofline(self, row) -> List[Verdict]:
        measured = ledger_lib.row_field(row, "mfu")
        ceiling = ledger_lib.row_field(row, "analytical_mfu")
        if measured is None or ceiling is None or ceiling <= 0:
            return []
        ratio = measured / ceiling
        return [Verdict(
            field="mfu_vs_roofline", kind="roofline", measured=measured,
            reference=ceiling, ratio=ratio, ok=ratio >= self.roofline_floor,
            detail=(f"mfu {measured:g} is {100 * ratio:.2f}% of the "
                    f"analytical ceiling {ceiling:g} "
                    f"(floor {100 * self.roofline_floor:g}%)"))]

    # ------------------------------------------------------------ report

    @staticmethod
    def report(verdicts: List[Verdict],
               row: Optional[Dict[str, Any]] = None) -> str:
        """Human-readable verdict table (regressions first)."""
        lines: List[str] = []
        if row is not None:
            fp = row.get("fingerprint") or {}
            lines.append(
                f"perf sentinel: config={row.get('config')} "
                f"run={row.get('run_id')} sha={row.get('git_sha')} "
                f"backend={fp.get('backend')}x{fp.get('device_count')}")
        if not verdicts:
            lines.append("no gateable fields (nothing shared with the "
                         "baseline, no roofline statics)")
        for v in verdicts:
            mark = "ok  " if v.ok else "FAIL"
            lines.append(f"  [{mark}] ({v.kind}) {v.detail}")
        bad = [v for v in verdicts if not v.ok]
        lines.append(f"verdict: {'REGRESSED' if bad else 'pass'} "
                     f"({len(verdicts)} checks, {len(bad)} regressions)")
        return "\n".join(lines)


def parse_tolerance_overrides(specs: List[str]) -> Dict[str, Tolerance]:
    """CLI helper: ``field=min:max`` specs (either side empty keeps the
    default) -> a tolerances dict for :class:`Sentinel`."""
    out: Dict[str, Tolerance] = {}
    for spec in specs:
        field, _, bounds = spec.partition("=")
        if not field or "=" not in spec:
            raise ValueError(f"bad tolerance spec {spec!r}; "
                             "expected field=min:max")
        lo, _, hi = bounds.partition(":")
        out[field] = Tolerance(
            min_ratio=float(lo) if lo else DEFAULT_MIN_RATIO,
            max_ratio=float(hi) if hi else DEFAULT_MAX_RATIO)
    return out
