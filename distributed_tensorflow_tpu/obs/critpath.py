"""Per-request critical-path ledger: where each request's latency went.

ROADMAP item 4 claims the engine's mixed prefill/decode tick causes
head-of-line blocking — one long prompt inflating every co-scheduled
tenant's inter-token latency — and proposes disaggregation to fix it.
This module turns that claim into a measurement.  It is the goodput
invariant (PR 15: exclusive buckets summing to wall by construction)
applied *per request*: every retired request's end-to-end wall time
decomposes into exclusive phases

* ``queue_wait`` — submit until the admission that started its prefill,
* ``prefill_compute`` — its own prefill windows' wall time,
* ``prefill_interference`` — the HOL signal: time this request's decode
  ticks were stretched by *other* requests' prefill windows sharing the
  tick (each co-scheduled decode slot is charged the tick's
  other-requests' window cost in full — every slot experiences the
  stretch in parallel, exactly as the fleet simulator prices it),
* ``decode_compute`` — its decode ticks' wall time minus interference,
* ``migration`` — export-to-import gap when the request moved engines,
* ``backpressure_requeue`` — re-queued wait after an admission bounce
  (adapter table / page pool exhaustion),
* derived ``other`` — the unattributed remainder (host glue, stream
  flush), never accrued directly, so the split stays honest.

The scheduler accrues into a plain per-request dict at its existing
transition seams (the same places reqtrace hooks) and calls
:func:`finalize` + :func:`observe` exactly once at retirement (the
claim-once ``_retire_accounting`` guarantee).  The finished breakdown
rides the request handle, the reqtrace retirement mark, and — through
this ledger — per-tenant phase histograms, a bounded worst-K slow
request reservoir (full breakdown + trace_id for Perfetto lookup),
``dttpu_critpath_seconds_total{phase,tenant}`` /
``dttpu_critpath_interference_ratio`` on /metrics, a ``/statusz``
top-K table, and a Chrome-trace counter lane.

Same activation contract as ``obs.goodput``: a module-level *active
ledger* (``activate``/``deactivate``/``activated``); with nothing
active, :func:`new_phases` returns ``None`` and the scheduler's
accrual sites reduce to one attribute check — the serve hot path pays
nothing when critpath accounting is off.  Pure stdlib.
"""
from __future__ import annotations

import contextlib
import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import trace as trace_lib

__all__ = ["PHASES", "CritpathLedger", "new_phases", "finalize",
           "activate", "deactivate", "active", "activated", "observe"]

# The attribution vocabulary.  "other" is derived (e2e minus the
# measured phases), never accrued directly — untracked host time shows
# up there instead of silently inflating a named phase.
PHASES = ("queue_wait", "prefill_compute", "prefill_interference",
          "decode_compute", "migration", "backpressure_requeue", "other")

_MEASURED = PHASES[:-1]

# log-spaced per-phase histogram edges (seconds): serve latencies span
# sub-ms decode ticks to multi-second queue waits
HIST_EDGES_S = (0.0001, 0.001, 0.01, 0.1, 1.0, 10.0, 100.0)


def new_phases() -> Optional[Dict[str, float]]:
    """A zeroed accrual dict for one request — or ``None`` when no
    ledger is active (the scheduler's disabled fast path: accrual sites
    gate on the request's ``phases is None``)."""
    if _ACTIVE is None:
        return None
    return {p: 0.0 for p in _MEASURED}


def finalize(phases: Dict[str, float], e2e_s: float) -> Dict[str, float]:
    """Close one request's accrual dict into the finished breakdown:
    a COPY with the derived ``other`` remainder, the measured ``e2e_s``,
    and ``interference_share``.  Phases sum to ``e2e_s`` by construction
    (every accrued interval is disjoint and inside [submit, finish], so
    the remainder is nonnegative up to clock granularity — the property
    test's tolerance)."""
    out = {p: float(phases.get(p, 0.0)) for p in _MEASURED}
    e2e = max(float(e2e_s), 0.0)
    out["other"] = max(0.0, e2e - sum(out.values()))
    out["e2e_s"] = e2e
    out["interference_share"] = (
        out["prefill_interference"] / e2e if e2e > 0.0 else 0.0)
    return out


def _pct(ordered: List[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending list (stdlib —
    no numpy in obs/)."""
    n = len(ordered)
    if n == 0:
        return 0.0
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


class CritpathLedger:
    """Aggregates finished per-request breakdowns.

    Args:
      registry: an ``obs.metrics.Registry`` to export
        ``dttpu_critpath_seconds_total{phase,tenant}`` counters and the
        ``dttpu_critpath_interference_ratio`` gauge into (``None`` =
        in-process report only).
      worst_k: slow-request exemplars kept (min-heap on e2e — full
        breakdown + trace_id, the Perfetto lookup key).
      reservoir: bounded per-request interference-share sample count;
        past the cap, sample ``i`` overwrites slot ``i % cap``
        (deterministic — no randomness, so seeded runs reproduce).
      trace_counters: mirror cumulative phase totals onto the active
        tracer as a Chrome ``"C"`` counter lane.
    """

    def __init__(self, registry=None, worst_k: int = 8,
                 reservoir: int = 4096, trace_counters: bool = True,
                 clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self.worst_k = int(worst_k)
        self._reservoir_cap = max(1, int(reservoir))
        self._count = 0
        self._totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._e2e_total = 0.0
        self._tenants: Dict[str, Dict[str, float]] = {}
        self._tenant_counts: Dict[str, int] = {}
        # per-(tenant, phase) log-bucket histogram: len(edges)+1 counts
        self._hist: Dict[str, Dict[str, List[int]]] = {}
        self._worst: List[Tuple[float, int, Dict[str, Any]]] = []
        self._shares: List[float] = []
        self.trace_counters = trace_counters
        self._registry = registry
        self._counters: Dict[Tuple[str, str], Any] = {}
        self._ratio_gauge = None
        if registry is not None:
            self._ratio_gauge = registry.gauge(
                "dttpu_critpath_interference_ratio",
                "Cumulative prefill_interference seconds over cumulative "
                "request e2e seconds — the fleet-wide head-of-line "
                "blocking fraction (docs/OBSERVABILITY.md Critical "
                "path).")

    # ------------------------------------------------------------ observe

    def _counter(self, phase: str, tenant: str):
        """Lazy ``{phase,tenant}`` counter (serve tenants are an open
        set, same pattern as ServeMetrics' tenant counters).  Caller
        holds ``_lock``."""
        key = (phase, tenant)
        c = self._counters.get(key)
        if c is None:
            c = self._counters[key] = self._registry.counter(
                "dttpu_critpath_seconds_total",
                "Wall-clock seconds attributed to each critical-path "
                "phase, per tenant (exclusive; 'other' is report-only, "
                "as with goodput).",
                labels={"phase": phase, "tenant": tenant})
        return c

    def observe(self, tenant: Optional[str],
                breakdown: Dict[str, float],
                trace_id: Optional[str] = None,
                ts_us: Optional[int] = None) -> None:
        """Fold one :func:`finalize`\\ d breakdown into the aggregates.
        Called once per retired request (the scheduler's claim-once
        retirement path); thread-safe."""
        tenant = tenant or "default"
        e2e = float(breakdown.get("e2e_s", 0.0))
        share = float(breakdown.get("interference_share", 0.0))
        with self._lock:
            self._count += 1
            seq = self._count
            per = self._tenants.setdefault(
                tenant, {p: 0.0 for p in PHASES})
            hist = self._hist.setdefault(
                tenant, {p: [0] * (len(HIST_EDGES_S) + 1)
                         for p in _MEASURED})
            for p in PHASES:
                v = float(breakdown.get(p, 0.0))
                self._totals[p] += v
                per[p] += v
                if p != "other":
                    b = 0
                    while b < len(HIST_EDGES_S) and v > HIST_EDGES_S[b]:
                        b += 1
                    hist[p][b] += 1
                    if self._registry is not None and v > 0.0:
                        self._counter(p, tenant).inc(v)
            self._tenant_counts[tenant] = \
                self._tenant_counts.get(tenant, 0) + 1
            self._e2e_total += e2e
            entry = dict(breakdown)
            entry["tenant"] = tenant
            if trace_id is not None:
                entry["trace_id"] = trace_id
            heapq.heappush(self._worst, (e2e, seq, entry))
            if len(self._worst) > self.worst_k:
                heapq.heappop(self._worst)
            if len(self._shares) < self._reservoir_cap:
                self._shares.append(share)
            else:
                self._shares[seq % self._reservoir_cap] = share
            interf_total = self._totals["prefill_interference"]
            e2e_total = self._e2e_total
            lane = dict(self._totals) if self.trace_counters else None
        if self._ratio_gauge is not None:
            self._ratio_gauge.set(
                interf_total / e2e_total if e2e_total > 0.0 else 0.0)
        if lane is not None:
            tracer = trace_lib.active_tracer()
            if tracer is not None and tracer.enabled:
                tracer.add_event({
                    "name": "critpath_seconds", "ph": "C",
                    "ts": trace_lib.now_us() if ts_us is None else ts_us,
                    "cat": "critpath", "args": lane})

    # ------------------------------------------------------------ report

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative phase totals + request count (cheap, lock-bounded)."""
        with self._lock:
            return {"requests": self._count,
                    "phase_seconds": dict(self._totals),
                    "e2e_seconds": self._e2e_total}

    def interference_shares(self) -> List[float]:
        """A copy of the bounded per-request interference-share samples."""
        with self._lock:
            return list(self._shares)

    def worst(self) -> List[Dict[str, Any]]:
        """The worst-K exemplars, slowest first (full breakdown each)."""
        with self._lock:
            ranked = sorted(self._worst, key=lambda t: (-t[0], t[1]))
        return [dict(entry) for _, _, entry in ranked]

    def report(self) -> Dict[str, Any]:
        """The per-run critpath document bench rows and the CI artifact
        embed: request count, the fleet phase split, the per-tenant
        phase table (totals + log-bucket histograms), the
        interference-share distribution, and the worst-K exemplars."""
        with self._lock:
            count = self._count
            totals = dict(self._totals)
            e2e_total = self._e2e_total
            per_tenant = {
                t: {"requests": self._tenant_counts.get(t, 0),
                    "phase_seconds": {p: round(v, 6)
                                      for p, v in per.items()},
                    "phase_hist": {p: list(h)
                                   for p, h in self._hist[t].items()}}
                for t, per in sorted(self._tenants.items())}
            shares = sorted(self._shares)
        worst = self.worst()
        return {
            "requests": count,
            "phase_seconds": {p: round(totals[p], 6) for p in PHASES},
            "e2e_seconds": round(e2e_total, 6),
            "interference_ratio": round(
                totals["prefill_interference"] / e2e_total, 6)
            if e2e_total > 0.0 else 0.0,
            "interference_share_p50": round(_pct(shares, 50.0), 6),
            "interference_share_p95": round(_pct(shares, 95.0), 6),
            "hist_edges_s": list(HIST_EDGES_S),
            "per_tenant": per_tenant,
            "worst": worst,
        }

    def statusz(self) -> Dict[str, Any]:
        """The compact ``/statusz`` section: headline ratio + the top-K
        slow-request table (one row per exemplar, phases rounded)."""
        snap = self.snapshot()
        e2e = snap["e2e_seconds"]
        rows = [{
            "trace_id": e.get("trace_id"),
            "tenant": e.get("tenant"),
            "e2e_s": round(e.get("e2e_s", 0.0), 4),
            "interference_share": round(
                e.get("interference_share", 0.0), 4),
            "phases_s": {p: round(e.get(p, 0.0), 4) for p in PHASES},
        } for e in self.worst()]
        return {"requests": snap["requests"],
                "interference_ratio": round(
                    snap["phase_seconds"]["prefill_interference"] / e2e,
                    6) if e2e > 0.0 else 0.0,
                "slowest": rows}


# ---------------------------------------------------------------------------
# Active ledger: the process-wide sink the scheduler accrues into.  Same
# contract as goodput's active accountant — the scheduler cannot thread
# a handle through Request objects that migrate between engines.

_ACTIVE: Optional[CritpathLedger] = None
_ACTIVE_LOCK = threading.Lock()


def activate(led: CritpathLedger) -> CritpathLedger:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = led
    return led


def deactivate(led: Optional[CritpathLedger] = None) -> None:
    """Clear the active ledger (only if it is ``led``, when given)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if led is None or _ACTIVE is led:
            _ACTIVE = None


def active() -> Optional[CritpathLedger]:
    return _ACTIVE


def observe(tenant: Optional[str], breakdown: Dict[str, float],
            trace_id: Optional[str] = None,
            ts_us: Optional[int] = None) -> None:
    """Module-level observe: routes to the active ledger, no-op when
    nothing is active.  The scheduler still attaches the breakdown to
    the request handle either way — aggregation is what's optional."""
    led = _ACTIVE
    if led is not None:
        led.observe(tenant, breakdown, trace_id=trace_id, ts_us=ts_us)


@contextlib.contextmanager
def activated(led: CritpathLedger):
    """Scoped activation (tests, bench): restores the previous ledger."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, led
    try:
        yield led
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = prev
