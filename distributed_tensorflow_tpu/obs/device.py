"""Device-health accumulators: in-graph signals + host memory totals.

The in-graph half computes replica-health scalars *inside* the compiled
step and returns them through the metrics dict the step already emits —
the same contract ``train/hooks.py`` documents: hooks that don't fire
never pull a value, so the hot loop stays async-dispatch clean and the
health signals cost two small reductions fused into the step's XLA
program (no extra device->host syncs, no extra dispatches).

* ``grad_health(grads)`` — global gradient L2 norm + the count of
  non-finite gradient elements.  A rising ``grad_norm`` gauge is the
  earliest divergence tell; a nonzero ``nonfinite_grads`` pinpoints the
  step an overflow started (NaNHook then tells you when the *loss* went
  bad — usually later).
* ``tree_bytes(tree)`` — in-graph-free static accounting of a pytree's
  device footprint.

The host half — ``live_arrays_bytes()`` — totals ``jax.live_arrays()``
buffer sizes: the "is this replica leaking device memory" gauge that
``MetricsExportHook`` exports.  It walks a host-side list (no device
sync) but the list can be long, so it runs at hook cadence, never
per step.

JAX imports are local to each function: the obs package stays importable
(and the trace/metrics/http pillars fully usable) on machines without
JAX.
"""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["grad_health", "tree_bytes", "live_arrays_bytes",
           "GRAD_NORM_KEY", "NONFINITE_KEY"]

# Metric-dict keys the train-step builders emit and MetricsExportHook
# recognizes — one name, three layers.
GRAD_NORM_KEY = "grad_norm"
NONFINITE_KEY = "nonfinite_grads"


def grad_health(grads: Any) -> Dict[str, Any]:
    """In-graph gradient health: ``{grad_norm, nonfinite_grads}``.

    Call inside a (possibly jitted) step function on the gradient pytree
    and merge the result into the step's metrics dict.  Norm is computed
    in f32 whatever the gradient dtype (bf16 squares overflow at ~2e19).
    """
    import jax
    import jax.numpy as jnp

    leaves = [jnp.asarray(g) for g in jax.tree_util.tree_leaves(grads)]
    if not leaves:
        zero = jnp.zeros((), jnp.float32)
        return {GRAD_NORM_KEY: zero, NONFINITE_KEY: zero}
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    bad = sum(jnp.sum(~jnp.isfinite(g.astype(jnp.float32))) for g in leaves)
    return {GRAD_NORM_KEY: jnp.sqrt(sq),
            NONFINITE_KEY: bad.astype(jnp.float32)}


def tree_bytes(tree: Any) -> int:
    """Static byte size of a pytree's array leaves (shape/dtype only —
    no device access, safe on abstract values)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def live_arrays_bytes() -> int:
    """Total bytes of all live ``jax.Array`` buffers in this process —
    the device-memory-leak gauge.  Host-side bookkeeping only."""
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:  # deleted/donated between list and read
            pass
    return total
