"""ctypes bindings for the native C++ runtime (native/libdttpu.so).

The reference consumes its native layer through the TF 1.4 wheel
(SURVEY.md §2b); here the host-side native components are in-repo C++
(``native/dttpu_native.cpp``) exposed over a C ABI — pybind11 is not in the
image, so bindings are plain ctypes.

Load policy: build-on-demand (``make -C native``) the first time the
library is requested, cache the handle, and degrade to ``None`` (callers
fall back to their pure-Python paths) if the toolchain or the build is
unavailable.  Set ``DTTPU_NO_NATIVE=1`` to force the fallback paths.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["load_native", "native_available", "crc32c", "masked_crc32c",
           "xor_generate", "NativeLoader"]

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdttpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.dt_crc32c.restype = ctypes.c_uint32
    lib.dt_crc32c.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint32]
    lib.dt_masked_crc32c.restype = ctypes.c_uint32
    lib.dt_masked_crc32c.argtypes = [u8p, ctypes.c_uint64]
    lib.dt_xor_generate.restype = None
    lib.dt_xor_generate.argtypes = [ctypes.c_uint64, ctypes.c_int64,
                                    ctypes.c_int32, f32p, f32p]
    lib.dt_loader_create.restype = ctypes.c_void_p
    lib.dt_loader_create.argtypes = [u8p, ctypes.c_int64, u8p,
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_uint64,
                                     ctypes.c_int32, ctypes.c_int32,
                                     ctypes.c_int32]
    lib.dt_loader_next.restype = None
    lib.dt_loader_next.argtypes = [ctypes.c_void_p, u8p, u8p]
    lib.dt_loader_batches_per_epoch.restype = ctypes.c_int64
    lib.dt_loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.dt_loader_destroy.restype = None
    lib.dt_loader_destroy.argtypes = [ctypes.c_void_p]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.dt_bpe_encode.restype = ctypes.c_int64
    lib.dt_bpe_encode.argtypes = [u8p, ctypes.c_int64, i32p,
                                  ctypes.c_int64, ctypes.c_int32,
                                  i32p, ctypes.c_int64]
    return lib


def _build_locked() -> None:
    """Run make under an exclusive file lock so concurrent processes
    (multi-host launch, pytest-xdist) don't race the compile; the Makefile
    renames the artifact into place atomically."""
    import fcntl
    os.makedirs(_NATIVE_DIR, exist_ok=True)
    with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if not os.path.exists(_LIB_PATH):
            # intentional blocking-under-lock: the one-time native build
            # must pin the lock — a thread arriving meanwhile needs the
            # built artifact and has nothing to do but wait
            subprocess.run(  # dtlint: disable=DT304 -- see comment above
                ["make", "-C", _NATIVE_DIR],
                check=True, capture_output=True, timeout=120)


def load_native(build: bool = True) -> Optional[ctypes.CDLL]:
    """The cached library handle; None if unavailable.

    ``build=True`` compiles on first use (file-locked).  ``build=False``
    only loads an already-built library — used on import paths that must
    never block on a compiler (e.g. ``summary.crc32c``).
    """
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        if os.environ.get("DTTPU_NO_NATIVE"):
            _tried = True
            return None
        if not os.path.exists(_LIB_PATH):
            if not build:
                return None  # not a terminal failure; a later build may land
            try:
                _build_locked()
            except Exception:
                _tried = True
                return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception:
            _lib = None
        _tried = True
        return _lib


def native_available(build: bool = True) -> bool:
    return load_native(build=build) is not None


def _u8(buf: bytes):
    return ctypes.cast(ctypes.c_char_p(buf), ctypes.POINTER(ctypes.c_uint8))


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = load_native()
    assert lib is not None
    return lib.dt_crc32c(_u8(data), len(data), crc)


def masked_crc32c(data: bytes) -> int:
    lib = load_native()
    assert lib is not None
    return lib.dt_masked_crc32c(_u8(data), len(data))


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def bpe_encode(data: bytes, merge_pairs: np.ndarray,
               base_id: int) -> np.ndarray:
    """Native BPE encode: ``merge_pairs`` [n_merges, 2] int32 in rank
    order; returns int32 ids (bytes + base_id+rank merged tokens).  Exact
    same segmentation as the Python loop in data.text."""
    lib = load_native()
    assert lib is not None
    arr = np.frombuffer(data, np.uint8)
    pairs = np.ascontiguousarray(merge_pairs, np.int32)
    out = np.empty(max(len(arr), 1), np.int32)
    n = lib.dt_bpe_encode(
        _u8p(arr) if len(arr) else _u8(b"\0"), len(arr),
        pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        pairs.shape[0], int(base_id),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), out.shape[0])
    assert n >= 0
    return out[:n].copy()


def xor_generate(n: int, bits: int = 32,
                 seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """n samples of the XOR task, generated by the native threaded PRNG."""
    lib = load_native()
    assert lib is not None
    x = np.empty((n, 2 * bits), np.float32)
    y = np.empty((n, bits), np.float32)
    lib.dt_xor_generate(seed, n, bits, _f32p(x), _f32p(y))
    return x, y


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class NativeLoader:
    """Threaded shuffle+gather batch loader over two host-resident arrays.

    Worker threads gather upcoming shuffled batches into a ring of
    pre-allocated buffers so ``next()`` never waits on the gather; epochs
    reshuffle with a seed fold-in (same contract as ``data.Dataset``, which
    this backs when the native library is present).  Rows move as raw bytes,
    so any fixed-width dtype (f32 features, i32 labels, ...) works.
    """

    def __init__(self, x: np.ndarray, y: Optional[np.ndarray],
                 batch_size: int, seed: int = 0, shuffle: bool = True,
                 num_threads: int = 2, queue_depth: int = 4):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        # Keep source arrays alive and contiguous for the C side.
        self._x = np.ascontiguousarray(x)
        self._y = np.ascontiguousarray(y) if y is not None else None
        self.batch_size = int(batch_size)
        self._xrow = self._x.nbytes // self._x.shape[0]
        self._yrow = (self._y.nbytes // self._y.shape[0]
                      if self._y is not None else 0)
        self._handle = lib.dt_loader_create(
            _u8p(self._x), self._xrow,
            _u8p(self._y) if self._y is not None else None, self._yrow,
            self._x.shape[0], self.batch_size, seed, int(shuffle),
            num_threads, queue_depth)
        if not self._handle:
            raise ValueError("loader_create failed (batch > n?)")
        self.batches_per_epoch = lib.dt_loader_batches_per_epoch(self._handle)
        self._xshape = (self.batch_size,) + self._x.shape[1:]
        self._yshape = ((self.batch_size,) + self._y.shape[1:]
                        if self._y is not None else None)

    def next(self):
        xb = np.empty(self._xshape, self._x.dtype)
        yb = (np.empty(self._yshape, self._y.dtype)
              if self._yshape is not None else None)
        self._lib.dt_loader_next(
            self._handle, _u8p(xb), _u8p(yb) if yb is not None else None)
        return (xb, yb) if yb is not None else (xb,)

    def __iter__(self):
        for _ in range(self.batches_per_epoch):
            yield self.next()

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.dt_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
