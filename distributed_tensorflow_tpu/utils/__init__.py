from . import flags

__all__ = ["flags"]
