from . import flags, native
from .native import NativeLoader, native_available

__all__ = ["flags", "native", "NativeLoader", "native_available"]
