from . import flags, native, paths
from .native import NativeLoader, native_available
from .paths import get_data_path, get_logs_path

__all__ = ["flags", "native", "paths", "NativeLoader", "native_available",
           "get_data_path", "get_logs_path"]
