"""Local-vs-cloud path portability — the ``clusterone`` helper analogue.

The reference defaults its ``data_dir``/``log_dir`` flags through
``clusterone.get_data_path``/``get_logs_path`` so the SAME script runs on a
laptop or on the managed platform with no code change (reference
example.py:7,83-102).  Here the managed platform is a TPU VM / GKE job, and
the switch is environment variables:

  ``DTTPU_DATA_ROOT``  root for datasets  (e.g. ``gs://bucket/data`` or a
                       mounted ``/data`` volume)
  ``DTTPU_LOGS_ROOT``  root for logs/checkpoints/TB events

When a root is set, paths resolve under it (cloud mode); otherwise under the
caller's local fallbacks — mirroring the reference's local/cloud split
without the hard-coded Windows paths it ships (example.py:53-54).
"""
from __future__ import annotations

import getpass
import os
from typing import Optional

__all__ = ["get_data_path", "get_logs_path"]


def _join(root: str, *parts: str) -> str:
    """os.path.join that preserves URL-style roots (gs://...)."""
    parts = tuple(p.strip("/") for p in parts if p)
    if "://" in root:
        return "/".join((root.rstrip("/"),) + parts)
    return os.path.join(root, *parts)


def get_data_path(dataset_name: str = "",
                  local_root: Optional[str] = None,
                  local_repo: str = "", path: str = "") -> str:
    """Dataset directory: ``$DTTPU_DATA_ROOT/<dataset>/<path>`` on the
    managed platform, else ``<local_root>/<local_repo>/<path>``.

    Signature parity with ``clusterone.get_data_path`` (reference
    example.py:85-89): ``dataset_name`` is the ``user/dataset`` identifier
    used in cloud mode, ``local_root``/``local_repo`` the local fallback.
    """
    root = os.environ.get("DTTPU_DATA_ROOT")
    if root:
        return _join(root, dataset_name, path)
    local_root = local_root or os.path.join(
        os.path.expanduser("~"), "Documents", "data")
    return os.path.join(local_root, local_repo, path).rstrip(os.sep)


def get_logs_path(root: Optional[str] = None) -> str:
    """Log/checkpoint directory: ``$DTTPU_LOGS_ROOT/<user>/<job>`` on the
    managed platform, else the caller's ``root`` (parity with
    ``clusterone.get_logs_path``, reference example.py:96-99)."""
    env_root = os.environ.get("DTTPU_LOGS_ROOT")
    if env_root:
        user = os.environ.get("USER") or getpass.getuser()
        job = os.environ.get("DTTPU_JOB_NAME", "default")
        return _join(env_root, user, job)
    return root or os.path.join(os.path.expanduser("~"), "Documents",
                                "tpu_logs")
