"""Two-tier flag system: CLI flags with env-var-seeded defaults.

TPU-native re-design of the reference's config layer
(reference example.py:56,71-105): the reference seeds ``tf.app.flags``
definitions from ``os.environ`` reads and exposes a module-level ``FLAGS``
object.  We keep the same user-visible pattern (DEFINE_* + a lazily parsed
``FLAGS`` singleton) without TF.

Notable deliberate divergences from the reference:
  * ``TASK_INDEX`` is parsed to ``int`` before becoming a flag default.  The
    reference passes the raw env *string* into ``DEFINE_integer``
    (reference example.py:61,73), so ``FLAGS.task_index == 0`` is False on a
    real cluster and no worker ever becomes chief.  We do not reproduce that
    bug (SURVEY.md §7 "Hard parts").
  * Unknown CLI arguments are ignored rather than fatal, so the same module
    works under pytest / bench harnesses.
"""
from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "DEFINE_string", "DEFINE_integer", "DEFINE_float", "DEFINE_bool",
    "FLAGS", "env_default",
]


def _parse_bool(text: str) -> bool:
    lowered = str(text).strip().lower()
    if lowered in ("1", "true", "t", "yes", "y"):
        return True
    if lowered in ("0", "false", "f", "no", "n"):
        return False
    raise ValueError(f"cannot parse boolean flag value {text!r}")


class Flag:
    def __init__(self, name: str, default: Any, help_text: str,
                 parser: Callable[[str], Any]):
        self.name = name
        self.default = default
        self.help = help_text
        self.parser = parser
        self.value = default
        self.present = False  # set True when seen on the command line


class FlagValues:
    """Registry + lazily-parsed value store (the ``FLAGS`` singleton)."""

    def __init__(self) -> None:
        self._flags: Dict[str, Flag] = {}
        self._parsed = False

    # -- registration -----------------------------------------------------
    def define(self, name: str, default: Any, help_text: str,
               parser: Callable[[str], Any]) -> None:
        if name in self._flags:
            # Re-definition with identical default is tolerated so that
            # modules can be re-imported (e.g. under pytest).
            self._flags[name].default = default
            if not self._flags[name].present:
                self._flags[name].value = default
            return
        self._flags[name] = Flag(name, default, help_text, parser)
        self._parsed = False

    # -- parsing ----------------------------------------------------------
    def parse(self, argv: Optional[List[str]] = None) -> List[str]:
        """Parse ``--name value`` / ``--name=value`` / ``--[no]boolflag``.

        Returns the list of arguments that were not recognised as flags.
        """
        if argv is None:
            argv = sys.argv[1:]
        remaining: List[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            consumed = False
            if arg.startswith("--"):
                body = arg[2:]
                if "=" in body:
                    key, _, raw = body.partition("=")
                    flag = self._flags.get(key)
                    if flag is not None:
                        flag.value = flag.parser(raw)
                        flag.present = True
                        consumed = True
                else:
                    flag = self._flags.get(body)
                    if flag is not None:
                        if flag.parser is _parse_bool:
                            flag.value = True
                            flag.present = True
                            consumed = True
                        else:
                            # A valued flag must be followed by its value —
                            # another --flag or end-of-argv means the value
                            # was forgotten; fail loudly rather than train
                            # with a silently unchanged default.
                            if (i + 1 >= len(argv) or
                                    argv[i + 1].startswith("--")):
                                raise ValueError(
                                    f"flag --{body} requires a value")
                            flag.value = flag.parser(argv[i + 1])
                            flag.present = True
                            i += 1
                            consumed = True
                    elif body.startswith("no") and body[2:] in self._flags:
                        flag = self._flags[body[2:]]
                        if flag.parser is _parse_bool:
                            flag.value = False
                            flag.present = True
                            consumed = True
            if not consumed:
                remaining.append(arg)
            i += 1
        self._parsed = True
        return remaining

    # -- access -----------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        flags = self.__dict__.get("_flags", {})
        if name not in flags:
            raise AttributeError(f"flag --{name} is not defined")
        if not self.__dict__.get("_parsed", False):
            self.parse()
        return flags[name].value

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if name in self._flags:
            self._flags[name].value = value
            self._flags[name].present = True
        else:
            object.__setattr__(self, name, value)

    def reset(self) -> None:
        """Restore every flag to its default (test helper)."""
        for flag in self._flags.values():
            flag.value = flag.default
            flag.present = False
        self._parsed = False


FLAGS = FlagValues()


def DEFINE_string(name: str, default: Optional[str], help_text: str = "") -> None:
    FLAGS.define(name, default, help_text, str)


def DEFINE_integer(name: str, default: Optional[int], help_text: str = "") -> None:
    FLAGS.define(name, None if default is None else int(default), help_text, int)


def DEFINE_float(name: str, default: Optional[float], help_text: str = "") -> None:
    FLAGS.define(name, None if default is None else float(default), help_text, float)


def DEFINE_bool(name: str, default: Optional[bool], help_text: str = "") -> None:
    FLAGS.define(name, None if default is None else _parse_bool(str(default)),
                 help_text, _parse_bool)


def env_default(var: str, default: Any, cast: Callable[[str], Any] = str) -> Any:
    """Read an env var with a typed fallback.

    The reference wraps its env reads in a bare ``try/except`` that silently
    falls back to single-machine mode (reference example.py:59-68).  We keep
    the fallback semantics but only catch the actual failure modes (missing
    var, bad cast).
    """
    raw = os.environ.get(var)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        return default
