"""Learning-rate schedules (step -> lr), jit-traceable.

The reference trains at a fixed default Adam LR (example.py:168); schedules
are required by the larger baseline configs (ResNet-50 step decay, BERT
linear warmup/decay).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["constant", "exponential_decay", "cosine_decay",
           "warmup_cosine_decay", "warmup_linear_decay", "piecewise_constant",
           "polynomial_decay"]


def constant(value: float):
    def schedule(count):
        return jnp.full((), value, jnp.float32)
    return schedule


def exponential_decay(init_value: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False):
    def schedule(count):
        p = count.astype(jnp.float32) / decay_steps
        if staircase:
            p = jnp.floor(p)
        return init_value * jnp.power(decay_rate, p)
    return schedule


def cosine_decay(init_value: float, decay_steps: int, alpha: float = 0.0):
    def schedule(count):
        frac = jnp.clip(count.astype(jnp.float32) / decay_steps, 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return init_value * ((1 - alpha) * cos + alpha)
    return schedule


def warmup_cosine_decay(peak_value: float, warmup_steps: int,
                        decay_steps: int, end_value: float = 0.0):
    def schedule(count):
        t = count.astype(jnp.float32)
        warm = peak_value * t / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip((t - warmup_steps) /
                        jnp.maximum(decay_steps - warmup_steps, 1), 0.0, 1.0)
        cos = end_value + (peak_value - end_value) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(t < warmup_steps, warm, cos)
    return schedule


def warmup_linear_decay(peak_value: float, warmup_steps: int,
                        total_steps: int):
    """BERT-style: linear warmup then linear decay to zero."""
    def schedule(count):
        t = count.astype(jnp.float32)
        warm = peak_value * t / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip((t - warmup_steps) /
                        jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0)
        return jnp.where(t < warmup_steps, warm, peak_value * (1.0 - frac))
    return schedule


def polynomial_decay(init_value: float, decay_steps: int,
                     end_value: float = 1e-4, power: float = 1.0,
                     cycle: bool = False):
    """tf.train.polynomial_decay semantics: decay from ``init_value`` to
    ``end_value`` over ``decay_steps`` following ``(1 - t/T)^power``; with
    ``cycle=True`` the horizon T expands to the next multiple of
    ``decay_steps`` past the current step instead of clamping.
    """
    def schedule(count):
        t = count.astype(jnp.float32)
        if cycle:
            mult = jnp.maximum(jnp.ceil(t / decay_steps), 1.0)
            horizon = decay_steps * mult
        else:
            horizon = jnp.asarray(decay_steps, jnp.float32)
            t = jnp.minimum(t, horizon)
        frac = 1.0 - t / horizon
        return (init_value - end_value) * jnp.power(frac, power) + end_value
    return schedule


def piecewise_constant(boundaries, values):
    """ResNet-style step schedule: values[i] for step < boundaries[i]."""
    bounds = jnp.asarray(boundaries, jnp.float32)
    vals = jnp.asarray(values, jnp.float32)

    def schedule(count):
        idx = jnp.sum(count.astype(jnp.float32) >= bounds)
        return vals[idx]
    return schedule
