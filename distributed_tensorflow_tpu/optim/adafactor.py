"""Adafactor (Shazeer & Stern, 2018) — sublinear-memory Adam for pod-scale
training.

The TPU-era optimizer behind T5: for a [r, c] weight matrix it keeps one
row EMA [r] and one column EMA [c] of squared gradients instead of the full
[r, c] second moment (their outer product over mean reconstructs it), so
optimizer memory for matrices drops from O(rc) to O(r + c).  Scalars and
vectors keep a full second moment.  No first moment by default.

Implemented pieces (paper sections 3-5): factored second moments with the
time-dependent decay β2_t = 1 − t^−0.8, per-tensor update RMS clipping
(d = 1.0), and the relative step size max(ε₂, RMS(p)) · min(10⁻², 1/√t)
when no explicit learning rate is given.

State layout: ``inner = {"vr": tree, "vc": tree, "v": tree}`` where every
tree shares the params treedef and non-applicable slots hold zeros((0,)) —
uniform structure keeps ``jax.tree.map`` and ZeRO placement simple (the
factored vectors are O(r + c), so replicating them costs ~nothing).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .optimizers import Optimizer, OptState, ScalarOrSchedule, _lr_at

__all__ = ["adafactor"]


def _rms(x):
    return jnp.sqrt(jnp.mean(jnp.square(x)))


def _slice_rms(x):
    """Per-tensor RMS, treating dim 0 of rank>=3 tensors as a layer stack.

    This repo's transformer stacks are vmap-initialized [L, ...] pytrees
    (one scanned XLA loop per stack), so the paper's per-tensor clipping /
    relative-step rule maps to per-leading-slice reductions there; plain
    matrices and vectors reduce whole-tensor.  Returns a shape
    broadcastable against ``x``.
    """
    if x.ndim >= 3:
        axes = tuple(range(1, x.ndim))
        return jnp.sqrt(jnp.mean(jnp.square(x), axis=axes, keepdims=True))
    return _rms(x)


def adafactor(learning_rate: Optional[ScalarOrSchedule] = None,
              min_dim_size_to_factor: int = 128,
              decay_exponent: float = 0.8,
              clipping_threshold: float = 1.0,
              eps1: float = 1e-30, eps2: float = 1e-3,
              relative_step_cap: float = 1e-2) -> Optimizer:
    """``learning_rate=None`` uses the paper's relative step size
    (``max(eps2, RMS(p)) * min(relative_step_cap, 1/sqrt(t))``); a float or
    schedule overrides it.  Tensors whose two trailing dims are both at
    least ``min_dim_size_to_factor`` get factored second moments."""

    def _factored(p) -> bool:
        return (p.ndim >= 2 and p.shape[-1] >= min_dim_size_to_factor
                and p.shape[-2] >= min_dim_size_to_factor)

    def init(params) -> OptState:
        def vr(p):
            return (jnp.zeros(p.shape[:-1], jnp.float32) if _factored(p)
                    else jnp.zeros((0,), jnp.float32))

        def vc(p):
            return (jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
                    if _factored(p) else jnp.zeros((0,), jnp.float32))

        def v(p):
            return (jnp.zeros((0,), jnp.float32) if _factored(p)
                    else jnp.zeros_like(p, jnp.float32))

        return OptState(jnp.zeros((), jnp.int32),
                        {"vr": jax.tree.map(vr, params),
                         "vc": jax.tree.map(vc, params),
                         "v": jax.tree.map(v, params)})

    def update(grads, state: OptState, params):
        if params is None:
            raise ValueError("adafactor needs params at update() (relative "
                             "step + factored reconstruction)")
        count = state.count + 1
        t = count.astype(jnp.float32)
        beta2 = 1.0 - jnp.power(t, -decay_exponent)

        def one(g, p, vr, vc, v):
            g = g.astype(jnp.float32)
            g2 = jnp.square(g) + eps1
            if _factored(p):
                new_vr = beta2 * vr + (1 - beta2) * jnp.mean(g2, axis=-1)
                new_vc = beta2 * vc + (1 - beta2) * jnp.mean(g2, axis=-2)
                # v̂ = outer(vr, vc) / mean(vr): reconstruct rsqrt directly
                r_inv = jax.lax.rsqrt(
                    new_vr / jnp.mean(new_vr, axis=-1, keepdims=True))
                c_inv = jax.lax.rsqrt(new_vc)
                u = g * r_inv[..., None] * c_inv[..., None, :]
                new_v = v
            else:
                new_v = beta2 * v + (1 - beta2) * g2
                u = g * jax.lax.rsqrt(new_v)
                new_vr, new_vc = vr, vc
            u = u / jnp.maximum(1.0, _slice_rms(u) / clipping_threshold)
            if learning_rate is None:
                step_size = (jnp.maximum(eps2,
                                         _slice_rms(p.astype(jnp.float32)))
                             * jnp.minimum(relative_step_cap,
                                           1.0 / jnp.sqrt(t)))
            else:
                step_size = _lr_at(learning_rate, count)
            return -step_size * u, new_vr, new_vc, new_v

        moved = jax.tree.map(one, grads, params, state.inner["vr"],
                             state.inner["vc"], state.inner["v"])
        pick = lambda i: jax.tree.map(
            lambda x: x[i], moved,
            is_leaf=lambda x: isinstance(x, tuple) and len(x) == 4)
        return pick(0), OptState(count, {"vr": pick(1), "vc": pick(2),
                                         "v": pick(3)})

    return Optimizer(init, update)


# by-name registration ("adafactor" in optim.get / compile(optimizer=...));
# here rather than in optimizers.py so the module dependency stays one-way
from .optimizers import _REGISTRY  # noqa: E402

_REGISTRY["adafactor"] = adafactor
