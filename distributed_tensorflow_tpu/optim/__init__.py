"""Optimizers and LR schedules (pure-functional, shardable opt_state)."""

from . import schedules
from .adafactor import adafactor
from .ema import EMAState, ema, ema_params, with_ema
from .optimizers import (Optimizer, OptState, adadelta, adagrad, adam, adamw,
                         apply_updates, clip_by_global_norm, ftrl, get,
                         global_norm, lamb, momentum, rmsprop, sgd)

__all__ = ["schedules", "adafactor", "Optimizer", "OptState", "adadelta",
           "adagrad", "adam", "adamw", "apply_updates", "clip_by_global_norm",
           "ftrl", "get", "global_norm", "lamb", "momentum", "rmsprop", "sgd",
           "EMAState", "ema", "ema_params", "with_ema"]
