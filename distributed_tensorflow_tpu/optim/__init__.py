"""Optimizers and LR schedules (pure-functional, shardable opt_state)."""

from . import schedules
from .adafactor import adafactor
from .ema import EMAState, ema, ema_params, with_ema
from .optimizers import (Optimizer, OptState, adadelta, adagrad, adam, adamw,
                         apply_updates, clip_by_global_norm, ftrl, get,
                         get_lr_scale, global_norm, lamb, momentum, rmsprop,
                         set_lr_scale, sgd, with_lr_scale)

__all__ = ["schedules", "adafactor", "Optimizer", "OptState", "adadelta",
           "adagrad", "adam", "adamw", "apply_updates", "clip_by_global_norm",
           "ftrl", "get", "get_lr_scale", "global_norm", "lamb", "momentum",
           "rmsprop", "set_lr_scale", "sgd", "with_lr_scale",
           "EMAState", "ema", "ema_params", "with_ema"]
