"""Optimizers and LR schedules (pure-functional, shardable opt_state)."""

from . import schedules
from .adafactor import adafactor
from .ema import EMAState, ema, ema_params, with_ema
from .optimizers import (Optimizer, OptState, adam, adamw, apply_updates,
                         clip_by_global_norm, get, global_norm, lamb,
                         momentum, sgd)

__all__ = ["schedules", "adafactor", "Optimizer", "OptState", "adam", "adamw",
           "apply_updates", "clip_by_global_norm", "get", "global_norm",
           "lamb", "momentum", "sgd", "EMAState", "ema", "ema_params", "with_ema"]
