"""Optimizers (L4).

The reference uses ``tf.train.AdamOptimizer().minimize(loss, global_step)``
with TF 1.4 defaults (reference example.py:168-170).  Our ``Adam`` reproduces
the *TF 1.4 update rule* exactly (bias-corrected LR folded in, epsilon added
OUTSIDE the sqrt):

    lr_t = lr * sqrt(1 - b2^t) / (1 - b1^t)
    p   -= lr_t * m / (sqrt(v) + eps)

(which differs from the common "epsilon-inside-bias-correction" variant) so
single-device runs are numerically comparable to the reference's optimizer.

Design: pure-functional GradientTransformation —
``init(params) -> opt_state``, ``update(grads, opt_state, params) ->
(updates, new_opt_state)`` — the pair jits cleanly and the opt_state pytree
shards with the same PartitionSpecs as the params (fsdp-friendly).  The
shared ``global_step`` variable of the PS design (example.py:169) becomes a
scalar carried in ``opt_state.count`` / ``TrainState.step``.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

__all__ = ["Optimizer", "OptState", "sgd", "momentum", "adam", "adamw",
           "lamb", "rmsprop", "adagrad", "adadelta", "ftrl",
           "apply_updates", "clip_by_global_norm", "global_norm", "get",
           "with_lr_scale", "get_lr_scale", "set_lr_scale"]

Schedule = Callable[[jnp.ndarray], jnp.ndarray]
ScalarOrSchedule = Union[float, Schedule]


class OptState(NamedTuple):
    count: jnp.ndarray          # int32 step counter (the global_step cursor)
    inner: Any                  # optimizer-specific pytree(s)


class Optimizer(NamedTuple):
    init: Callable[[Any], OptState]
    update: Callable[..., Any]  # (grads, state, params) -> (updates, state)


def _lr_at(lr: ScalarOrSchedule, count) -> jnp.ndarray:
    if callable(lr):
        return jnp.asarray(lr(count), jnp.float32)
    return jnp.asarray(lr, jnp.float32)


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def clip_by_global_norm(tree, max_norm: float):
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                        tree), norm


def apply_updates(params, updates):
    """p + u, computed in f32 and cast back to each param's dtype."""
    return jax.tree.map(
        lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)
                      ).astype(p.dtype),
        params, updates)


def sgd(learning_rate: ScalarOrSchedule = 0.01) -> Optimizer:
    def init(params):
        del params
        return OptState(jnp.zeros((), jnp.int32), ())

    def update(grads, state: OptState, params=None):
        del params
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        updates = jax.tree.map(lambda g: -lr * g.astype(jnp.float32), grads)
        return updates, OptState(count, ())

    return Optimizer(init, update)


def momentum(learning_rate: ScalarOrSchedule = 0.01, beta: float = 0.9,
             nesterov: bool = False) -> Optimizer:
    def init(params):
        mu = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return OptState(jnp.zeros((), jnp.int32), mu)

    def update(grads, state: OptState, params=None):
        del params
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        mu = jax.tree.map(lambda m, g: beta * m + g.astype(jnp.float32),
                          state.inner, grads)
        if nesterov:
            updates = jax.tree.map(
                lambda m, g: -lr * (beta * m + g.astype(jnp.float32)),
                mu, grads)
        else:
            updates = jax.tree.map(lambda m: -lr * m, mu)
        return updates, OptState(count, mu)

    return Optimizer(init, update)


def _moments_init(params) -> OptState:
    """Adam-family state: f32 first/second moments + step count."""
    zeros = lambda p: jnp.zeros_like(p, jnp.float32)
    return OptState(jnp.zeros((), jnp.int32),
                    {"m": jax.tree.map(zeros, params),
                     "v": jax.tree.map(zeros, params)})


def _moments_update(inner, grads, b1: float, b2: float):
    """One EMA step of the (m, v) pair, accumulated in f32."""
    m = jax.tree.map(
        lambda m_, g: b1 * m_ + (1 - b1) * g.astype(jnp.float32),
        inner["m"], grads)
    v = jax.tree.map(
        lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g.astype(jnp.float32)),
        inner["v"], grads)
    return m, v


def adam(learning_rate: ScalarOrSchedule = 1e-3, b1: float = 0.9,
         b2: float = 0.999, eps: float = 1e-8,
         fused: bool = False) -> Optimizer:
    """TF-1.4-parity Adam (defaults match reference example.py:168).

    ``fused=True`` runs the whole per-tensor update (m, v, p) in ONE Pallas
    TPU kernel (``ops.pallas.fused_adam_update``) — one HBM round-trip per
    tensor instead of several XLA ops; numerically identical update rule
    (bias correction folded into scalar prefactors).  Requires ``params``
    at ``update`` time; off-TPU the kernel runs in interpret mode.
    """

    def update(grads, state: OptState, params=None):
        count = state.count + 1
        if fused:
            if params is None:
                raise ValueError("adam(fused=True) needs params at update()")
            from ..ops.pallas import fused_adam_update
            lr = _lr_at(learning_rate, count)
            # Flatten/unzip (no structural heuristics): every leaf maps to a
            # (delta, m, v) triple from one kernel call; tf14_eps keeps the
            # module's documented epsilon placement.
            flat_p, treedef = jax.tree_util.tree_flatten(params)
            triples = [
                fused_adam_update(p, g, m_, v_, count, lr=lr, b1=b1, b2=b2,
                                  eps=eps, tf14_eps=True, return_delta=True)
                for p, g, m_, v_ in zip(
                    flat_p, jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(state.inner["m"]),
                    jax.tree_util.tree_leaves(state.inner["v"]))]
            unzip = lambda i: jax.tree_util.tree_unflatten(
                treedef, [t[i] for t in triples])
            return unzip(0), OptState(count, {"m": unzip(1), "v": unzip(2)})
        t = count.astype(jnp.float32)
        lr_t = _lr_at(learning_rate, count) * jnp.sqrt(
            1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))
        m, v = _moments_update(state.inner, grads, b1, b2)
        updates = jax.tree.map(lambda m_, v_: -lr_t * m_ / (jnp.sqrt(v_) + eps),
                               m, v)
        return updates, OptState(count, {"m": m, "v": v})

    return Optimizer(_moments_init, update)


def adamw(learning_rate: ScalarOrSchedule = 1e-3, b1: float = 0.9,
          b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01,
          mask: Optional[Callable[[Any], Any]] = None,
          fused: bool = False) -> Optimizer:
    """Adam with decoupled weight decay (BERT fine-tune config).

    ``mask(params)`` returns a same-structure pytree of bools selecting which
    leaves decay (convention: no decay on biases / norm scales).
    """
    base = adam(learning_rate, b1, b2, eps, fused=fused)

    def update(grads, state: OptState, params):
        updates, new_state = base.update(grads, state, params)
        lr = _lr_at(learning_rate, new_state.count)
        decay_mask = (mask(params) if mask is not None
                      else jax.tree.map(lambda p: p.ndim > 1, params))
        updates = jax.tree.map(
            lambda u, p, m_: u - (lr * weight_decay * p.astype(jnp.float32)
                                  if m_ else 0.0),
            updates, params, decay_mask)
        return updates, new_state

    return Optimizer(base.init, update)


def lamb(learning_rate: ScalarOrSchedule = 1e-3, b1: float = 0.9,
         b2: float = 0.999, eps: float = 1e-6,
         weight_decay: float = 0.01,
         mask: Optional[Callable[[Any], Any]] = None,
         min_trust: float = 0.0, max_trust: float = 10.0) -> Optimizer:
    """LAMB (You et al. 2020): layer-wise trust-ratio Adam for LARGE-batch
    training — the optimizer behind 76-minute BERT on TPU pods, where the
    global batch grows with the mesh's data axis and plain Adam diverges.

    Per leaf: Adam direction r = m̂/(√v̂+eps) (+ decoupled weight decay),
    scaled by trust ratio ‖p‖/‖r‖ so every layer takes a step proportional
    to its own weight norm.  ``mask`` selects leaves that get weight decay
    AND trust scaling (default: ndim > 1, i.e. not biases/norm scales —
    those fall back to the plain Adam step).
    """

    def update(grads, state: OptState, params):
        count = state.count + 1
        t = count.astype(jnp.float32)
        lr = _lr_at(learning_rate, count)
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)
        m, v = _moments_update(state.inner, grads, b1, b2)
        decay_mask = (mask(params) if mask is not None
                      else jax.tree.map(lambda p: p.ndim > 1, params))

        def step(m_, v_, p, use_trust):
            r = (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
            if use_trust:
                r = r + weight_decay * p.astype(jnp.float32)
                w_norm = jnp.linalg.norm(p.astype(jnp.float32))
                r_norm = jnp.linalg.norm(r)
                trust = jnp.where(
                    (w_norm > 0) & (r_norm > 0),
                    jnp.clip(w_norm / r_norm, min_trust, max_trust), 1.0)
                return -lr * trust * r
            return -lr * r

        updates = jax.tree.map(step, m, v, params, decay_mask)
        return updates, OptState(count, {"m": m, "v": v})

    return Optimizer(_moments_init, update)


def rmsprop(learning_rate: ScalarOrSchedule = 0.001, decay: float = 0.9,
            momentum: float = 0.0, eps: float = 1e-10,
            centered: bool = False) -> Optimizer:
    """RMSProp with the tf.train.RMSPropOptimizer update rule (TF-1.4-era
    defaults: decay=0.9, momentum=0.0, epsilon=1e-10; epsilon sits INSIDE
    the sqrt denominator's accumulator, i.e. g / sqrt(ms + eps)).

    ``centered=True`` additionally tracks the gradient mean and divides by
    the estimated variance (sqrt(ms - mg^2 + eps)).
    """

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        inner = {"ms": jax.tree.map(zeros, params),
                 "mom": jax.tree.map(zeros, params)}
        if centered:
            inner["mg"] = jax.tree.map(zeros, params)
        return OptState(jnp.zeros((), jnp.int32), inner)

    def update(grads, state: OptState, params=None):
        del params
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        ms = jax.tree.map(
            lambda s, g: decay * s + (1 - decay) * jnp.square(
                g.astype(jnp.float32)),
            state.inner["ms"], grads)
        if centered:
            mg = jax.tree.map(
                lambda a, g: decay * a + (1 - decay) * g.astype(jnp.float32),
                state.inner["mg"], grads)
            denom = jax.tree.map(
                lambda s, a: jnp.sqrt(s - jnp.square(a) + eps), ms, mg)
        else:
            denom = jax.tree.map(lambda s: jnp.sqrt(s + eps), ms)
        mom = jax.tree.map(
            lambda mo, g, d: momentum * mo + lr * g.astype(jnp.float32) / d,
            state.inner["mom"], grads, denom)
        updates = jax.tree.map(lambda mo: -mo, mom)
        inner = {"ms": ms, "mom": mom}
        if centered:
            inner["mg"] = mg
        return updates, OptState(count, inner)

    return Optimizer(init, update)


def adagrad(learning_rate: ScalarOrSchedule = 0.01,
            initial_accumulator_value: float = 0.1) -> Optimizer:
    """Adagrad matching tf.train.AdagradOptimizer: the squared-gradient
    accumulator starts at ``initial_accumulator_value`` (0.1, which is what
    keeps the very first steps finite — TF 1.4 has no epsilon here) and the
    step is ``-lr * g / sqrt(acc)``.
    """
    if initial_accumulator_value <= 0:
        raise ValueError("adagrad needs initial_accumulator_value > 0 "
                         "(it is the only thing keeping step 1 finite)")

    def init(params):
        acc = jax.tree.map(
            lambda p: jnp.full(p.shape, initial_accumulator_value,
                               jnp.float32), params)
        return OptState(jnp.zeros((), jnp.int32), acc)

    def update(grads, state: OptState, params=None):
        del params
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        acc = jax.tree.map(
            lambda a, g: a + jnp.square(g.astype(jnp.float32)),
            state.inner, grads)
        updates = jax.tree.map(
            lambda g, a: -lr * g.astype(jnp.float32) / jnp.sqrt(a),
            grads, acc)
        return updates, OptState(count, acc)

    return Optimizer(init, update)


def adadelta(learning_rate: ScalarOrSchedule = 0.001, rho: float = 0.95,
             eps: float = 1e-8) -> Optimizer:
    """Adadelta (Zeiler 2012) with tf.train.AdadeltaOptimizer semantics:
    two EMAs (squared grads, squared updates); the unit-correcting step is
    ``sqrt(acc_delta + eps) / sqrt(acc_grad + eps) * g`` scaled by ``lr``.
    """

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, jnp.float32)
        return OptState(jnp.zeros((), jnp.int32),
                        {"acc_g": jax.tree.map(zeros, params),
                         "acc_d": jax.tree.map(zeros, params)})

    def update(grads, state: OptState, params=None):
        del params
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        acc_g = jax.tree.map(
            lambda a, g: rho * a + (1 - rho) * jnp.square(
                g.astype(jnp.float32)),
            state.inner["acc_g"], grads)
        delta = jax.tree.map(
            lambda g, ag, ad: (jnp.sqrt(ad + eps) / jnp.sqrt(ag + eps)
                               ) * g.astype(jnp.float32),
            grads, acc_g, state.inner["acc_d"])
        acc_d = jax.tree.map(
            lambda a, d: rho * a + (1 - rho) * jnp.square(d),
            state.inner["acc_d"], delta)
        updates = jax.tree.map(lambda d: -lr * d, delta)
        return updates, OptState(count, {"acc_g": acc_g, "acc_d": acc_d})

    return Optimizer(init, update)


def ftrl(learning_rate: ScalarOrSchedule = 0.001,
         learning_rate_power: float = -0.5,
         initial_accumulator_value: float = 0.1,
         l1_regularization_strength: float = 0.0,
         l2_regularization_strength: float = 0.0) -> Optimizer:
    """FTRL-Proximal (McMahan et al. 2013), the tf.train.FtrlOptimizer
    surface: per-coordinate adaptive rates with L1 (sparsity) / L2 shrinkage
    applied in closed form at each step.  Unlike the delta-style optimizers
    above, FTRL recomputes the weight from its (z, n) state, so ``params``
    is required at update() and the returned update is ``w_new - p``.
    """
    if initial_accumulator_value < 0:
        raise ValueError("ftrl needs initial_accumulator_value >= 0")
    l1, l2 = l1_regularization_strength, l2_regularization_strength
    p_pow = -learning_rate_power  # 0.5 for the default inverse-sqrt rate

    def init(params):
        n = jax.tree.map(
            lambda p: jnp.full(p.shape, initial_accumulator_value,
                               jnp.float32), params)
        z = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return OptState(jnp.zeros((), jnp.int32), {"n": n, "z": z})

    def update(grads, state: OptState, params=None):
        if params is None:
            raise ValueError("ftrl needs params at update()")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)

        # Three structure-validated tree.maps (XLA CSEs the shared
        # subexpressions) instead of a flatten/zip that could silently
        # misalign leaves on a grads/params structure mismatch.
        n_new = jax.tree.map(
            lambda n, g: n + jnp.square(g.astype(jnp.float32)),
            state.inner["n"], grads)
        z_new = jax.tree.map(
            lambda z, g, n, nn, p: (
                z + g.astype(jnp.float32)
                - (jnp.power(nn, p_pow) - jnp.power(n, p_pow)) / lr
                * p.astype(jnp.float32)),
            state.inner["z"], grads, state.inner["n"], n_new, params)
        updates = jax.tree.map(
            lambda z, nn, p: jnp.where(
                jnp.abs(z) <= l1, 0.0,
                -(z - jnp.sign(z) * l1)
                / (jnp.power(nn, p_pow) / lr + 2.0 * l2)
            ) - p.astype(jnp.float32),
            z_new, n_new, params)
        return updates, OptState(count, {"n": n_new, "z": z_new})

    return Optimizer(init, update)


def with_lr_scale(optimizer: Optimizer) -> Optimizer:
    """Wrap an optimizer with a host-settable learning-rate multiplier.

    The scale lives in ``opt_state.inner["scale"]`` — a device scalar, so
    changing it between steps (``set_lr_scale``) is pure state surgery with
    NO recompilation: the jitted step reads whatever scalar the state
    carries.  This is the functional replacement for mutating
    ``optimizer.lr`` the way Keras's LearningRateScheduler /
    ReduceLROnPlateau callbacks do on a stateful optimizer object.

    Exactness: scaling the returned update by s is identical to scaling the
    learning rate by s for every delta-style optimizer here (sgd, momentum,
    adam(w), lamb, rmsprop, adagrad, adadelta, adafactor's explicit-lr
    mode) because their update is linear in lr.  ftrl recomputes weights
    from (z, n) state, so for ftrl the scale damps the step toward the
    FTRL target rather than re-deriving it at a lower rate.
    """

    def init(params):
        inner = optimizer.init(params)
        # A fresh zero, not inner.count itself: the same concrete array in
        # two pytree slots breaks buffer donation at the first dispatch
        # (`donate(a), donate(a)`) — values equal, buffers must not be.
        return OptState(jnp.zeros_like(inner.count),
                        {"scale": jnp.ones((), jnp.float32), "inner": inner})

    def update(grads, state: OptState, params=None):
        scale = state.inner["scale"]
        updates, new_inner = optimizer.update(grads, state.inner["inner"],
                                              params)
        updates = jax.tree.map(lambda u: u * scale, updates)
        # state.count + 1, NOT new_inner.count: mirroring the inner value
        # puts one jaxpr output in two pytree slots, and when XLA aliases
        # identical outputs to one buffer the NEXT dispatch donates it
        # twice — the same class of failure init avoids with its fresh
        # zero.  The add keeps the value equal but the buffer distinct.
        return updates, OptState(state.count + 1,
                                 {"scale": scale, "inner": new_inner})

    return Optimizer(init, update)


def get_lr_scale(opt_state: OptState) -> float:
    """Current multiplier of a ``with_lr_scale``-wrapped opt_state."""
    try:
        return float(opt_state.inner["scale"])
    except (TypeError, KeyError, IndexError):
        raise ValueError("opt_state was not created by a with_lr_scale-"
                         "wrapped optimizer") from None


def set_lr_scale(opt_state: OptState, value: float) -> OptState:
    """Return the opt_state with the LR multiplier replaced (pure; the
    caller re-threads it into its TrainState)."""
    get_lr_scale(opt_state)  # structure check
    inner = dict(opt_state.inner)
    inner["scale"] = jnp.asarray(value, jnp.float32)
    return OptState(opt_state.count, inner)


_REGISTRY = {
    "sgd": sgd,
    "momentum": momentum,
    "adam": adam,
    "adamw": adamw,
    "lamb": lamb,
    "rmsprop": rmsprop,
    "adagrad": adagrad,
    "adadelta": adadelta,
    "ftrl": ftrl,
}


def get(name_or_opt, **kwargs):
    """'adam' -> TF-1.4-default Adam, matching ``compile(optimizer='adam')``
    at reference example2.py:165."""
    if isinstance(name_or_opt, Optimizer):
        return name_or_opt
    try:
        return _REGISTRY[name_or_opt](**kwargs)
    except KeyError:
        raise ValueError(f"unknown optimizer {name_or_opt!r}; "
                         f"known: {sorted(_REGISTRY)}") from None
