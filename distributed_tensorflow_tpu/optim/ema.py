"""Exponential moving average of parameters.

The evaluation-time trick the reference era shipped as
``tf.train.ExponentialMovingAverage``: keep a shadow copy
``s ← d·s + (1−d)·p`` of every parameter and evaluate/serve from the shadow.
Two forms:

  * ``ema(decay)`` — a standalone functional tracker (init/update/value)
    for custom loops.
  * ``with_ema(optimizer, decay)`` — an Optimizer wrapper: the shadow rides
    inside ``opt_state`` so every existing step builder, checkpoint, and
    session works unchanged; pull the averaged params out with
    ``ema_params(state.opt_state)``.

Both debias by default (divide by ``1 − d^t``), so early-step averages are
unbiased instead of pulled toward the zero initialization.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optimizers import Optimizer, OptState, apply_updates

__all__ = ["EMAState", "ema", "with_ema", "ema_params"]


class EMAState(NamedTuple):
    count: jnp.ndarray     # int32 number of updates folded in
    decay: jnp.ndarray     # f32 scalar (carried so readers need no config)
    debias: jnp.ndarray    # bool scalar (ditto — readers honor it)
    shadow: Any            # params-shaped pytree


def _update_shadow(state: EMAState, params) -> EMAState:
    d = state.decay

    def one(s, p):
        # Accumulate in f32, store back in the shadow's own dtype — the
        # carry type must be step-invariant (lax.scan, buffer donation).
        new = d * s.astype(jnp.float32) + (1.0 - d) * p.astype(jnp.float32)
        return new.astype(s.dtype)

    return state._replace(count=state.count + 1,
                          shadow=jax.tree.map(one, state.shadow, params))


def _value(state: EMAState):
    # After t updates from a zero init the shadow carries total weight
    # 1 - d^t; dividing restores an unbiased average (Adam-style).  The
    # debias choice was made at construction and travels in the state.
    scale = jnp.where(
        state.debias,
        1.0 / (1.0 - state.decay
               ** jnp.maximum(state.count, 1).astype(jnp.float32)),
        1.0)
    return jax.tree.map(lambda s: s * scale.astype(s.dtype), state.shadow)


class _EMA(NamedTuple):
    init: Any
    update: Any
    value: Any


def ema(decay: float = 0.999, debias: bool = True) -> _EMA:
    """Standalone tracker: ``state = e.init(params)``,
    ``state = e.update(state, params)`` each step,
    ``e.value(state)`` -> averaged params."""

    def init(params) -> EMAState:
        return EMAState(jnp.zeros((), jnp.int32),
                        jnp.asarray(decay, jnp.float32),
                        jnp.asarray(debias),
                        jax.tree.map(jnp.zeros_like, params))

    def update(state: EMAState, params) -> EMAState:
        return _update_shadow(state, params)

    return _EMA(init, update, _value)


def with_ema(optimizer: Optimizer, decay: float = 0.999,
             debias: bool = True) -> Optimizer:
    """Wrap an Optimizer so the post-update params feed a shadow average
    carried in ``opt_state.inner['ema']``.  Requires the step to pass
    ``params`` to ``update`` (every builder in train/step.py does)."""
    tracker = ema(decay, debias)

    def init(params) -> OptState:
        inner = optimizer.init(params)
        # The wrapper's count is its own buffer, NOT a reference to
        # inner.count — aliased leaves in one state break buffer donation
        # ("donate the same buffer twice").
        return OptState(jnp.zeros((), jnp.int32),
                        {"opt": inner, "ema": tracker.init(params)})

    def update(grads, state: OptState, params=None):
        if params is None:
            raise ValueError("with_ema needs params passed to update()")
        updates, new_inner = optimizer.update(grads, state.inner["opt"],
                                              params)
        new_params = apply_updates(params, updates)
        new_ema = tracker.update(state.inner["ema"], new_params)
        return updates, OptState(state.count + 1,
                                 {"opt": new_inner, "ema": new_ema})

    return Optimizer(init, update)


def ema_params(opt_state: OptState):
    """The averaged params from a ``with_ema`` optimizer's state (debias
    honored as configured at construction)."""
    try:
        state = opt_state.inner["ema"]
    except (TypeError, KeyError):
        raise ValueError("opt_state does not carry an EMA (build the "
                         "optimizer with optim.with_ema)") from None
    return _value(state)
