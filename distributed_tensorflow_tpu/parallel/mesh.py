"""Device mesh construction — the declarative replacement for device pinning.

The reference pins variables to PS tasks and compute to the local worker via
``tf.train.replica_device_setter`` (reference example.py:133-141).  On TPU,
placement is a *sharding* over a named ``jax.sharding.Mesh``; XLA inserts the
ICI collectives implied by the shardings (SURVEY.md §7 translation table).

Canonical axis names used across the framework:

  ``data``     data parallelism (batch dim)           — ref's only strategy
  ``fsdp``     parameter-sharded data parallelism
  ``tensor``   tensor/model parallelism (hidden dims)
  ``seq``      sequence/context parallelism (ring attention)
  ``pipe``     pipeline stage axis
  ``expert``   expert (MoE) axis

Axes the caller does not ask for simply have size 1, so a PartitionSpec that
mentions them is still valid — this keeps one set of sharding rules working
from a single chip up to a multi-pod mesh.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["MeshConfig", "make_mesh", "data_parallel_mesh", "AXIS_ORDER",
           "named_sharding", "replicated", "local_batch_size"]

# Fixed major-to-minor order: pipe outermost (cross-slice / DCN friendly),
# then the data-like axes, with tensor parallelism innermost so it rides the
# fastest ICI links (scaling-book recipe: TP wants the tightest torus links).
AXIS_ORDER: Sequence[str] = ("pipe", "data", "fsdp", "expert", "seq", "tensor")


class MeshConfig(dict):
    """{axis_name: size} with validation against the device count."""

    def total(self) -> int:
        return math.prod(self.values()) if self else 1


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh. Unspecified => all devices on the ``data`` axis.

    ``axes`` may leave exactly one axis as ``-1`` to absorb the remaining
    devices (like a reshape).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if not axes:
        axes = {"data": n}
    axes = dict(axes)

    wildcard = [k for k, v in axes.items() if v == -1]
    if len(wildcard) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if wildcard:
        known = math.prod(v for v in axes.values() if v != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        axes[wildcard[0]] = n // known

    size = math.prod(axes.values())
    if size != n:
        raise ValueError(
            f"mesh axes {axes} require {size} devices, have {n}")

    unknown = set(axes) - set(AXIS_ORDER)
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; use {AXIS_ORDER}")

    names = tuple(a for a in AXIS_ORDER if a in axes)
    shape = tuple(axes[a] for a in names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """All devices on one ``data`` axis — the reference-parity topology."""
    return make_mesh(None, devices)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """Shorthand: ``named_sharding(mesh, 'data', None)``."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_shards(mesh: Mesh, axes: Sequence[str] = ("data", "fsdp")) -> int:
    """Number of ways the batch dim is split on this mesh."""
    shard = 1
    for a in axes:
        if a in mesh.shape:
            shard *= mesh.shape[a]
    return shard


def round_batch_to_mesh(global_batch: int, mesh: Mesh,
                        axes: Sequence[str] = ("data", "fsdp")) -> int:
    """Smallest batch >= global_batch divisible by the mesh's data shards.

    The reference's batch of 50 (example.py:13) does not shard over 8 chips;
    callers round up (56) rather than silently dropping devices.
    """
    shard = data_shards(mesh, axes)
    return -(-global_batch // shard) * shard


def local_batch_size(global_batch: int, mesh: Mesh,
                     axes: Sequence[str] = ("data", "fsdp")) -> int:
    """Per-process batch share for building host-local input pipelines."""
    shard = 1
    for a in axes:
        if a in mesh.shape:
            shard *= mesh.shape[a]
    if global_batch % shard:
        raise ValueError(
            f"global batch {global_batch} not divisible by data shards {shard}")
    return global_batch // shard
