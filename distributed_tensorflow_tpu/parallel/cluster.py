"""Cluster bootstrap: env-var topology with a single-machine fallback.

TPU-native replacement for the reference's L1/L2 stack
(reference example.py:59-68 env bootstrap; example.py:108-143
``device_and_target()`` building a ``ClusterSpec``, starting a gRPC
``tf.train.Server`` and parking PS processes in ``server.join()``).

Design (SURVEY.md §2d, §7):
  * There is **no parameter server**.  Every process runs the same SPMD
    program; parameters are replicated or sharded via ``jax.sharding`` and
    gradient sync is an XLA collective over ICI — not a per-step gRPC
    variable pull/push.
  * Topology comes from the environment, exactly like the reference, and the
    same script with no env vars set runs single-machine
    (reference example.py:111-113).  New-style vars take priority;
    the reference's legacy names are honoured for drop-in compatibility:

      new                    legacy (reference example.py:59-68)
      COORDINATOR_ADDRESS    first host in WORKER_HOSTS
      NUM_PROCESSES          len(WORKER_HOSTS.split(','))
      PROCESS_ID             TASK_INDEX
      (no role)              JOB_NAME — "ps" processes exit with a warning;
                             collectives have no passive role to park in
                             ``server.join()``.
  * Chief == ``jax.process_index() == 0`` (the reference's
    ``is_chief=(task_index == 0)``, example.py:190 — minus its str/int
    comparison bug, see SURVEY.md §7).
"""
from __future__ import annotations

import dataclasses
import logging
import os
from typing import List, Optional

log = logging.getLogger(__name__)

__all__ = ["ClusterConfig", "LEGACY_PS_EXIT_CODE", "cluster_from_env",
           "initialize", "is_chief", "process_index", "process_count"]

# A legacy JOB_NAME=ps process under the fleet launcher exits with this
# code so the launcher classifies it fatal-with-reason ("role refused")
# instead of restart-looping a process that will never participate.
# 64 == EX_USAGE (sysexits.h): the configuration asked for a role that
# does not exist here.
LEGACY_PS_EXIT_CODE = 64


@dataclasses.dataclass
class ClusterConfig:
    """Resolved multi-process topology. ``num_processes == 1`` => local."""
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    job_name: Optional[str] = None          # legacy role, informational only
    worker_hosts: Optional[List[str]] = None

    @property
    def distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_legacy_ps(self) -> bool:
        return self.job_name == "ps"


def _split_hosts(raw: Optional[str]) -> Optional[List[str]]:
    if not raw:
        return None
    return [h.strip() for h in raw.split(",") if h.strip()]


def cluster_from_env(environ=None) -> ClusterConfig:
    """Resolve topology from env vars; absent vars => single-machine.

    Mirrors the reference's try/except fallback (example.py:59-68) without
    the bare ``except`` or the string-typed ``task_index``.
    """
    env = os.environ if environ is None else environ

    coordinator = env.get("COORDINATOR_ADDRESS")
    workers = _split_hosts(env.get("WORKER_HOSTS"))
    job_name = env.get("JOB_NAME") or None

    def _int(var: str, default: int) -> int:
        raw = env.get(var)
        if raw is None:
            return default
        try:
            return int(raw)
        except ValueError:
            log.warning("env var %s=%r is not an int; using %d", var, raw, default)
            return default

    num_processes = _int("NUM_PROCESSES", len(workers) if workers else 1)
    process_id = _int("PROCESS_ID", _int("TASK_INDEX", 0))

    if coordinator is None and workers:
        # Legacy convention: the first worker is the coordinator.  Chief
        # (task 0) semantics then line up with the reference's
        # ``is_chief=(task_index == 0)`` (example.py:190).
        coordinator = workers[0]

    if coordinator is None:
        return ClusterConfig(job_name=job_name)

    return ClusterConfig(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        job_name=job_name,
        worker_hosts=workers,
    )


_initialized = False


def initialize(config: Optional[ClusterConfig] = None) -> ClusterConfig:
    """Bring up the multi-process JAX runtime (idempotent).

    Single-machine (no topology in env) is a no-op, mirroring the
    reference's local fallback path (example.py:111-113).  A legacy
    ``JOB_NAME=ps`` process gets a warning and is treated as a normal
    participant refusal: there is nothing for it to serve.  Under the
    fleet launcher (``DTTPU_LAUNCHER`` set) the refusal must be LOUD —
    a ps child that merely warned and returned used to exit 0 after
    doing nothing, which the launcher read as a clean completion and
    silently ran the job one host short — so it exits
    ``LEGACY_PS_EXIT_CODE``, which the launcher classifies as
    fatal-with-reason in its report (fleet/launcher.py).
    """
    global _initialized
    if config is None:
        config = cluster_from_env()

    if config.is_legacy_ps:
        if os.environ.get("DTTPU_LAUNCHER"):
            log.error(
                "JOB_NAME=ps refused: the TPU runtime has no "
                "parameter-server role (SURVEY.md §2d); exiting %d so "
                "the launcher reports this host fatal instead of "
                "counting a silent no-op as success.",
                LEGACY_PS_EXIT_CODE)
            raise SystemExit(LEGACY_PS_EXIT_CODE)
        log.warning(
            "JOB_NAME=ps ignored: the TPU runtime has no parameter-server "
            "role (gradient sync is an ICI all-reduce, not a variable push; "
            "see SURVEY.md §2d). This process will not start.")
        return config

    if config.distributed and not _initialized:
        import jax
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        _initialized = True
    return config


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_chief() -> bool:
    """Chief does checkpointing and summary writes (reference example.py:74-76,190)."""
    return process_index() == 0
