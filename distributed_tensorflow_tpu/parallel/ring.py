"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence dimension at all (fixed 64-bit MLP input,
reference example.py:149); this implements the long-context capability the
framework treats as first-class (SURVEY.md §5 long-context row).

Blockwise attention with an online softmax: each device owns one sequence
shard of Q, K, V.  K/V blocks rotate around the ring with
``lax.ppermute`` (ICI neighbor exchange) while every device accumulates
``softmax(QK^T)V`` against the passing blocks using the numerically-stable
running (max, sum) trick — peak memory is O(block²) instead of O(seq²) and
the sequence can exceed one chip's HBM.

Two entry points:
  * ``ring_attention(q, k, v, axis_name=...)`` — call inside an existing
    ``shard_map``/manual region where ``axis_name`` is bound;
  * ``ring_attention_sharded(q, k, v, mesh, seq_axis)`` — wraps itself in a
    partial-manual ``jax.shard_map`` over only the sequence axis (other mesh
    axes stay on the automatic pjit path), so models can drop it into an
    otherwise auto-sharded step.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   kv_valid: Optional[jnp.ndarray] = None,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """q,k,v: local shards [batch, seq_local, heads, head_dim].

    Must be traced where ``axis_name`` is a *manual* (shard_map) axis.
    ``causal=True`` masks by global position, reconstructed from the ring
    rotation: after ``i`` steps, the resident K/V block came from device
    ``(my_index - i) mod ring_size``.  ``kv_valid``: optional
    [batch, seq_local] bool/int padding mask (1 = real token) for the local
    key block; it rotates around the ring alongside K/V.
    """
    ring = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32)
    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    row_max = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    row_sum = jnp.zeros((b, h, sq), jnp.float32)

    q_pos = my_idx * sq + jnp.arange(sq)             # global query positions
    valid0 = (jnp.ones((b, k.shape[1]), jnp.bool_) if kv_valid is None
              else kv_valid.astype(jnp.bool_))

    def step(i, carry):
        acc, row_max, row_sum, k_blk, v_blk, valid_blk = carry
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        logits = jnp.where(valid_blk[:, None, None, :], logits, -jnp.inf)
        if causal:
            src = (my_idx - i) % ring                 # owner of this block
            k_pos = src * sq + jnp.arange(k_blk.shape[1])
            mask = q_pos[:, None] >= k_pos[None, :]   # [sq, sk]
            logits = jnp.where(mask[None, None], logits, -jnp.inf)

        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        # Fully-masked rows keep -inf; guard the exp shift.
        shift = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(row_max),
                                       row_max - shift, -jnp.inf))
        correction = jnp.nan_to_num(correction)
        probs = jnp.exp(logits - shift[..., None])
        probs = jnp.nan_to_num(probs)

        row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        acc = (acc * correction[..., None] +
               jnp.einsum("bhqk,bkhd->bhqd", probs,
                          v_blk.astype(jnp.float32)))

        perm = [(j, (j + 1) % ring) for j in range(ring)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        valid_blk = lax.ppermute(valid_blk, axis_name, perm)
        return acc, new_max, row_sum, k_blk, v_blk, valid_blk

    acc, row_max, row_sum, _, _, _ = lax.fori_loop(
        0, ring, step, (acc, row_max, row_sum, k, v, valid0))
    out = acc / jnp.maximum(row_sum, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                           causal: bool = False, kv_valid=None,
                           scale: Optional[float] = None):
    """Partial-manual wrapper: manual over ``seq_axis`` only, other mesh
    axes (data/tensor/...) remain automatically partitioned by XLA.
    ``kv_valid``: optional [batch, seq] padding mask (1 = real token)."""
    spec = P(None, seq_axis, None, None)
    vspec = P(None, seq_axis)

    def inner(q, k, v, valid):
        return ring_attention(q, k, v, axis_name=seq_axis, causal=causal,
                              kv_valid=valid, scale=scale)

    if kv_valid is None:
        kv_valid = jnp.ones(q.shape[:2], jnp.bool_)
    return shard_map(inner, mesh=mesh,
                         in_specs=(spec, spec, spec, vspec),
                         out_specs=spec,
                         axis_names=frozenset({seq_axis}),
                         check_vma=False)(q, k, v, kv_valid)


# --------------------------------------------------- dtlint graph tier

from ..analysis import graph as _graph_lib  # noqa: E402  (registration)


@_graph_lib.trace_entry("parallel.ring", hbm_budget=8 << 20)
def _graph_entries():
    """Ring attention with q/k/v sharded over ``seq`` — the specs match
    the shard_map's own in_specs, so no DT501 resharding fires and the
    ledger holds exactly the ring traffic: one k/v-block ppermute per
    hop times (seq-1) hops."""
    import jax

    from .mesh import make_mesh

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"seq": n})
    q = jax.ShapeDtypeStruct((2, n * 8, 2, 16), jnp.float32)
    spec = P(None, "seq", None, None)

    def fwd(q, k, v):
        return ring_attention_sharded(q, k, v, mesh=mesh, causal=True)

    return _graph_lib.Target("ring_attention_sharded", fwd, (q, q, q),
                             in_specs=(spec, spec, spec), mesh=mesh)
