"""Parallelism layer: cluster bootstrap, meshes, shardings, collectives."""

from . import (cluster, data_parallel, mesh, pipeline, ring,
               ring_flash, sharding)
from .data_parallel import make_psum_train_step
from .cluster import ClusterConfig, cluster_from_env, initialize, is_chief
from .pipeline import (pipeline_apply, pipeline_rules_spec,
                       pipeline_value_and_grad, stack_pipeline_params)
from .ring import ring_attention, ring_attention_sharded
from .ring_flash import ring_flash_attention, ring_flash_attention_sharded
from .sharding import PartitionRules, shard_pytree
from .mesh import (AXIS_ORDER, data_parallel_mesh, data_shards,
                   local_batch_size, make_mesh, named_sharding, replicated,
                   round_batch_to_mesh)

__all__ = ["cluster", "data_parallel", "make_psum_train_step",
           "mesh", "pipeline", "ring", "ring_flash", "sharding",
           "pipeline_apply", "pipeline_rules_spec", "pipeline_value_and_grad",
           "stack_pipeline_params",
           "ClusterConfig",
           "cluster_from_env", "initialize", "is_chief", "ring_attention",
           "ring_attention_sharded", "ring_flash_attention",
           "ring_flash_attention_sharded", "PartitionRules", "shard_pytree",
           "AXIS_ORDER", "data_parallel_mesh", "data_shards",
           "local_batch_size", "make_mesh", "named_sharding", "replicated",
           "round_batch_to_mesh"]
