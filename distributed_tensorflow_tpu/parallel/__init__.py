"""Parallelism layer: cluster bootstrap, meshes, shardings, collectives."""

from . import cluster, mesh
from .cluster import ClusterConfig, cluster_from_env, initialize, is_chief
from .mesh import (AXIS_ORDER, data_parallel_mesh, data_shards,
                   local_batch_size, make_mesh, named_sharding, replicated,
                   round_batch_to_mesh)

__all__ = ["cluster", "mesh", "ClusterConfig", "cluster_from_env",
           "initialize", "is_chief", "AXIS_ORDER", "data_parallel_mesh",
           "data_shards", "local_batch_size", "make_mesh", "named_sharding",
           "replicated", "round_batch_to_mesh"]
