"""Explicit ``shard_map`` + ``psum`` sync data parallelism.

The north-star translation of the reference's PS architecture
(BASELINE.json; SURVEY.md §2c): each replica computes gradients on its batch
shard and the mean is taken with ONE ``lax.pmean`` all-reduce over the ICI
``data`` axis — replacing the per-step variable pull / async gradient push
gRPC round-trips of `replica_device_setter` training (reference
example.py:133-141, §3.1 hot loop).

Two spellings of the same computation exist in this framework:
  * ``train.make_train_step(mesh=...)`` — the pjit/global-view spelling:
    the loss is a global-batch mean and XLA's partitioner inserts the
    all-reduce implied by the shardings (preferred; composes with tp/sp/pp);
  * this module — the explicit per-replica spelling with a visible
    ``pmean``, mirroring how pmap-era training loops were written and
    serving as the numerical cross-check of the pjit path
    (tests/test_parallel.py::test_psum_spelling_matches_pjit_step).

Per-replica RNG: the dropout key is folded with BOTH the global step and the
replica index (SURVEY.md §7 "Dropout determinism"), so replicas draw
independent masks while remaining resume-deterministic.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

from ..ops import losses as loss_lib
from ..ops import metrics as metric_lib
from ..optim import optimizers as opt_lib

__all__ = ["make_psum_train_step"]


def make_psum_train_step(model, loss, optimizer: opt_lib.Optimizer,
                         mesh: Mesh, axis: str = "data",
                         metric_fns: Optional[Dict[str, Any]] = None,
                         seed: int = 0,
                         per_replica_rng: bool = True) -> Callable:
    """Build ``step(state, (x, y)) -> (new_state, metrics)``.

    ``state`` is replicated; the batch is sharded over ``axis``.  Inside the
    ``shard_map`` every replica runs forward/backward on its shard, then
    ``lax.pmean`` reduces gradients and metrics — parameters stay bit-
    identical across replicas without a parameter server.

    ``per_replica_rng=False`` gives every replica the same dropout key —
    only useful for numerical parity tests against a single-device run.
    """
    from ..train.session import TrainState

    loss_value_fn = loss_lib.get(loss)
    base_key = jax.random.PRNGKey(seed)

    def replica_step(state: TrainState, batch):
        x, y = batch
        rng = jax.random.fold_in(base_key, state.step)
        if per_replica_rng:
            rng = jax.random.fold_in(rng, lax.axis_index(axis))

        def compute(params):
            preds, new_model_state = model.apply(params, state.model_state,
                                                 x, train=True, rng=rng)
            metrics = {name: metric_lib.get(fn)(preds, y)
                       for name, fn in (metric_fns or {}).items()}
            return loss_value_fn(preds, y), (metrics, new_model_state)

        (loss_value, (metrics, new_model_state)), grads = jax.value_and_grad(
            compute, has_aux=True)(state.params)

        # THE all-reduce: grad/metric mean over the data axis (equal shard
        # sizes => identical to the global-batch mean of the pjit spelling).
        grads = lax.pmean(grads, axis)
        metrics = lax.pmean({"loss": loss_value, **metrics}, axis)

        updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
        new_params = opt_lib.apply_updates(state.params, updates)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt_state,
                          model_state=new_model_state), metrics

    sharded = shard_map(
        replica_step, mesh=mesh,
        in_specs=(P(), (P(axis), P(axis))),
        out_specs=(P(), P()),
        axis_names=frozenset({axis}),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=0)


# --------------------------------------------------- dtlint graph tier

from ..analysis import graph as _graph_lib  # noqa: E402  (registration)


@_graph_lib.trace_entry("parallel.data_parallel", hbm_budget=8 << 20)
def _graph_entries():
    """The psum-spelled data-parallel step on a tiny MLP, seeded with
    the specs callers actually use (state replicated, batch sharded
    over ``data``), so the DT5xx ledger prices THE all-reduce: one
    grad/metric pmean over the data axis per step."""
    import jax
    import jax.numpy as jnp

    from .. import ops
    from ..optim import adam
    from ..train import init_train_state
    from .mesh import make_mesh

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"data": n})
    model = ops.serial(ops.Dense(32, "relu"), ops.Dense(8, "sigmoid"))
    optimizer = adam()
    step = make_psum_train_step(model, "mse", optimizer, mesh)
    state = jax.eval_shape(
        lambda k: init_train_state(model, optimizer, k, (64,)),
        jax.random.PRNGKey(0))
    batch = (jax.ShapeDtypeStruct((n * 4, 64), jnp.float32),
             jax.ShapeDtypeStruct((n * 4, 8), jnp.float32))
    return _graph_lib.Target(
        "make_psum_train_step", step, (state, batch),
        in_specs=(P(), (P("data"), P("data"))), mesh=mesh)
