"""Partition-rule machinery: regex path -> PartitionSpec for param pytrees.

The declarative replacement for the reference's ``replica_device_setter``
(reference example.py:133-141): instead of pinning variables to PS tasks, a
rule table maps parameter *paths* to ``PartitionSpec``s over named mesh axes.
One rule set covers every mesh size because absent axes have size 1.

Conventions (scaling-book recipe):
  * ``tensor`` shards hidden/head dims (megatron-style: column-parallel
    first matmul, row-parallel second);
  * ``fsdp`` optionally shards the remaining large dim of each matrix
    (zero-3 style) — applied via ``fsdp_rules``;
  * everything unmatched is replicated (P()).
"""
from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["PartitionRules", "tree_paths", "shard_pytree",
           "logical_to_mesh", "prune_spec"]

Rules = Sequence[Tuple[str, P]]


def prune_spec(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh doesn't have (-> replicated on that dim).

    Lets ONE rule table serve every mesh: a spec like
    ``P(None, 'fsdp', 'tensor')`` on a data-only mesh simply degrades to
    ``P(None, None, None)``.
    """
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, str):
            return entry if entry in names else None
        kept = tuple(a for a in entry if a in names)
        return kept if kept else None

    return P(*(keep(e) for e in spec))


def tree_paths(tree) -> List[str]:
    """'/'-joined dict-key paths for every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, _leaf in flat:
        parts = []
        for entry in path:
            if hasattr(entry, "key"):
                parts.append(str(entry.key))
            elif hasattr(entry, "idx"):
                parts.append(str(entry.idx))
            else:
                parts.append(str(entry))
        out.append("/".join(parts))
    return out


class PartitionRules:
    """Ordered (regex, PartitionSpec) table; first match wins."""

    def __init__(self, rules: Rules):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()

    def tree_specs(self, params) -> Any:
        """Same-structure pytree of PartitionSpecs."""
        flat, treedef = jax.tree_util.tree_flatten(params)
        paths = tree_paths(params)
        return jax.tree_util.tree_unflatten(
            treedef, [self.spec_for(p) for p in paths])

    def tree_shardings(self, mesh: Mesh, params) -> Any:
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, prune_spec(spec, mesh)),
            self.tree_specs(params),
            is_leaf=lambda v: isinstance(v, P))


def shard_pytree(params, mesh: Mesh, rules: PartitionRules):
    """device_put a param pytree according to the rule table."""
    return jax.device_put(params, rules.tree_shardings(mesh, params))


def logical_to_mesh(specs, mesh: Mesh):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh``."""
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs,
                        is_leaf=lambda v: isinstance(v, P))
