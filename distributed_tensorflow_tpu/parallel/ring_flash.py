"""Ring attention composed with the fused Pallas flash kernel.

``parallel.ring`` gives sequence parallelism (each device owns one
sequence shard; K/V blocks rotate over ICI with ``ppermute``) but
computes each block pair with dense XLA attention — materialising
[b, h, sq_local, sk_local] logits per step.  This module runs the SAME
ring schedule with the validated flash kernel per block pair, merging
block outputs by their row logsumexp — i.e. ring-flash attention, the
long-context configuration where both levers stack: O(block) memory
inside each device AND sequence sharding across devices.

Correctness structure (the standard ring-flash derivation):
 * forward: each block call returns (out_i, lse_i) where ``out_i`` is
   softmax-normalised within the block; the running merge
   ``out = Σ_i exp(lse_i - lse_tot) out_i`` reconstructs the global
   softmax exactly.
 * backward: with the GLOBAL ``lse`` (and global D = rowsum(dO·O)), the
   per-block flash backward recovers exactly this block's contribution
   to dq and the block's own dk/dv — so the ring runs again, rotating
   the K/V blocks WITH their gradient accumulators; after a full loop
   each accumulator is home.

Off-TPU the kernels run in interpret mode, so the CPU mesh tests cover
the identical code path (reference: /root/reference has no attention at
all — SURVEY.md §5 long-context row; this is framework-native scope).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

from ..ops.pallas.common import use_interpret as _use_interpret
from ..ops.pallas.flash_attention import _flash_backward, _flash_forward

__all__ = ["ring_flash_attention", "ring_flash_attention_sharded"]


def _rel_index(src, my, causal: bool):
    """0 = block fully visible, 1 = diagonal (aligned causal), 2 = skip."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(src < my, jnp.int32(0),
                     jnp.where(src == my, jnp.int32(1), jnp.int32(2)))


def _block_fwd(q, k_blk, v_blk, valid_blk, rel, scale, bq, bk, interpret):
    def full(_):
        return _flash_forward(q, k_blk, v_blk, valid_blk, scale, False,
                              bq, bk, interpret)

    def diag(_):
        return _flash_forward(q, k_blk, v_blk, valid_blk, scale, True,
                              bq, bk, interpret)

    def skip(_):
        b, h, sq, d = q.shape
        return (jnp.zeros((b, h, sq, d), q.dtype),
                jnp.full((b, h, sq), -jnp.inf, jnp.float32))

    return lax.switch(rel, (full, diag, skip), None)


def _block_bwd(q, k_blk, v_blk, valid_blk, out, lse, do, dvec, rel,
               scale, bq, bk, interpret):
    def full(_):
        return _flash_backward(q, k_blk, v_blk, valid_blk, out, lse, do,
                               scale, False, bq, bk, interpret, dvec=dvec)

    def diag(_):
        return _flash_backward(q, k_blk, v_blk, valid_blk, out, lse, do,
                               scale, True, bq, bk, interpret, dvec=dvec)

    def skip(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k_blk),
                jnp.zeros_like(v_blk))

    return lax.switch(rel, (full, diag, skip), None)


def _rotate(x, axis_name, ring):
    perm = [(j, (j + 1) % ring) for j in range(ring)]
    return lax.ppermute(x, axis_name, perm)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, valid, axis_name, causal, scale, block_q,
                block_k, interpret):
    out, _ = _ring_flash_fwd_loop(q, k, v, valid, axis_name, causal,
                                  scale, block_q, block_k, interpret)
    return out.astype(q.dtype)


def _ring_flash_fwd_loop(q, k, v, valid, axis_name, causal, scale,
                         block_q, block_k, interpret):
    ring = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    out = jnp.zeros((b, h, sq, d), jnp.float32)
    lse = jnp.full((b, h, sq), -jnp.inf, jnp.float32)

    def step(i, carry):
        out, lse, k_blk, v_blk, valid_blk = carry
        src = (my - i) % ring
        rel = _rel_index(src, my, causal)
        o_i, lse_i = _block_fwd(q, k_blk, v_blk, valid_blk, rel, scale,
                                block_q, block_k, interpret)
        new_lse = jnp.logaddexp(lse, lse_i)
        # fully-masked rows stay -inf; guard the exp shifts
        w_old = jnp.exp(jnp.where(jnp.isfinite(new_lse), lse - new_lse,
                                  -jnp.inf))
        w_new = jnp.exp(jnp.where(jnp.isfinite(new_lse), lse_i - new_lse,
                                  -jnp.inf))
        out = (out * jnp.nan_to_num(w_old)[..., None]
               + o_i.astype(jnp.float32)
               * jnp.nan_to_num(w_new)[..., None])
        return (out, new_lse, _rotate(k_blk, axis_name, ring),
                _rotate(v_blk, axis_name, ring),
                _rotate(valid_blk, axis_name, ring))

    out, lse, _, _, _ = lax.fori_loop(0, ring, step,
                                      (out, lse, k, v, valid))
    return out, lse


def _ring_flash_fwd(q, k, v, valid, axis_name, causal, scale, block_q,
                    block_k, interpret):
    out, lse = _ring_flash_fwd_loop(q, k, v, valid, axis_name, causal,
                                    scale, block_q, block_k, interpret)
    # residual in the INPUT dtype (the f32 merge accumulator would double
    # this residual's memory for bf16 models — the backward upcasts where
    # it matters: D = rowsum(dO·O) in f32); matches the non-ring flash
    # path, which saves the kernel-dtype out.
    out = out.astype(q.dtype)
    return out, (q, k, v, valid, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, interpret,
                    res, g):
    q, k, v, valid, out, lse = res
    ring = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    do = g
    # D = rowsum(dO·O) is identical for every K/V block — compute once,
    # not once per ring step
    dvec = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1)

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_rot = jnp.zeros(k.shape, jnp.float32)
    dv_rot = jnp.zeros(v.shape, jnp.float32)

    def step(i, carry):
        dq, dk_rot, dv_rot, k_blk, v_blk, valid_blk = carry
        src = (my - i) % ring
        rel = _rel_index(src, my, causal)
        dq_i, dk_i, dv_i = _block_bwd(q, k_blk, v_blk, valid_blk, out,
                                      lse, do, dvec, rel, scale, block_q,
                                      block_k, interpret)
        dq = dq + dq_i.astype(jnp.float32)
        dk_rot = dk_rot + dk_i.astype(jnp.float32)
        dv_rot = dv_rot + dv_i.astype(jnp.float32)
        # gradient accumulators travel WITH their k/v blocks: after the
        # full ring both are back at the owning device
        return (dq, _rotate(dk_rot, axis_name, ring),
                _rotate(dv_rot, axis_name, ring),
                _rotate(k_blk, axis_name, ring),
                _rotate(v_blk, axis_name, ring),
                _rotate(valid_blk, axis_name, ring))

    dq, dk_rot, dv_rot, _, _, _ = lax.fori_loop(
        0, ring, step, (dq, dk_rot, dv_rot, k, v, valid))
    return (dq.astype(q.dtype), dk_rot.astype(k.dtype),
            dv_rot.astype(v.dtype), jnp.zeros_like(valid))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         kv_valid=None, scale: Optional[float] = None,
                         block_q: int = 512, block_k: int = 1024,
                         interpret: Optional[bool] = None):
    """Flash-kernel ring attention over a manual (shard_map) mesh axis.

    q, k, v: local shards [batch, seq_local, heads, head_dim] (the
    framework-wide head layout); ``kv_valid``: optional
    [batch, seq_local] padding mask for the local key block (1 = real),
    rotating with K/V.  Same contract as ``ring.ring_attention``.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _use_interpret()
    valid = (jnp.ones((k.shape[0], k.shape[1]), jnp.float32)
             if kv_valid is None else kv_valid.astype(jnp.float32))
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _ring_flash(qt, kt, vt, valid, axis_name, bool(causal),
                      float(scale), int(block_q), int(block_k),
                      bool(interpret))
    return jnp.swapaxes(out, 1, 2)


def ring_flash_attention_sharded(q, k, v, mesh: Mesh,
                                 seq_axis: str = "seq",
                                 causal: bool = False, kv_valid=None,
                                 scale: Optional[float] = None,
                                 block_q: int = 512, block_k: int = 1024):
    """Partial-manual wrapper mirroring ``ring.ring_attention_sharded``:
    manual over ``seq_axis`` only; other mesh axes stay on the automatic
    pjit path."""
    spec = P(None, seq_axis, None, None)
    vspec = P(None, seq_axis)

    def inner(q, k, v, valid):
        return ring_flash_attention(q, k, v, axis_name=seq_axis,
                                    causal=causal, kv_valid=valid,
                                    scale=scale, block_q=block_q,
                                    block_k=block_k)

    if kv_valid is None:
        kv_valid = jnp.ones(q.shape[:2], jnp.bool_)
    return shard_map(inner, mesh=mesh,
                         in_specs=(spec, spec, spec, vspec),
                         out_specs=spec,
                         axis_names=frozenset({seq_axis}),
                         check_vma=False)(q, k, v, kv_valid)


# --------------------------------------------------- dtlint graph tier

from ..analysis import graph as _graph_lib  # noqa: E402  (registration)


@_graph_lib.trace_entry("parallel.ring_flash", hbm_budget=8 << 20)
def _graph_entries():
    """The fused-kernel ring: same sharding contract as parallel.ring
    (specs match the shard_map in_specs — no implicit resharding), the
    kernel body opaque to propagation (degrades to unknown, per the
    tier's contract) while the ring ppermutes around it still price."""
    import jax

    from .mesh import make_mesh

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"seq": n})
    q = jax.ShapeDtypeStruct((2, n * 8, 2, 16), jnp.float32)
    spec = P(None, "seq", None, None)

    def fwd(q, k, v):
        return ring_flash_attention_sharded(q, k, v, mesh=mesh,
                                            causal=True, block_q=8,
                                            block_k=8)

    return _graph_lib.Target("ring_flash_attention_sharded", fwd,
                             (q, q, q), in_specs=(spec, spec, spec),
                             mesh=mesh)
