"""Pipeline parallelism: GPipe-style microbatched stages over a ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY.md §2c: "no stage
partitioning anywhere"); this supplies the strategy TPU-natively so the one
framework covers dp/fsdp/tp/sp/pp/ep on a single named Mesh.

Design (TPU-first, not a port of any PS/NCCL scheme):
  * every stage runs the SAME compiled program under ``shard_map`` manual
    over the ``pipe`` axis — SPMD, no per-stage executables, no host-side
    scheduler process;
  * stage parameters are stacked on a leading axis and sharded
    ``P('pipe')``, so each device holds exactly its stage's weights;
  * activations move stage-to-stage with ``lax.ppermute`` — a neighbor
    exchange that rides ICI, never the host;
  * the schedule is a ``lax.scan`` over ``num_microbatches + num_stages - 1``
    ticks (the classic GPipe fill/steady/drain trapezoid).  Backward is not
    hand-scheduled: JAX autodiff transposes the scan+ppermute program into
    the reverse pipeline automatically, which XLA overlaps the same way.

Constraint of this formulation: every stage maps activations of one shape to
activations of the SAME shape (transformer-block style).  Embed before the
pipeline, project after — see tests/test_pipeline.py for the usage pattern.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_pipeline_params", "pipeline_rules_spec"]


def stack_pipeline_params(stage_params: Sequence[Any]):
    """Stack per-stage param pytrees on a new leading ``pipe`` axis.

    All stages must share one tree structure/shapes (same-shape stages are
    already required by the schedule).  Shard the result with ``P('pipe')``
    on every leaf (``pipeline_rules_spec``).
    """
    return jax.tree.map(lambda *ps: jnp.stack(ps), *stage_params)


def pipeline_rules_spec(stacked_params, axis: str = "pipe"):
    """Same-structure pytree of ``P(axis)`` specs for the stacked params."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params, x: jnp.ndarray, mesh: Mesh,
                   num_microbatches: int, axis: str = "pipe") -> jnp.ndarray:
    """Run ``x`` through ``num_stages`` copies of ``stage_fn`` as a pipeline.

    ``stage_fn(params_for_one_stage, acts) -> acts`` (same shape in/out).
    ``stacked_params``: leaves with leading dim == mesh.shape[axis]
    (see ``stack_pipeline_params``); pass them in already sharded
    ``P('pipe')`` or let shard_map slice them.
    ``x``: [global_batch, ...] — must divide by ``num_microbatches``.

    Returns [global_batch, ...] outputs, replicated over the pipe axis
    (a masked ``psum`` broadcast from the last stage).  Differentiable:
    ``jax.grad`` through this IS the backward pipeline.
    """
    n_stages = mesh.shape[axis]
    leading = {p.shape[0] for p in jax.tree.leaves(stacked_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stacked params have leading dim(s) {sorted(leading)} but the "
            f"'{axis}' mesh axis has {n_stages} stages — shard_map would "
            "silently drop stages")
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_microbatches} "
            "microbatches")
    mb = x.shape[0] // num_microbatches
    n_ticks = num_microbatches + n_stages - 1

    # Activation dtype for the scan carry: a stage may promote (bf16 batch
    # through f32 params -> f32 activations), and lax.scan requires a fixed
    # carry dtype — resolve the promotion once, outside the trace.
    one_stage = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype), stacked_params)
    mb_in = jax.ShapeDtypeStruct((mb, *x.shape[1:]), x.dtype)
    act_dtype = jnp.result_type(
        x.dtype, jax.eval_shape(stage_fn, one_stage, mb_in).dtype)

    def inner(params, x):
        # shard_map hands each device a leading pipe-dim of 1 — drop it.
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        mbs = x.reshape(num_microbatches, mb, *x.shape[1:])

        shift_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def tick(carry, t):
            state, buf = carry
            # Stage 0 injects microbatch t (clamped repeat once drained —
            # its outputs past t==M-1 never land in ``buf``); later stages
            # consume what arrived over the ring last tick.
            feed = mbs[jnp.clip(t, 0, num_microbatches - 1)]
            inp = jnp.where(is_first, feed.astype(act_dtype), state)
            out = stage_fn(params, inp).astype(act_dtype)
            # The last stage banks microbatch ``t - (n_stages-1)`` once the
            # pipeline has filled; O(1) slot-sized select, not a full-buffer
            # copy.
            slot = t - (n_stages - 1)
            write = is_last & (slot >= 0)
            slot_c = jnp.clip(slot, 0, num_microbatches - 1)
            buf = buf.at[slot_c].set(jnp.where(write, out, buf[slot_c]))
            state = lax.ppermute(out, axis, shift_perm)
            return (state, buf), None

        state0 = jnp.zeros((mb, *x.shape[1:]), act_dtype)
        buf0 = jnp.zeros((num_microbatches, mb, *x.shape[1:]), act_dtype)
        (_, buf), _ = lax.scan(tick, (state0, buf0), jnp.arange(n_ticks))
        # Broadcast the last stage's result to every stage (masked psum) so
        # the caller sees a pipe-replicated output.
        out = lax.psum(jnp.where(is_last, buf, 0.0), axis)
        return out.reshape(x.shape[0], *x.shape[1:])

    return jax.shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_params), P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False)(stacked_params, x)
