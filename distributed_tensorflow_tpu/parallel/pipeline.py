"""Pipeline parallelism: GPipe-style microbatched stages over a ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY.md §2c: "no stage
partitioning anywhere"); this supplies the strategy TPU-natively so the one
framework covers dp/fsdp/tp/sp/pp/ep on a single named Mesh.

Design (TPU-first, not a port of any PS/NCCL scheme):
  * every stage runs the SAME compiled program under ``shard_map`` manual
    over the ``pipe`` axis — SPMD, no per-stage executables, no host-side
    scheduler process;
  * stage parameters are stacked on a leading axis and sharded
    ``P('pipe')``, so each device holds exactly its stage's weights;
  * activations move stage-to-stage with ``lax.ppermute`` — a neighbor
    exchange that rides ICI, never the host;
  * the schedule is a ``lax.scan`` over ``num_microbatches + num_stages - 1``
    ticks (the classic GPipe fill/steady/drain trapezoid).  Backward is not
    hand-scheduled: JAX autodiff transposes the scan+ppermute program into
    the reverse pipeline automatically, which XLA overlaps the same way.

Constraint of this formulation: every stage maps activations of one shape to
activations of the SAME shape (transformer-block style).  Embed before the
pipeline, project after — see tests/test_pipeline.py for the usage pattern.

Known backend limitation (NOT a bug here): XLA:CPU miscompiles some of
these scan+ppermute programs with **bfloat16** activations — a fatal
"Invalid binary instruction opcode copy" check failure in the compiler
(seen in the GPipe autodiff transpose and in a jitted pipelined forward on
a pipe×data mesh; hand-scheduled 1F1B training compiles).  Use f32
activations for pp work on the CPU test rig (examples/train_gpt.py does
this automatically); TPU is the real target.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

__all__ = ["pipeline_apply", "stack_pipeline_params", "pipeline_rules_spec",
           "pipeline_value_and_grad"]


def stack_pipeline_params(stage_params: Sequence[Any]):
    """Stack per-stage param pytrees on a new leading ``pipe`` axis.

    All stages must share one tree structure/shapes (same-shape stages are
    already required by the schedule).  Shard the result with ``P('pipe')``
    on every leaf (``pipeline_rules_spec``).
    """
    return jax.tree.map(lambda *ps: jnp.stack(ps), *stage_params)


def pipeline_rules_spec(stacked_params, axis: str = "pipe"):
    """Same-structure pytree of ``P(axis)`` specs for the stacked params."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params, x: jnp.ndarray, mesh: Mesh,
                   num_microbatches: int, axis: str = "pipe") -> jnp.ndarray:
    """Run ``x`` through ``num_stages`` copies of ``stage_fn`` as a pipeline.

    ``stage_fn(params_for_one_stage, acts) -> acts`` (same shape in/out).
    ``stacked_params``: leaves with leading dim == mesh.shape[axis]
    (see ``stack_pipeline_params``); pass them in already sharded
    ``P('pipe')`` or let shard_map slice them.
    ``x``: [global_batch, ...] — must divide by ``num_microbatches``.

    Returns [global_batch, ...] outputs, replicated over the pipe axis
    (a masked ``psum`` broadcast from the last stage).  Differentiable:
    ``jax.grad`` through this IS the backward pipeline.
    """
    n_stages = mesh.shape[axis]
    leading = {p.shape[0] for p in jax.tree.leaves(stacked_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stacked params have leading dim(s) {sorted(leading)} but the "
            f"'{axis}' mesh axis has {n_stages} stages — shard_map would "
            "silently drop stages")
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_microbatches} "
            "microbatches")
    mb = x.shape[0] // num_microbatches
    n_ticks = num_microbatches + n_stages - 1

    # Activation dtype for the scan carry: a stage may promote (bf16 batch
    # through f32 params -> f32 activations), and lax.scan requires a fixed
    # carry dtype — resolve the promotion once, outside the trace.
    one_stage = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype), stacked_params)
    mb_in = jax.ShapeDtypeStruct((mb, *x.shape[1:]), x.dtype)
    act_dtype = jnp.result_type(
        x.dtype, jax.eval_shape(stage_fn, one_stage, mb_in).dtype)

    def inner(params, x):
        # shard_map hands each device a leading pipe-dim of 1 — drop it.
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        mbs = x.reshape(num_microbatches, mb, *x.shape[1:])

        shift_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def tick(carry, t):
            state, buf = carry
            # Stage 0 injects microbatch t (clamped repeat once drained —
            # its outputs past t==M-1 never land in ``buf``); later stages
            # consume what arrived over the ring last tick.
            feed = mbs[jnp.clip(t, 0, num_microbatches - 1)]
            inp = jnp.where(is_first, feed.astype(act_dtype), state)
            out = stage_fn(params, inp).astype(act_dtype)
            # The last stage banks microbatch ``t - (n_stages-1)`` once the
            # pipeline has filled; O(1) slot-sized select, not a full-buffer
            # copy.
            slot = t - (n_stages - 1)
            write = is_last & (slot >= 0)
            slot_c = jnp.clip(slot, 0, num_microbatches - 1)
            buf = buf.at[slot_c].set(jnp.where(write, out, buf[slot_c]))
            state = lax.ppermute(out, axis, shift_perm)
            return (state, buf), None

        state0 = jnp.zeros((mb, *x.shape[1:]), act_dtype)
        buf0 = jnp.zeros((num_microbatches, mb, *x.shape[1:]), act_dtype)
        (_, buf), _ = lax.scan(tick, (state0, buf0), jnp.arange(n_ticks))
        # Broadcast the last stage's result to every stage (masked psum) so
        # the caller sees a pipe-replicated output.
        out = lax.psum(jnp.where(is_last, buf, 0.0), axis)
        return out.reshape(x.shape[0], *x.shape[1:])

    return shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_params), P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False)(stacked_params, x)


def pipeline_value_and_grad(stage_fn: Callable[[Any, jnp.ndarray],
                                               jnp.ndarray],
                            loss_fn: Callable[[jnp.ndarray, jnp.ndarray],
                                              jnp.ndarray],
                            stacked_params, x: jnp.ndarray, y: jnp.ndarray,
                            mesh: Mesh, num_microbatches: int,
                            axis: str = "pipe",
                            aux_params: Any = None,
                            with_dx: bool = False,
                            microbatch_weights: Any = None):
    """Hand-scheduled **1F1B** pipeline training pass -> ``(loss, grads)``.

    GPipe via ``jax.grad(pipeline_apply)`` runs all M forwards, then all M
    backwards — autodiff keeps every microbatch's residuals live, so
    activation memory grows O(M).  The 1F1B schedule (PipeDream-flush /
    Megatron) starts each microbatch's backward as soon as its forward
    clears the last stage, holding at most ``2*num_stages - 1`` microbatch
    inputs in flight — O(S), independent of M.  Residuals are not stored at
    all: the backward tick RECOMPUTES its stage forward from the stashed
    stage INPUT under ``jax.vjp`` (same FLOPs as GPipe-with-remat, which is
    how pipelines run in practice anyway).

    Schedule (lockstep SPMD, one fwd + one bwd sub-tick per tick): stage
    ``s`` forwards microbatch ``m`` at tick ``m + s`` (activations ppermute
    down the ring) and backwards it at tick ``m + 2(S-1) - s`` (cotangents
    ppermute back up), so the last stage's backward fires the very tick its
    forward completes — the "1F1B" interleave.  Total ``M + 2S - 2`` ticks.

    ``loss_fn(out_mb, y_mb) -> scalar`` (a per-microbatch mean); the
    returned loss is the mean over microbatches and the grads are exactly
    ``d(loss)/d(stacked_params)``, sharded ``P(axis)`` like the params.
    The last stage seeds both its own cotangent and the loss value through
    ONE combined ``jax.vjp`` over ``(out, loss)``, so every stage runs an
    identical program — no per-device branching.

    Full-model integration hooks (what lets a MODEL — embeddings before the
    pipeline, a head inside the loss — train under 1F1B, not just the
    stages):

      * ``aux_params``: extra pytree differentiated THROUGH the loss —
        ``loss_fn(aux_params, out_mb, y_mb)`` when given.  Returns their
        grads (pipe-replicated psum; only the last stage's loss touches
        them) appended to the result: the tied LM head / final-LN case.
      * ``with_dx=True``: also return ``d(loss)/d(x)`` — stage 0's input
        cotangents banked per microbatch — so the caller can chain
        ``jax.vjp`` through whatever produced ``x`` (embeddings).

    ``y`` may be any pytree whose leaves share the batch leading dim (e.g.
    ``{"targets": ..., "mask": ...}``); ``loss_fn`` receives the matching
    microbatch slice.  ``microbatch_weights``: optional [M] f32 summing to
    1 — the per-microbatch contribution to the total loss/gradient.  A
    MASKED-mean loss needs this: per-microbatch masked means averaged
    uniformly are NOT the global masked mean when mask counts differ, so
    pass each microbatch's normalizer share (mask-sum / total).  Default
    uniform 1/M is exact for plain-mean losses.

    Returns ``(loss, grads[, aux_grads][, dx])``.
    """
    n_stages = mesh.shape[axis]
    leading = {p.shape[0] for p in jax.tree.leaves(stacked_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stacked params have leading dim(s) {sorted(leading)} but the "
            f"'{axis}' mesh axis has {n_stages} stages")
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_microbatches} "
            "microbatches")
    mb = x.shape[0] // num_microbatches
    n_ticks = num_microbatches + 2 * (n_stages - 1)
    n_slots = min(num_microbatches, 2 * n_stages - 1)

    one_stage = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype), stacked_params)
    mb_in = jax.ShapeDtypeStruct((mb, *x.shape[1:]), x.dtype)
    act_dtype = jnp.result_type(
        x.dtype, jax.eval_shape(stage_fn, one_stage, mb_in).dtype)

    has_aux = aux_params is not None

    def inner(params, x, y, aux, weights):
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        mbs = x.reshape(num_microbatches, mb, *x.shape[1:])
        mbs_y = jax.tree.map(
            lambda a: a.reshape(num_microbatches, a.shape[0]
                                // num_microbatches, *a.shape[1:]), y)

        fwd_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        bwd_perm = [(j, (j - 1) % n_stages) for j in range(n_stages)]

        # Differentiate only floating leaves: integer leaves (e.g. stacked
        # PRNG keys riding in the stage params) as vjp PRIMALS trip an
        # unimplemented ShardMapTracer path — close over them instead
        # (same-body closure, which shard_map allows).
        p_leaves, p_tdef = jax.tree_util.tree_flatten(params)
        p_isdiff = [jnp.issubdtype(l.dtype, jnp.floating) for l in p_leaves]
        p_diff = [l for l, d in zip(p_leaves, p_isdiff) if d]

        def rebuild(diff_leaves):
            it = iter(diff_leaves)
            return jax.tree_util.tree_unflatten(
                p_tdef, [next(it) if d else l
                         for l, d in zip(p_leaves, p_isdiff)])

        def fwd_and_loss(dl, xin, a, y_mb):
            # cast as the forward sub-tick does: the vjp's `out` cotangent
            # must be act_dtype or mixed-precision stages (bf16 compute on
            # f32 carries) reject the incoming bwd_state
            out = stage_fn(rebuild(dl), xin).astype(act_dtype)
            loss = (loss_fn(a, out, y_mb) if has_aux
                    else loss_fn(out, y_mb))
            return out, loss.astype(jnp.float32)

        def tick(carry, t):
            fwd_state, bwd_state, stash, gacc, ga_acc, dx_buf, loss_sum = \
                carry

            # ---- F sub-tick: stage s forwards microbatch t - s ----------
            mf = t - idx
            active_f = (mf >= 0) & (mf < num_microbatches)
            feed = mbs[jnp.clip(mf, 0, num_microbatches - 1)]
            xin = jnp.where(is_first, feed.astype(act_dtype), fwd_state)
            out = stage_fn(params, xin).astype(act_dtype)
            slot_f = jnp.clip(mf, 0, num_microbatches - 1) % n_slots
            stash = stash.at[slot_f].set(
                jnp.where(active_f, xin, stash[slot_f]))
            fwd_state = lax.ppermute(out, axis, fwd_perm)

            # ---- B sub-tick: stage s backwards t - 2(S-1) + s -----------
            mb_i = t - 2 * (n_stages - 1) + idx
            active_b = (mb_i >= 0) & (mb_i < num_microbatches)
            mb_c = jnp.clip(mb_i, 0, num_microbatches - 1)
            xin_b = stash[mb_c % n_slots]
            y_mb = jax.tree.map(lambda a: a[mb_c], mbs_y)
            (out_b, loss_b), vjp = jax.vjp(
                lambda dl, x_, a: fwd_and_loss(dl, x_, a, y_mb),
                p_diff, xin_b, aux)
            del out_b
            # last stage: seed this microbatch's share of d(loss); others:
            # incoming cotangent on out
            seed = weights[mb_c]
            g_out = jnp.where(is_last, jnp.zeros_like(bwd_state), bwd_state)
            g_loss = jnp.where(is_last, seed, jnp.float32(0.0))
            gp, gx, ga = vjp((g_out, g_loss))

            def acc(mask):
                def f(a_, g):
                    if g.dtype == jax.dtypes.float0:   # non-diff aux leaf
                        return a_
                    return a_ + jnp.where(mask, g, 0.0).astype(a_.dtype)
                return f

            gacc = jax.tree.map(acc(active_b), gacc, gp)
            # aux (loss-side) grads are nonzero only where g_loss seeds —
            # the last stage; accumulate there, psum-broadcast at the end
            ga_acc = jax.tree.map(acc(is_last & active_b), ga_acc, ga)
            if with_dx:
                # stage 0's input cotangent IS d(loss)/d(x[microbatch]) —
                # bank it (same slot trick as the forward output buffer;
                # act_dtype: each slot is written once, nothing accumulates)
                dx_buf = dx_buf.at[mb_c].set(
                    jnp.where(is_first & active_b, gx.astype(act_dtype),
                              dx_buf[mb_c]))
            bwd_state = lax.ppermute(gx.astype(act_dtype), axis, bwd_perm)
            loss_sum = loss_sum + jnp.where(
                is_last & active_b, loss_b, 0.0) * seed
            return (fwd_state, bwd_state, stash, gacc, ga_acc, dx_buf,
                    loss_sum), None

        fwd0 = jnp.zeros((mb, *x.shape[1:]), act_dtype)
        stash0 = jnp.zeros((n_slots, mb, *x.shape[1:]), act_dtype)
        gacc0 = [jnp.zeros(p.shape, jnp.float32) for p in p_diff]
        ga0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), aux)
        dx0 = jnp.zeros((num_microbatches, mb, *x.shape[1:]), act_dtype
                        ) if with_dx else jnp.zeros((), jnp.float32)
        carry0 = (fwd0, fwd0, stash0, gacc0, ga0, dx0, jnp.float32(0.0))
        (_, _, _, gacc, ga_acc, dx_buf, loss_sum), _ = lax.scan(
            tick, carry0, jnp.arange(n_ticks))
        loss = lax.psum(jnp.where(is_last, loss_sum, 0.0), axis)
        # grads in the full params structure; non-diff leaves get zeros
        g_it = iter(gacc)
        grads = jax.tree_util.tree_unflatten(
            p_tdef,
            [(next(g_it).astype(l.dtype) if d else jnp.zeros_like(l))[None]
             for l, d in zip(p_leaves, p_isdiff)])
        aux_grads = jax.tree.map(
            lambda g, p: lax.psum(jnp.where(is_last, g, 0.0), axis
                                  ).astype(p.dtype), ga_acc, aux)
        dx = (lax.psum(jnp.where(is_first, dx_buf, 0.0), axis
                       ).reshape(x.shape).astype(x.dtype)
              if with_dx else dx_buf)
        return loss, grads, aux_grads, dx

    aux_in = aux_params if has_aux else ()
    w_in = (jnp.full((num_microbatches,), 1.0 / num_microbatches,
                     jnp.float32)
            if microbatch_weights is None
            else jnp.asarray(microbatch_weights, jnp.float32))
    if w_in.shape != (num_microbatches,):
        raise ValueError(
            f"microbatch_weights shape {w_in.shape} != "
            f"({num_microbatches},) — clamp-indexing would silently "
            "mis-scale the loss")
    loss, grads, aux_grads, dx = shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_params), P(),
                  jax.tree.map(lambda _: P(), y),
                  jax.tree.map(lambda _: P(), aux_in), P()),
        out_specs=(P(), jax.tree.map(lambda _: P(axis), stacked_params),
                   jax.tree.map(lambda _: P(), aux_in), P()),
        axis_names=frozenset({axis}),
        check_vma=False)(stacked_params, x, y, aux_in, w_in)
    result = (loss, grads)
    if has_aux:
        result += (aux_grads,)
    if with_dx:
        result += (dx,)
    return result


# --------------------------------------------------- dtlint graph tier

from ..analysis import graph as _graph_lib  # noqa: E402  (registration)


@_graph_lib.trace_entry("parallel.pipeline", hbm_budget=8 << 20)
def _graph_entries():
    """The GPipe forward at registry scale: stacked stage params sharded
    ``P('pipe')``, batch replicated.  The DT5xx ledger prices the
    per-tick ``ppermute`` neighbor exchange inside the scan (by design:
    activations MUST move every tick, so DT502 stays quiet) plus the
    masked psum broadcast after it."""
    import jax

    from .mesh import make_mesh

    n = min(8, len(jax.devices()))
    mesh = make_mesh({"pipe": n})
    d = 16

    def stage(params, acts):
        w, b = params
        return jnp.tanh(acts @ w + b)

    def fwd(stacked, x):
        return pipeline_apply(stage, stacked, x, mesh,
                              num_microbatches=4)

    stacked = (jax.ShapeDtypeStruct((n, d, d), jnp.float32),
               jax.ShapeDtypeStruct((n, d), jnp.float32))
    x = jax.ShapeDtypeStruct((8, d), jnp.float32)
    return _graph_lib.Target(
        "pipeline_apply", fwd, (stacked, x),
        in_specs=((P("pipe"), P("pipe")), P()), mesh=mesh)
