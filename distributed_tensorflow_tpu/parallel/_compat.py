"""JAX version compatibility for the manual-collectives entry point.

The framework is written against the modern ``jax.shard_map`` API
(``axis_names=`` set of *manual* axes, ``check_vma=``).  Older JAX
releases (<= 0.4.x) only ship ``jax.experimental.shard_map.shard_map``
with the complementary ``auto=`` parameter (the mesh axes that STAY
automatic) and ``check_rep=``.  This wrapper speaks the modern calling
convention and translates when running on the legacy API, so every
``parallel/`` call site works on both.

Two legacy landmines are papered over here:

* **partial-manual**: the legacy partial-auto path (``auto=`` nonempty)
  CHECK-fails inside XLA's SPMD partitioner (IsManualSubgroup mismatch)
  — a fatal process abort, not an exception.  Instead of handing legacy
  shard_map an ``auto=`` set, we lower the body *full-manual over the
  whole mesh* with the same specs: the body only ever names the manual
  axes, so making the auto axes manual-but-unused is semantically the
  replicated computation the partial-auto path would have run (each
  device along an auto axis redundantly computes its replica).  Inputs
  sharded over an auto axis are gathered at region entry by XLA —
  exactly the resharding the modern API performs.  Replication checking
  cannot see through the translation, so ``check_rep`` is forced off
  when auto axes exist.
* **axis_index**: ``lax.axis_index`` inside a legacy manual region
  lowers to ``partition-id`` arithmetic, which XLA:CPU's SPMD
  partitioner rejects (``UNIMPLEMENTED``) whenever the region is
  compiled under ``jit``/``lax.scan``.  We thread one tiny
  ``iota(size)`` operand per manual axis into the region (in_spec
  ``P(axis)`` — each device's shard IS its coordinate) and patch
  ``jax.lax.axis_index`` through a thread-local map that is only active
  while the body traces, so the body reads its coordinate from data
  instead of from ``partition-id``.
"""
import threading

import jax

__all__ = ["shard_map"]

# Thread-local stack of {axis_name: index scalar} maps, pushed while a
# legacy shard_map body is being traced.  The patched ``axis_index``
# consults the innermost map first and falls through to the real
# primitive for axis names it does not cover (nested shard_maps, vmapped
# axes, the custom-vjp backward traced outside the window).
_AXIS_IDS = threading.local()
_PATCH_LOCK = threading.Lock()
_PATCHED = False


def _ensure_axis_index_patch():
    global _PATCHED
    if _PATCHED:
        return
    with _PATCH_LOCK:
        if _PATCHED:
            return
        real = jax.lax.axis_index

        def axis_index(axis_name):
            for mapping in reversed(getattr(_AXIS_IDS, "stack", ())):
                if axis_name in mapping:
                    return mapping[axis_name]
            return real(axis_name)

        axis_index.__wrapped__ = real
        axis_index.__doc__ = real.__doc__
        jax.lax.axis_index = axis_index
        _PATCHED = True


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=True):
    """Modern-signature shard_map that degrades to the legacy API.

    ``axis_names``: the mesh axes the body is manual over (None = all).
    ``check_vma``: replication checking (legacy name: ``check_rep``).
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)

    from jax.experimental.shard_map import shard_map as legacy
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    wanted = (frozenset(mesh.axis_names) if axis_names is None
              else frozenset(axis_names))
    manual = tuple(a for a in mesh.axis_names if a in wanted)
    auto = frozenset(mesh.axis_names) - wanted
    _ensure_axis_index_patch()

    def call(*args):
        specs = (tuple(in_specs) if isinstance(in_specs, (tuple, list))
                 else (in_specs,) * len(args))
        specs += tuple(P(a) for a in manual)
        idx_args = tuple(jnp.arange(mesh.shape[a], dtype=jnp.int32)
                         for a in manual)

        def body(*flat):
            user, ids = flat[:len(args)], flat[len(args):]
            mapping = {a: ids[i][0] for i, a in enumerate(manual)}
            stack = getattr(_AXIS_IDS, "stack", ())
            _AXIS_IDS.stack = stack + (mapping,)
            try:
                return f(*user)
            finally:
                _AXIS_IDS.stack = stack

        sm = legacy(body, mesh=mesh, in_specs=specs,
                    out_specs=out_specs,
                    check_rep=False if auto else check_vma)
        return sm(*args, *idx_args)

    return call
