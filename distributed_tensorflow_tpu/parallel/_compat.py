"""JAX version compatibility for the manual-collectives entry point.

The framework is written against the modern ``jax.shard_map`` API
(``axis_names=`` set of *manual* axes, ``check_vma=``).  Older JAX
releases (<= 0.4.x) only ship ``jax.experimental.shard_map.shard_map``
with the complementary ``auto=`` parameter (the mesh axes that STAY
automatic) and ``check_rep=``.  This wrapper speaks the modern calling
convention and translates when running on the legacy API, so every
``parallel/`` call site works on both.
"""
import jax

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=True):
    """Modern-signature shard_map that degrades to the legacy API.

    ``axis_names``: the mesh axes the body is manual over (None = all).
    ``check_vma``: replication checking (legacy name: ``check_rep``).
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)

    from jax.experimental.shard_map import shard_map as legacy
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    if auto:
        # The legacy partial-auto path CHECK-fails inside XLA's SPMD
        # partitioner (IsManualSubgroup mismatch) — a fatal process
        # abort, not an exception.  Refuse up front so callers see a
        # catchable error instead of a dead interpreter.
        raise NotImplementedError(
            f"partial-manual shard_map over {sorted(axis_names)} with "
            f"auto axes {sorted(auto)} requires the modern jax.shard_map "
            f"API; this JAX ({jax.__version__}) only ships the legacy "
            "experimental one, whose partial-auto path aborts in the "
            "SPMD partitioner")
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
