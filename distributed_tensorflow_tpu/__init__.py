"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

Brand-new framework with the capabilities of Rmeredith99/distributed_tensorflow
(TF 1.4 parameter-server data parallelism; see SURVEY.md), re-designed for
TPU: JAX/XLA compiled step functions, sync data parallelism via ICI
all-reduce, and the pjit/Mesh generalization to tensor / sequence / pipeline
parallelism.  No parameter server, no gRPC variable push — placement is
declarative sharding and gradient sync is a compiled collective.

Public surface (two tiers, mirroring the reference's two scripts):
  * low-level: ``ops`` (functional layers) + ``optim`` + ``train.TrainSession``
    — the analogue of reference example.py's graph + MonitoredTrainingSession.
  * high-level: ``models.Sequential`` with ``compile``/``fit``
    — the analogue of reference example2.py's Keras path.
"""

from . import (data, fleet, models, obs, ops, optim, parallel, resilience,
               serve, summary, train, utils)
from .utils import flags
from .utils.flags import FLAGS

__version__ = "0.1.0"
