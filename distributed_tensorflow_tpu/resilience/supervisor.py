"""Auto-resume supervisor: the survival half of fault tolerance.

The reference's whole failure story is "restart the process and
``MonitoredTrainingSession`` restores the latest checkpoint" (reference
example.py:189-192; TensorFlow paper §4.3 calls user-level checkpointing
the system's entire fault-tolerance mechanism).  ``Supervisor`` is that
restart loop brought in-process and made honest about *which* failures
deserve a restart:

* **transient** (preemption-shaped: ``OSError``/``ConnectionError``/
  ``TimeoutError`` from storage and RPC, ``FloatingPointError`` from a
  divergence guard, injected chaos faults) → restart from the last good
  checkpoint, with bounded retries and exponential backoff + jitter so a
  hard-down dependency is not hammered in lockstep by every host;
* **fatal** (everything else: shape errors, assertion failures,
  ``KeyboardInterrupt``) → re-raise immediately; a code bug replayed
  from a checkpoint fails identically forever and must reach the
  operator, not burn the retry budget.

Restarts are observable: ``dttpu_restarts_total`` counts them and
``dttpu_recovery_seconds`` measures failure → restored-session wall
clock (docs/OBSERVABILITY.md).

``NonfiniteGuardHook`` is the divergence tripwire that makes the NaN
fault class *transient*: it rides the ``device_health`` metrics the step
already returns (``obs.device.grad_health``), tolerates isolated
non-finite steps (the in-graph ``skip_nonfinite`` step option drops
those updates, so params stay clean), and aborts with
``FloatingPointError`` — which the supervisor classifies transient —
after K *consecutive* bad steps, when skipping clearly isn't converging
back to health.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional

from ..obs import device as obs_device
from ..obs import goodput as goodput_lib
from ..obs import metrics as metrics_lib
from .faults import InjectedFault

log = logging.getLogger(__name__)

__all__ = ["Supervisor", "NonfiniteGuardHook", "TRANSIENT_EXCEPTIONS"]

# The preemption-shaped failure set.  FloatingPointError is transient by
# design: NonfiniteGuardHook (and NaNHook) raise it exactly when a
# restart-from-checkpoint is the right recovery.  InjectedFault keeps
# chaos runs inside the same classification the real faults would get.
TRANSIENT_EXCEPTIONS = (OSError, ConnectionError, TimeoutError,
                        FloatingPointError, InjectedFault)


class Supervisor:
    """Bounded-retry auto-resume driver around a session factory.

    Usage::

        sup = Supervisor(max_restarts=3)

        def build_session():
            state, step_fn = rebuild()          # fresh state every attempt
            return TrainSession(state, step_fn, checkpoint_dir=d,
                                hooks=[...])    # restores the last GOOD ckpt

        def train(sess):
            for batch in batches():
                if sess.should_stop():
                    break
                sess.run_step(batch)
            return sess.step

        final_step = sup.run(build_session, train)

    ``build_session`` must return a *fresh* context-manager session that
    restores from the checkpoint dir (``TrainSession(restore=True)`` now
    walks ``restore_latest_good``, so a corrupt newest checkpoint falls
    back instead of killing every attempt identically).  ``train(sess)``
    runs inside the session's ``with`` block; its return value is
    ``run``'s.  Failures raised by either are classified; transient ones
    are retried up to ``max_restarts`` times with exponential backoff
    (``backoff_base * backoff_factor**(attempt-1)``, capped at
    ``backoff_max``) plus up to ``jitter`` fraction of random extra.

    ``classify``: optional ``exc -> "transient" | "fatal"`` override
    (e.g. to add a backend's preemption error type); default classifies
    by ``TRANSIENT_EXCEPTIONS``.  ``sleep`` is injectable for tests.
    """

    def __init__(self, *, max_restarts: int = 3,
                 backoff_base: float = 0.5,
                 backoff_factor: float = 2.0,
                 backoff_max: float = 30.0,
                 jitter: float = 0.5,
                 classify: Optional[Callable[[BaseException], str]] = None,
                 registry: Optional[metrics_lib.Registry] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 seed: int = 0):
        import numpy as np
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.classify = classify
        self.sleep = sleep
        self._rng = np.random.default_rng(seed)
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self.restarts_total = reg.counter(
            "dttpu_restarts_total",
            "Supervisor restarts after transient failures.")
        self.recovery_seconds = reg.histogram(
            "dttpu_recovery_seconds",
            "Failure to restored-session wall clock (backoff + rebuild "
            "+ checkpoint restore).")
        self.restart_log: list = []   # (attempt, repr(exc)) audit trail

    # ----------------------------------------------------------------- run

    def _is_transient(self, exc: BaseException) -> bool:
        if self.classify is not None:
            return self.classify(exc) == "transient"
        return isinstance(exc, TRANSIENT_EXCEPTIONS)

    def _delay(self, attempt: int) -> float:
        base = min(self.backoff_max,
                   self.backoff_base * self.backoff_factor ** (attempt - 1))
        return base * (1.0 + self.jitter * float(self._rng.random()))

    def run(self, build_session: Callable[[], Any],
            train: Callable[[Any], Any]) -> Any:
        """Drive ``train`` over fresh sessions until it returns, a fatal
        error escapes, or the restart budget is exhausted (the last
        transient error is then re-raised)."""
        attempt = 0
        failed_at: Optional[float] = None
        while True:
            try:
                if failed_at is not None:
                    # goodput "fault_recovery": the post-failure session
                    # rebuild.  The checkpoint restore inside it accrues
                    # to its own exclusive "checkpoint_restore" frame, so
                    # this bucket is the rebuild glue around the restore.
                    with goodput_lib.account("fault_recovery"):
                        session = build_session()
                    self.recovery_seconds.observe(
                        time.monotonic() - failed_at)
                    failed_at = None
                else:
                    session = build_session()
                with session:
                    return train(session)
            except BaseException as e:
                if not self._is_transient(e) or attempt >= self.max_restarts:
                    raise
                attempt += 1
                failed_at = time.monotonic() if failed_at is None \
                    else failed_at
                self.restarts_total.inc()
                self.restart_log.append((attempt, repr(e)))
                delay = self._delay(attempt)
                log.warning(
                    "transient failure (%r) — restart %d/%d from last good "
                    "checkpoint in %.2fs", e, attempt, self.max_restarts,
                    delay)
                with goodput_lib.account("restart_backoff"):
                    self.sleep(delay)


class NonfiniteGuardHook:
    """Abort (transiently) after K consecutive non-finite steps.

    Reads the step's returned metrics dict — ``nonfinite_grads`` from
    ``device_health=True`` steps, falling back to the ``grads_finite``
    flag the ``loss_scale``/``skip_nonfinite`` builders emit — so it
    needs no extra device computation.  Pair with a step built with
    ``skip_nonfinite=True``: that drops the bad updates IN-GRAPH (the
    returned state is already the rolled-back one — host-side rollback
    is impossible under donation, the old buffers are gone), and this
    hook supplies the escalation policy on top: isolated bad steps are
    skipped and survived; ``max_consecutive`` bad steps in a row raise
    ``FloatingPointError``, which ``Supervisor`` classifies transient
    and answers with a restart from the last good checkpoint.

    Cost note: evaluating the metric pulls one device scalar per step
    (the consecutive-run semantics need every step).  That is a
    deliberate exception to the hooks-don't-sync contract — install this
    hook when you want the guard, not by default.

    Duck-typed train Hook (no ``train.hooks`` import: resilience stays
    import-cycle-free below the train package).
    """

    def __init__(self, max_consecutive: int = 3):
        if max_consecutive < 1:
            raise ValueError(
                f"max_consecutive must be >= 1; got {max_consecutive}")
        self.max_consecutive = int(max_consecutive)
        self.consecutive = 0
        self.total_nonfinite = 0

    # Hook protocol ------------------------------------------------------
    def begin(self, session) -> None:
        self.consecutive = 0

    def before_step(self, session) -> None:
        pass

    def after_step(self, session, metrics) -> None:
        nf = metrics.get(obs_device.NONFINITE_KEY)
        if nf is not None:
            bad = float(nf) > 0
        else:
            finite = metrics.get("grads_finite")
            if finite is None:
                return
            bad = not bool(finite)
        if not bad:
            self.consecutive = 0
            return
        self.consecutive += 1
        self.total_nonfinite += 1
        log.warning("non-finite gradients at step %d (%d consecutive)",
                    session.step, self.consecutive)
        if self.consecutive >= self.max_consecutive:
            raise FloatingPointError(
                f"{self.consecutive} consecutive non-finite steps ending "
                f"at step {session.step} — aborting for restart from the "
                "last good checkpoint")

    def end(self, session) -> None:
        pass

    def close(self, session) -> None:
        pass
