"""Seeded, deterministic fault injection — the chaos half of resilience.

A ``FaultPlan`` is a list of scheduled faults, each named by *kind* and
armed at a deterministic trigger index (the Nth checkpoint save, the Nth
prefetched batch, global step N, request id K).  The plan is activated
process-wide (``activate``/``activated`` — the same module-active idiom
as ``obs.trace``) or via the ``DTTPU_FAULTS`` env var (a JSON list, so
chaos runs work through subprocess boundaries, e.g. ``bench.py
--config=recovery``); instrumented sites in checkpoint/session/pipeline/
serve call the plan's ``on_*`` hooks, which no-op unless a fault of the
matching kind is armed at that exact index.

Fault catalog (docs/RESILIENCE.md):

==================  =========================================================
kind                effect (trigger field ``at``)
==================  =========================================================
corrupt_checkpoint  after the ``at``-th successful ``checkpoint.save``,
                    truncate (``mode="truncate"``, default) or bit-flip
                    (``mode="flip"``) ``file`` (default ``arrays.npz``)
                    inside the just-written checkpoint dir
save_oserror        raise a transient ``OSError`` at entry of the ``at``-th
                    ``checkpoint.save`` call
poison_batch        replace every float leaf of the ``at``-th batch flowing
                    through ``data.prefetch_to_device`` with NaN
nan_grads           NaN-poison the batch of the training step whose
                    pre-step global step equals ``at`` (the gradients of
                    that step become non-finite in-graph)
kill_prefetch       raise ``OSError`` inside the ``dttpu-prefetch``
                    producer thread at the ``at``-th batch (the consumer
                    sees the producer die and re-raises)
fail_decode         raise ``InjectedFault`` when the serve scheduler
                    delivers tokens for request id ``at`` (fails exactly
                    that handle; scheduler isolation keeps the tick loop
                    and every other slot alive)
kill_replica        raise ``ConnectionError`` at the fleet Router's pump
                    site for replica id ``replica`` on its ``at``-th
                    pump — the router sees the replica die mid-traffic,
                    removes it, and migrates its in-flight requests to
                    the survivors (fleet/router.py)
stall_tick          sleep ``seconds`` inside the serve scheduler's pump
                    at its ``at``-th tick (engine tagged ``replica`` —
                    the fleet Router stamps ``Engine.chaos_tag`` with
                    the replica id) — the tick completes late, so the
                    fleet ``Watchdog`` sees a blown tick deadline in
                    ``Engine.stats()`` and quarantines the replica
wedge_replica       block the serve scheduler's pump at its ``at``-th
                    tick (engine tagged ``replica``) until
                    ``plan.release_wedges()`` or the ``seconds`` cap —
                    a stuck-but-alive replica: the pump holds its mutex
                    mid-tick, which only the watchdog's in-progress
                    heartbeat check can see
correlated_kill     kill ``k`` replicas within a window of ``window``
                    router pumps starting at the ``at``-th pump counted
                    across ALL replicas: when the window opens, the
                    plan's seeded generator picks the victims among the
                    replicas it has seen pumped so far, and each victim
                    raises ``ConnectionError`` on its next pump inside
                    the window (a victim never pumped in the window
                    escapes — failure domains, not a guaranteed body
                    count).  The fleet simulator and the real chaos
                    tests schedule rack/PSU-style correlated failures
                    through this one kind (``times`` is ignored; ``k``
                    governs)
drop_chunk          the page wire's ``at``-th chunk frame on wire
                    ``replica`` vanishes in flight (the sender sees a
                    per-chunk timeout and re-sends; fleet/pagewire.py)
corrupt_chunk       flip one byte of the page wire's ``at``-th chunk
                    frame on wire ``replica`` — the receiver's CRC32C
                    check NAKs it and the sender re-sends
stall_wire          delay delivery of the page wire's ``at``-th chunk
                    frame on wire ``replica`` by ``seconds`` — a late
                    frame the sender has already re-sent (the receiver
                    dedups the duplicate by chain key)
kill_host           raise ``ConnectionError`` at the page wire's
                    ``at``-th chunk on wire ``replica`` (host died
                    mid-transfer: the transfer degrades to re-prefill
                    migration), AND/OR kill the launcher-supervised
                    host process ``replica`` at the launcher's
                    ``at``-th liveness poll (fleet/launcher.py
                    restarts it).  The two sites keep separate
                    counters (``wire:N`` vs ``host:N``); arm one fault
                    per site when both must fire
==================  =========================================================

Every injection is auditable: it lands in ``plan.log``, increments the
``dttpu_faults_injected_total`` counter on the plan's registry, and emits
a ``fault`` instant on the active obs tracer (when one is active), so a
chaos run's timeline shows exactly where reality was bent.

Determinism: triggers are index-equality, each fault fires at most
``times`` times (default 1), and the only randomness (the flip offset of
``corrupt_checkpoint``) comes from the plan's seeded generator — the
same plan against the same run injects the same faults.

NOTE: an ACTIVE plan makes ``TrainSession.run_step`` read the device
step counter every step (a host sync) to evaluate ``nan_grads``
triggers.  That cost exists only during chaos runs; with no plan active
every hook site is a single module-global ``None`` check.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..obs import metrics as metrics_lib
from ..obs import trace as trace_lib

__all__ = ["Fault", "FaultPlan", "InjectedFault", "KINDS", "activate",
           "activated", "active", "deactivate", "plan_from_env"]

KINDS = ("corrupt_checkpoint", "save_oserror", "poison_batch",
         "nan_grads", "kill_prefetch", "fail_decode", "kill_replica",
         "stall_tick", "wedge_replica", "correlated_kill",
         "drop_chunk", "corrupt_chunk", "stall_wire", "kill_host")


class InjectedFault(RuntimeError):
    """An injected failure with no realistic stdlib exception type.

    Used where the real-world analogue is a component-internal error
    (a poisoned request's decode); sites injecting faults that DO have a
    realistic type raise that type instead (``OSError`` for save/IO).
    """


@dataclasses.dataclass
class Fault:
    """One scheduled fault.  ``at`` is the trigger index — what it
    indexes depends on ``kind`` (see the module catalog)."""
    kind: str
    at: int
    mode: str = "truncate"          # corrupt_checkpoint: truncate | flip
    file: str = "arrays.npz"        # corrupt_checkpoint target file
    replica: int = 0                # kill_replica/stall_tick/wedge_replica:
    #                                 target replica (engine chaos_tag)
    seconds: float = 1.0            # stall_tick: sleep length;
    #                                 wedge_replica: max block before the
    #                                 wedge self-releases
    times: int = 1                  # max fires
    k: int = 2                      # correlated_kill: victim count
    window: int = 8                 # correlated_kill: pump window length
    victims: tuple = ()             # correlated_kill: chosen at window
    #                                 open by the plan's seeded rng (audit
    #                                 trail; leave empty when scheduling)
    fired: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"choices: {KINDS}")


class FaultPlan:
    """A deterministic schedule of faults plus its audit trail."""

    def __init__(self, faults, seed: int = 0,
                 registry: Optional[metrics_lib.Registry] = None):
        import numpy as np
        self.faults: List[Fault] = [
            f if isinstance(f, Fault) else Fault(**f) for f in faults]
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._wedges: Dict[int, threading.Event] = {}
        self._seen_replicas: set = set()
        self._corr_killed: Dict[int, set] = {}   # id(fault) -> victims hit
        self.log: List[Dict[str, Any]] = []
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self._injected = reg.counter(
            "dttpu_faults_injected_total",
            "Faults injected by the resilience chaos harness.")

    # ----------------------------------------------------------- matching

    def add(self, fault: Fault) -> Fault:
        """Arm one more fault on a live plan — the fleet simulator
        translates trace-scheduled incidents into plan faults as their
        virtual time comes due."""
        with self._lock:
            self.faults.append(fault)
        return fault

    @property
    def global_pump_index(self) -> int:
        """The NEXT router-pump index across all replicas (what a
        ``correlated_kill`` scheduled with ``at=`` this value matches
        on the very next pump)."""
        with self._lock:
            return self._counters.get("replica:*", 0)

    def _tick(self, site: str) -> int:
        """0-based per-site call counter (thread-safe; the prefetch
        producer calls ``on_batch`` off the main thread)."""
        with self._lock:
            i = self._counters.get(site, 0)
            self._counters[site] = i + 1
            return i

    def _match(self, kind: str, index: int,
               replica: Optional[int] = None) -> Optional[Fault]:
        with self._lock:
            for f in self.faults:
                if f.kind == kind and f.at == index and f.fired < f.times \
                        and (replica is None or f.replica == replica):
                    f.fired += 1
                    return f
        return None

    def _record(self, fault: Fault, **ctx: Any) -> None:
        entry = {"kind": fault.kind, "at": fault.at, **ctx}
        with self._lock:
            self.log.append(entry)
        self._injected.inc()
        trace_lib.instant("fault", kind=fault.kind,
                          **{k: str(v) for k, v in ctx.items()})

    # ------------------------------------------------------ site hooks
    # Each is called by exactly one instrumented site; all are no-ops
    # (beyond a counter tick) unless a fault matches.

    def on_save(self) -> int:
        """Entry of ``checkpoint.save``: returns this call's save index;
        raises a transient ``OSError`` when a save_oserror is armed."""
        i = self._tick("save")
        f = self._match("save_oserror", i)
        if f is not None:
            self._record(f, save=i)
            raise OSError(f"injected fault: checkpoint save #{i} failed")
        return i

    def on_saved(self, ckpt_path: str, save_index: int) -> None:
        """After the atomic rename: corrupt the just-written checkpoint
        when a corrupt_checkpoint is armed at this save index."""
        f = self._match("corrupt_checkpoint", save_index)
        if f is not None:
            self._corrupt(ckpt_path, f)
            self._record(f, path=ckpt_path, mode=f.mode)

    def _corrupt(self, ckpt_path: str, fault: Fault) -> None:
        target = os.path.join(ckpt_path, fault.file)
        size = os.path.getsize(target)
        if fault.mode == "flip":
            off = int(self._rng.integers(0, max(1, size)))
            with open(target, "r+b") as fh:
                fh.seek(off)
                b = fh.read(1) or b"\x00"
                fh.seek(off)
                fh.write(bytes([b[0] ^ 0xFF]))
        else:                                   # truncate
            with open(target, "r+b") as fh:
                fh.truncate(size // 2)

    def on_batch(self, item: Any) -> Any:
        """One batch through the prefetch producer: kill the producer or
        poison the batch when armed; otherwise pass ``item`` through."""
        i = self._tick("batch")
        f = self._match("kill_prefetch", i)
        if f is not None:
            self._record(f, batch=i)
            raise OSError(
                f"injected fault: dttpu-prefetch producer killed at "
                f"batch #{i}")
        f = self._match("poison_batch", i)
        if f is not None:
            self._record(f, batch=i)
            return _poison(item)
        return item

    def on_step(self, step: int, args: tuple) -> tuple:
        """``TrainSession.run_step`` with pre-step global step ``step``:
        NaN-poison the step's args when a nan_grads fault is armed."""
        f = self._match("nan_grads", int(step))
        if f is not None:
            self._record(f, step=int(step))
            return _poison(args)
        return args

    def on_decode(self, rid: int) -> None:
        """Serve token delivery for request ``rid``: fail exactly that
        request when a fail_decode fault is armed."""
        f = self._match("fail_decode", int(rid))
        if f is not None:
            self._record(f, rid=int(rid))
            raise InjectedFault(
                f"injected fault: decode failed for request {rid}")

    def on_engine_tick(self, tag: int) -> None:
        """The serve scheduler's pump at tick entry for the engine
        tagged ``tag`` (the fleet Router stamps replica ids onto
        ``Engine.chaos_tag``; a standalone engine is tag 0).  A
        stall_tick armed at this tick index sleeps ``seconds`` — the
        tick completes, but past any sane watchdog deadline; a
        wedge_replica blocks the pump (mutex held, mid-tick) until
        ``release_wedges()`` or the ``seconds`` cap, the
        stuck-but-alive shape only an in-progress heartbeat check can
        see."""
        i = self._tick(f"tick:{tag}")
        f = self._match("stall_tick", i, replica=int(tag))
        if f is not None:
            self._record(f, replica=int(tag), tick=i, seconds=f.seconds)
            time.sleep(f.seconds)
        f = self._match("wedge_replica", i, replica=int(tag))
        if f is not None:
            with self._lock:
                ev = self._wedges.setdefault(int(tag), threading.Event())
            self._record(f, replica=int(tag), tick=i)
            ev.wait(f.seconds)

    def release_wedges(self) -> None:
        """Unblock every pump held by a fired wedge_replica fault (the
        test/bench driver's hand on the wedge — a wedge with no release
        self-frees at its ``seconds`` cap)."""
        with self._lock:
            evs = list(self._wedges.values())
        for ev in evs:
            ev.set()

    def on_replica_step(self, replica: int) -> None:
        """The fleet Router's pump of replica ``replica``: kill that
        replica (a ``ConnectionError`` — the realistic router-to-replica
        failure type) on its ``at``-th pump when a kill_replica fault
        targeting it is armed."""
        i = self._tick(f"replica:{replica}")
        f = self._match("kill_replica", i, replica=int(replica))
        if f is not None:
            self._record(f, replica=int(replica), step=i)
            raise ConnectionError(
                f"injected fault: replica {replica} killed at pump #{i}")
        f = self._match_correlated(int(replica))
        if f is not None:
            self._record(f, replica=int(replica), step=i,
                         victims=f.victims)
            raise ConnectionError(
                f"injected fault: replica {replica} killed by correlated "
                f"failure (victims {f.victims})")

    def on_wire_chunk(self, wire: int) -> Optional[str]:
        """The page wire's delivery of one chunk frame on wire ``wire``
        (``InProcessLink.deliver``, fleet/pagewire.py): returns the
        action the link applies to this frame — ``"drop"`` (vanish it),
        ``"corrupt"`` (flip a byte; the receiver's CRC NAKs), or
        ``None`` (deliver clean).  A stall_wire sleeps ``seconds``
        in-line (the whole flight lands late); a kill_host raises
        ``ConnectionError`` — the host died mid-transfer and the
        transfer is unrecoverable."""
        i = self._tick(f"wire:{wire}")
        f = self._match("kill_host", i, replica=int(wire))
        if f is not None:
            self._record(f, wire=int(wire), chunk=i)
            raise ConnectionError(
                f"injected fault: host behind wire {wire} died at "
                f"chunk #{i}")
        f = self._match("stall_wire", i, replica=int(wire))
        if f is not None:
            self._record(f, wire=int(wire), chunk=i, seconds=f.seconds)
            time.sleep(f.seconds)
        f = self._match("drop_chunk", i, replica=int(wire))
        if f is not None:
            self._record(f, wire=int(wire), chunk=i)
            return "drop"
        f = self._match("corrupt_chunk", i, replica=int(wire))
        if f is not None:
            self._record(f, wire=int(wire), chunk=i)
            return "corrupt"
        return None

    def on_host_poll(self, host: int) -> Optional[Fault]:
        """The launcher's ``at``-th liveness poll of host ``host``
        (fleet/launcher.py): returns the matched kill_host fault so the
        launcher SIGKILLs the child — the supervised-restart path —
        or ``None``.  Separate counter site from ``on_wire_chunk``
        (``host:N`` vs ``wire:N``); arm one fault per site when a test
        needs both the wire cut AND the process killed."""
        i = self._tick(f"host:{host}")
        f = self._match("kill_host", i, replica=int(host))
        if f is not None:
            self._record(f, host=int(host), poll=i)
        return f

    def _match_correlated(self, replica: int) -> Optional[Fault]:
        """correlated_kill matching: a *global* pump counter (across all
        replicas) opens the window at ``at``; victims are drawn once,
        seeded, from the replicas seen pumped so far; each victim dies on
        its first pump inside ``[at, at + window)``."""
        with self._lock:
            self._seen_replicas.add(replica)
            j = self._counters.get("replica:*", 0)
            self._counters["replica:*"] = j + 1
            for f in self.faults:
                if f.kind != "correlated_kill" or f.fired >= f.k:
                    continue
                if j < f.at or j >= f.at + f.window:
                    continue
                if not f.victims:
                    pool = sorted(self._seen_replicas)
                    size = min(f.k, len(pool))
                    f.victims = tuple(
                        int(x) for x in self._rng.choice(
                            pool, size=size, replace=False))
                killed = self._corr_killed.setdefault(id(f), set())
                if replica in f.victims and replica not in killed:
                    killed.add(replica)
                    f.fired += 1
                    return f
        return None


def _poison(tree: Any) -> Any:
    """Replace every float array leaf with NaN (jax arrays stay jax
    arrays — already-uploaded prefetch batches poison in place)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def bad(leaf):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
            return leaf
        if isinstance(leaf, jax.Array):
            return jnp.full_like(leaf, jnp.nan)
        return np.full_like(np.asarray(leaf), np.nan)

    return jax.tree.map(bad, tree)


# ---------------------------------------------------------------------------
# Active plan: process-wide activation (the obs.trace idiom) + env spec.

_ACTIVE: Optional[FaultPlan] = None
_ENV_CACHE = (None, None)   # (env string, parsed plan)
_ENV_LOCK = threading.Lock()  # rebuilds race across pump/producer threads


def activate(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def deactivate(plan: Optional[FaultPlan] = None) -> None:
    """Clear the active plan (only if it is ``plan``, when given)."""
    global _ACTIVE
    if plan is None or _ACTIVE is plan:
        _ACTIVE = None


@contextlib.contextmanager
def activated(plan: FaultPlan):
    """Scoped activation — the pytest-facing entry (the ``activate_faults``
    fixture in tests/conftest.py wraps this)."""
    activate(plan)
    try:
        yield plan
    finally:
        deactivate(plan)


def active() -> Optional[FaultPlan]:
    """The plan injection sites consult: an explicitly activated plan
    wins; otherwise ``DTTPU_FAULTS`` (JSON) is parsed once per distinct
    value and cached — counters must persist across calls.

    Injection sites run on scheduler pumps, router sweeps, and prefetch
    producers concurrently; the rebuild is locked so one spec value maps
    to ONE plan instance (two racing rebuilds would split the per-site
    at-most-``times`` counters across two plans and over-fire faults)."""
    global _ENV_CACHE
    if _ACTIVE is not None:
        return _ACTIVE
    spec = os.environ.get("DTTPU_FAULTS")
    if not spec:
        return None
    with _ENV_LOCK:
        if _ENV_CACHE[0] != spec:
            _ENV_CACHE = (spec, plan_from_env(spec))
        return _ENV_CACHE[1]


def plan_from_env(spec: str) -> FaultPlan:
    """Parse a ``DTTPU_FAULTS`` value: either a JSON list of fault dicts
    or ``{"seed": S, "faults": [...]}``."""
    doc = json.loads(spec)
    if isinstance(doc, dict):
        return FaultPlan(doc.get("faults", []), seed=doc.get("seed", 0))
    return FaultPlan(doc)
