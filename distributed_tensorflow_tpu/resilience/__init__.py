"""resilience — fault injection, verified-checkpoint fallback, auto-resume.

The survival layer above checkpointing (docs/RESILIENCE.md).  The
reference delegates its entire failure story to
``MonitoredTrainingSession`` restore-on-restart (reference
example.py:189-192); this package supplies the three pieces that story
silently assumes:

* ``resilience.faults`` — a seeded, deterministic fault-injection
  harness (``FaultPlan``: corrupt/truncate a checkpoint post-write,
  transient save ``OSError``, NaN-poisoned batches/steps, a killed
  prefetch producer, a failed serve decode), activated via
  ``DTTPU_FAULTS`` or ``faults.activated(plan)``, every injection
  audited through obs (``dttpu_faults_injected_total`` + trace
  instants).  Recovery paths are *proven* under these faults, not
  assumed from the happy path.
* verified checkpoints — ``train.checkpoint`` now records per-leaf
  masked CRC32C in the manifest and ``restore_latest_good`` walks
  newest→oldest, quarantining corrupt dirs (``corrupt-ckpt-*`` + reason
  file) and falling back to the previous good step
  (``TrainSession(restore=True)`` uses it).
* ``resilience.supervisor`` — ``Supervisor.run(build_session, train)``:
  transient-vs-fatal exception classification, bounded restarts with
  exponential backoff + jitter, ``dttpu_restarts_total`` /
  ``dttpu_recovery_seconds``; plus ``NonfiniteGuardHook``, the
  consecutive-non-finite tripwire over the ``device_health`` metrics
  (pair with the step builders' in-graph ``skip_nonfinite=True``).

Serve-side graceful degradation (queue-depth admission control,
per-request deadlines, failure isolation) lives in ``serve.engine`` /
``serve.scheduler`` and is cataloged in the same doc.
"""
from . import faults, supervisor
from .faults import Fault, FaultPlan, InjectedFault
from .supervisor import NonfiniteGuardHook, Supervisor

__all__ = ["faults", "supervisor", "Fault", "FaultPlan", "InjectedFault",
           "NonfiniteGuardHook", "Supervisor"]
