"""Per-tenant admission policy: quotas + deficit-weighted fair-share.

"Millions of users" means MANY tenants sharing one engine (or a fleet of
them), and a scheduler that admits strictly FIFO lets one tenant's burst
queue ahead of everyone else's traffic.  This module supplies the two
controls the serve tier enforces at admission (docs/SERVING.md §Fleet):

* **Quotas** (``TenantQuota``): hard per-tenant ceilings checked at
  ``Engine.submit`` — ``max_inflight`` bounds a tenant's
  queued+prefilling+active request count, ``max_tokens_inflight`` bounds
  the sum of its in-flight ``max_new_tokens`` budgets.  Exceeding either
  rejects the submit with ``QuotaExceededError`` (backpressure to THAT
  tenant; everyone else is untouched) and bumps
  ``dttpu_tenant_rejected_total{tenant=...}``.
* **Deficit-weighted fair-share** (``DeficitFairQueue``): the
  scheduler's admission queue becomes per-tenant FIFOs drained by
  deficit round-robin (DRR) with the request's TOKEN budget as its
  cost — each visit a backlogged tenant banks ``quantum x weight``
  tokens of deficit and admits requests while it can pay for them, so
  sustained service converges to the weight ratio measured in TOKENS,
  not requests (a tenant of few long requests and a tenant of many
  short ones get equal token throughput at equal weight).  Decisions
  depend only on arrival order and the static config, so a replayed
  trace admits in exactly the same order (pinned by
  tests/test_fleet.py).

One ``TenantPolicy`` is shared by every replica of a fleet (it is
static config — quotas and weights); each engine builds its OWN
``DeficitFairQueue`` from it (``make_queue``), since queue state is
per-scheduler.

Wired through ``Engine(tenancy=policy)`` / ``submit(tenant=...)``; the
scheduler's per-tenant in-flight counters (``Engine.stats()``) are the
single accounting source the quota checks read.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterator, Optional

__all__ = ["DeficitFairQueue", "QuotaExceededError", "TenantPolicy",
           "TenantQuota"]


class QuotaExceededError(RuntimeError):
    """``submit`` rejected: the tenant is at a quota ceiling.
    Backpressure for ONE tenant, not failure — retry after that
    tenant's in-flight work retires."""


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant ceilings and fair-share weight.

    ``max_inflight``: queued+prefilling+active requests (None = no cap);
    ``max_tokens_inflight``: sum of in-flight ``max_new_tokens`` budgets
    (None = no cap); ``weight``: relative fair-share — a weight-2 tenant
    sustains twice the token throughput of a weight-1 tenant while both
    are backlogged."""
    max_inflight: Optional[int] = None
    max_tokens_inflight: Optional[int] = None
    weight: float = 1.0

    def __post_init__(self):
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1; got {self.max_inflight}")
        if self.max_tokens_inflight is not None \
                and self.max_tokens_inflight < 1:
            raise ValueError(f"max_tokens_inflight must be >= 1; "
                             f"got {self.max_tokens_inflight}")
        if not self.weight > 0:
            raise ValueError(f"weight must be > 0; got {self.weight}")


class TenantPolicy:
    """Static tenancy config: per-tenant quotas + the DRR quantum.

    ``quotas`` maps tenant -> ``TenantQuota``; unlisted tenants get
    ``default``.  ``quantum`` is the DRR refill in TOKENS per round
    visit — it trades scheduling granularity (small = finer
    interleaving) against rounds spent banking deficit for a long
    request (it never affects the CONVERGED share, only the burst
    granularity)."""

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None,
                 default: TenantQuota = TenantQuota(),
                 quantum: int = 32):
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1; got {quantum}")
        self.quotas = dict(quotas or {})
        self.default = default
        self.quantum = int(quantum)

    def quota(self, tenant: str) -> TenantQuota:
        return self.quotas.get(tenant, self.default)

    def check_admission(self, tenant: str, new_tokens: int, *,
                        inflight: int, tokens_inflight: int) -> None:
        """Raise ``QuotaExceededError`` if admitting a ``new_tokens``-
        budget request would push ``tenant`` past a ceiling.  Called by
        ``Engine.submit`` with the scheduler's live counters."""
        q = self.quota(tenant)
        if q.max_inflight is not None and inflight >= q.max_inflight:
            raise QuotaExceededError(
                f"tenant {tenant!r} at max_inflight={q.max_inflight}")
        if q.max_tokens_inflight is not None \
                and tokens_inflight + new_tokens > q.max_tokens_inflight:
            raise QuotaExceededError(
                f"tenant {tenant!r} over max_tokens_inflight="
                f"{q.max_tokens_inflight} ({tokens_inflight} in flight "
                f"+ {new_tokens} requested)")

    def make_queue(self) -> "DeficitFairQueue":
        """A fresh fair-share admission queue for ONE scheduler."""
        return DeficitFairQueue(self)


class DeficitFairQueue:
    """Deficit-round-robin admission queue over per-tenant FIFOs.

    Implements the scheduler's queue protocol (append / popleft /
    remove / requeue / __len__ / __iter__ / __contains__) so it drops
    into ``SlotScheduler`` in place of the default deque.  ``popleft``
    serves the round-robin ring of backlogged tenants: each visit banks
    ``quantum x weight`` deficit tokens; a tenant whose head request's
    ``max_new_tokens`` fits its deficit pays and admits, otherwise the
    ring rotates.  A tenant leaving the backlog forfeits its deficit
    (standard DRR — an idle tenant cannot bank credit), which is what
    makes the schedule depend only on arrival order."""

    def __init__(self, policy: TenantPolicy):
        self.policy = policy
        self._fifos: Dict[str, collections.deque] = {}
        self._ring: collections.deque = collections.deque()   # tenants
        self._deficit: Dict[str, float] = {}
        self._len = 0
        # True while ring[0] is mid-visit (already granted this visit's
        # quantum); cleared whenever the pointer advances
        self._visited = False

    # ------------------------------------------------- queue protocol

    def append(self, req) -> None:
        fifo = self._fifos.get(req.tenant)
        if fifo is None:
            fifo = self._fifos[req.tenant] = collections.deque()
        if not fifo:
            self._ring.append(req.tenant)
            self._deficit.setdefault(req.tenant, 0.0)
        fifo.append(req)
        self._len += 1

    def popleft(self):
        """The next admissible request under DRR.  Raises IndexError on
        an empty queue (deque semantics — the scheduler len-guards).

        Pointer-based DRR: arriving at a tenant grants its quantum ONCE
        for the visit; the visit serves head requests while the banked
        deficit covers their token cost, then the pointer advances (the
        unspent remainder stays banked).  A cheap-request tenant can
        therefore never monopolize the ring — it spends its visit budget
        and waits for its next turn like everyone else."""
        if not self._len:
            raise IndexError("pop from an empty DeficitFairQueue")
        while True:
            tenant = self._ring[0]
            fifo = self._fifos[tenant]
            if not self._visited:
                self._deficit[tenant] += (
                    self.policy.quantum
                    * self.policy.quota(tenant).weight)
                self._visited = True
            if self._deficit[tenant] >= fifo[0].max_new_tokens:
                req = fifo.popleft()
                self._deficit[tenant] -= req.max_new_tokens
                self._len -= 1
                self._retire_if_idle(tenant)
                return req
            self._ring.rotate(-1)
            self._visited = False

    def requeue(self, req) -> None:
        """Put a popped-but-unstartable request back at the FRONT of its
        tenant's FIFO and refund its deficit charge — the replayed
        admission order stays deterministic."""
        fifo = self._fifos.get(req.tenant)
        if fifo is None:
            fifo = self._fifos[req.tenant] = collections.deque()
        if not fifo and req.tenant not in self._ring:
            self._ring.appendleft(req.tenant)
        self._deficit[req.tenant] = (self._deficit.get(req.tenant, 0.0)
                                     + req.max_new_tokens)
        fifo.appendleft(req)
        self._len += 1

    def remove(self, req) -> None:
        fifo = self._fifos.get(req.tenant)
        if fifo is None or req not in fifo:
            raise ValueError("request not in queue")
        fifo.remove(req)
        self._len -= 1
        self._retire_if_idle(req.tenant)

    def release(self, req) -> None:
        """Scheduler hook at request retirement — nothing to do here
        (deficits settle at pop time), kept for protocol symmetry."""

    def _retire_if_idle(self, tenant: str) -> None:
        if not self._fifos[tenant]:
            if self._ring and self._ring[0] == tenant:
                self._visited = False
            del self._fifos[tenant]
            self._ring.remove(tenant)
            # idle tenants forfeit deficit: no banking credit while away
            self._deficit.pop(tenant, None)

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator:
        for tenant in list(self._ring):
            yield from list(self._fifos.get(tenant, ()))

    def __contains__(self, req) -> bool:
        fifo = self._fifos.get(getattr(req, "tenant", None))
        return fifo is not None and req in fifo
