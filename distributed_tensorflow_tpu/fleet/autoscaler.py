"""SLO-driven fleet autoscaling: one policy for sim and real engines.

The :class:`Autoscaler` closes the loop the fleet tier left open: the
router can add, drain, and migrate replicas, but nothing DECIDED when.
This object does, from exactly two kinds of input the caller feeds it —
per-request SLO verdicts (``record``: did this request meet its TTFT /
inter-token target?) and the router's own ``stats()`` snapshot read at
``evaluate`` time.  Because both inputs exist identically for
:class:`fleet.sim.SimEngine` fleets (virtual time) and real
``serve.Engine`` fleets (wall time), the SAME policy object drives
both — the simulator is how a policy change is rehearsed at million-
request scale before it touches devices (docs/FLEET_SIM.md).

Policy (deliberately simple, deterministic, and auditable):

* **scale-out** when the sliding-window SLO attainment drops below
  ``target_attainment`` OR the fleet-wide queue backlog exceeds
  ``backlog_high`` × total slots — each trips ``router.add_replica``
  with a fresh engine from ``engine_factory``.
* **scale-in** when the window met the target, nothing is queued, and
  the total in-flight load would fit in ``util_low`` of the remaining
  capacity — the least-loaded replica (ties: highest id, i.e. newest)
  is drained with ``migrate=True`` (in-flight requests move with their
  progress; zero-downtime semantics from PR 8) and removed.
* stabilization is ASYMMETRIC (the HPA convention): scale-out may fire
  on every evaluation — a burst ramps faster than any cooldown — while
  scale-in waits ``cooldown_s`` after the last action of either kind;
  ``min_replicas`` / ``max_replicas`` rail both directions.

The objective the bench scores is SLO attainment per replica-second
(``charge`` integrates provisioned replica-time) — a policy only wins
by buying attainment with capacity at the right moments, not by
pinning the fleet at ``max_replicas``.

Metrics (``dttpu_autoscaler_*``, docs/OBSERVABILITY.md): ``replicas``
gauge, ``attainment`` window gauge, ``scale_out_total`` /
``scale_in_total`` counters, ``replica_seconds_total`` counter.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Tuple

from ..obs import metrics as metrics_lib
from .router import Router

__all__ = ["SLO", "Autoscaler"]


@dataclasses.dataclass(frozen=True)
class SLO:
    """p99 service-level targets: submit-to-first-token and mean
    inter-token gap (TPOT) per request."""
    ttft_s: float = 2.0
    itl_s: float = 0.1

    def __post_init__(self):
        if not self.ttft_s > 0 or not self.itl_s > 0:
            raise ValueError("SLO targets must be positive")


class Autoscaler:
    """See the module docstring.  The caller owns the cadence: feed
    ``record`` as requests finish, ``charge`` as time passes, and call
    ``evaluate(now)`` every ``eval_interval_s`` — wall seconds for a
    real fleet, virtual seconds under :class:`fleet.sim.FleetSim`."""

    def __init__(self, router: Router,
                 engine_factory: Callable[[], Any],
                 slo: SLO, *,
                 min_replicas: int = 1, max_replicas: int = 8,
                 target_attainment: float = 0.99,
                 eval_interval_s: float = 15.0,
                 cooldown_s: float = 60.0,
                 backlog_high: float = 2.0,
                 util_low: float = 0.40,
                 drain_timeout_s: Optional[float] = 30.0,
                 registry: Optional[metrics_lib.Registry] = None):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"{min_replicas}..{max_replicas}")
        self.router = router
        self.engine_factory = engine_factory
        self.slo = slo
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.target_attainment = float(target_attainment)
        self.eval_interval_s = float(eval_interval_s)
        self.cooldown_s = float(cooldown_s)
        self.backlog_high = float(backlog_high)
        self.util_low = float(util_low)
        self.drain_timeout_s = drain_timeout_s
        self.scale_outs = 0
        self.scale_ins = 0
        self.replica_seconds = 0.0
        self.history: List[tuple] = []
        self._last_action_at: Optional[float] = None
        self._w_ttft_ok = 0
        self._w_ttft_n = 0
        self._w_itl_ok = 0
        self._w_itl_n = 0
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self._m_replicas = reg.gauge(
            "dttpu_autoscaler_replicas",
            "Replicas behind the router at the last evaluation.")
        self._m_attainment = reg.gauge(
            "dttpu_autoscaler_attainment",
            "Sliding-window SLO attainment (min of TTFT and "
            "inter-token) at the last evaluation.")
        self._m_out = reg.counter(
            "dttpu_autoscaler_scale_out_total",
            "Replicas added by the autoscaler.")
        self._m_in = reg.counter(
            "dttpu_autoscaler_scale_in_total",
            "Replicas drained (migrate=True) and removed by the "
            "autoscaler.")
        self._m_seconds = reg.counter(
            "dttpu_autoscaler_replica_seconds_total",
            "Provisioned replica-time integrated by the driver "
            "(virtual seconds under the simulator).")

    # ---------------------------------------------------------- inputs

    def record(self, ttft_ok: Optional[bool] = None,
               itl_ok: Optional[bool] = None) -> None:
        """One request's SLO verdicts into the current window (either
        half may arrive alone — TTFT lands at first token, the
        inter-token verdict at retirement)."""
        if ttft_ok is not None:
            self._w_ttft_n += 1
            if ttft_ok:
                self._w_ttft_ok += 1
        if itl_ok is not None:
            self._w_itl_n += 1
            if itl_ok:
                self._w_itl_ok += 1

    def charge(self, dt_s: float, replicas: int) -> None:
        """Integrate provisioned replica-time (the cost denominator)."""
        amount = dt_s * replicas
        self.replica_seconds += amount
        self._m_seconds.inc(amount)

    def window_attainment(self) -> float:
        """min(TTFT, inter-token) attainment over the current window;
        an empty window counts as attained (no evidence of trouble)."""
        a = (self._w_ttft_ok / self._w_ttft_n if self._w_ttft_n
             else 1.0)
        b = self._w_itl_ok / self._w_itl_n if self._w_itl_n else 1.0
        return min(a, b)

    # --------------------------------------------------------- decide

    def evaluate(self, now: float) -> Optional[Tuple[str, int]]:
        """One policy evaluation at time ``now`` (the caller's clock —
        wall or virtual).  Returns ``("scale_out", rid)`` /
        ``("scale_in", rid)`` when an action was taken, else None.
        The window counters reset every evaluation."""
        stats = self.router.stats()
        replicas = len(stats)
        slots = sum(s.num_slots for s in stats.values())
        queued = sum(s.queued for s in stats.values())
        inflight = sum(s.inflight for s in stats.values())
        att = self.window_attainment()
        self._w_ttft_ok = self._w_ttft_n = 0
        self._w_itl_ok = self._w_itl_n = 0
        self._m_attainment.set(att)
        action: Optional[Tuple[str, int]] = None
        cooled = (self._last_action_at is None
                  or now - self._last_action_at >= self.cooldown_s)
        if replicas < self.min_replicas:
            # heal: the fleet fell below its floor (correlated kill,
            # quarantine) — restore capacity regardless of cooldown or
            # window attainment, one replica per evaluation.
            rid = self.router.add_replica(self.engine_factory())
            self.scale_outs += 1
            self._m_out.inc()
            action = ("scale_out", rid)
            self._last_action_at = now
            self.history.append((round(now, 9), action[0], action[1]))
        elif replicas > 0:
            # scale-out is NOT gated on cooldown: a burst ramps faster
            # than any flap-guard, and an extra replica is the cheap
            # mistake.  Scale-in is the risky direction — it waits.
            if replicas < self.max_replicas and (
                    att < self.target_attainment
                    or queued > self.backlog_high * slots):
                rid = self.router.add_replica(self.engine_factory())
                self.scale_outs += 1
                self._m_out.inc()
                action = ("scale_out", rid)
            elif (cooled
                  and replicas > self.min_replicas
                  and att >= self.target_attainment
                  and queued == 0
                  and inflight < self.util_low * slots
                  * (replicas - 1) / replicas):
                victim = self._scale_in_victim(stats)
                if victim is not None:
                    action = ("scale_in", victim)
            if action is not None:
                self._last_action_at = now
                self.history.append(
                    (round(now, 9), action[0], action[1]))
        self._m_replicas.set(len(self.router.replica_ids))
        return action

    def _scale_in_victim(self, stats) -> Optional[int]:
        """Drain-and-remove the replica whose hot prefix chains are
        cheapest to lose: primary key is the cached tokens of
        fingerprint chains held by NO other replica (migrate-based
        scale-in preserves in-flight requests but evicts the pool, so
        removing the fleet's only copy of a hot prefix re-prefills it
        from scratch for every follower), then least inflight, ties by
        highest id — retire the newest capacity first.  Fleets without
        fingerprints (contiguous engines, cold pools) score 0
        everywhere and keep the original least-loaded choice exactly.
        A drain that times out is rolled back with ``resume_replica``
        instead of failing requests."""
        holders: dict = {}
        for s in stats.values():
            for key, tokens in getattr(s, "prefix_fingerprint",
                                       {}).items():
                holders[key] = holders.get(key, 0) + 1

        def sole_hot_tokens(rid) -> int:
            fp = getattr(stats[rid], "prefix_fingerprint", {})
            return sum(tokens for key, tokens in fp.items()
                       if holders.get(key, 0) <= 1)

        victim = min(stats, key=lambda rid: (
            sole_hot_tokens(rid), stats[rid].inflight, -rid))
        ok = self.router.drain_replica(
            victim, timeout_s=self.drain_timeout_s, migrate=True)
        if not ok:
            self.router.resume_replica(victim)
            return None
        self.router.remove_replica(victim)
        self.scale_ins += 1
        self._m_in.inc()
        return victim
