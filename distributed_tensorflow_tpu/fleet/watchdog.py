"""Fleet watchdog: detect wedged/stalled replicas and quarantine them.

A replica can fail without dying: a pump thread blocked on a lost RPC,
a pathological tick that takes seconds instead of milliseconds, a host
thread wedged in a driver call.  The Router's failure path (a pump that
RAISES) never sees these — until this watchdog, a stuck-but-alive
replica was invisible until every request on it blew its deadline.

``Watchdog`` closes that gap with a **tick-deadline policy** over the
pump heartbeat every engine already publishes through
``Engine.stats()`` (``ticks_started``/``ticks_completed`` counters plus
perf_counter stamps bracketing the most recent tick — scheduler-side
bookkeeping, so reading it never touches the possibly-stuck pump
thread).  A replica is declared unhealthy when either:

* **wedged** — a tick is IN PROGRESS (started > completed) and its
  start stamp is older than ``tick_deadline_s``: the pump entered a
  tick and never came back; or
* **stalled** — the most recent COMPLETED tick took longer than
  ``tick_deadline_s``: the pump is alive but pathological (detected
  post-hoc, which is what makes the policy testable single-threaded —
  and a pump that blew its deadline once is not a pump to keep serving
  SLO-bearing traffic).

On a verdict the watchdog bumps ``dttpu_watchdog_unhealthy_total`` and
calls ``Router.quarantine_replica`` — the replica moves out of rotation
into ``router.quarantined`` (the PR 5 checkpoint-quarantine vocabulary,
applied to replicas), its in-flight requests are exported (past the
wedged pump via the bounded-wait forced export) and MIGRATED to
survivors with their progress intact, and the detached engine is kept
for the operator.

Deterministically testable: the ``stall_tick`` and ``wedge_replica``
fault kinds (resilience/faults.py) bend a targeted engine's pump at an
exact tick index, so both verdict branches are pinned by fast chaos
tests instead of real hangs (tests/test_migration.py), and
``bench.py --config=recovery`` measures the detection latency.

Threading: the watchdog owns no threads.  Call ``check()`` from any
loop you already have (the serving driver's pump loop, a metrics
scraper), or hand ``watch(stop_event)`` to a thread you own::

    wd = fleet.Watchdog(router, tick_deadline_s=2.0)
    stop = threading.Event()
    t = threading.Thread(target=wd.watch, args=(stop,),
                         name="dttpu-watchdog", daemon=True)
    t.start()
    ...
    stop.set(); t.join()
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from ..obs import goodput as goodput_lib
from ..obs import metrics as metrics_lib
from ..obs import reqtrace
from .router import Router

__all__ = ["Watchdog"]


class Watchdog:
    """Tick-deadline health policy over a ``Router``'s replicas.

    Args:
      router: the fleet to watch.
      tick_deadline_s: a pump tick older (in progress) or longer
        (completed) than this is pathological.  Set it well above the
        fleet's honest worst-case tick — first-compile ticks included,
        or warm the engines first.
      export_timeout_s: bound on waiting for an unhealthy replica's
        pump mutex during the quarantine's export (the wedged pump
        holds it forever — the forced export path takes over after
        this).
      registry: obs registry for ``dttpu_watchdog_unhealthy_total``.
    """

    def __init__(self, router: Router, *, tick_deadline_s: float = 5.0,
                 export_timeout_s: float = 0.25,
                 registry: Optional[metrics_lib.Registry] = None):
        if tick_deadline_s <= 0:
            raise ValueError(
                f"tick_deadline_s must be > 0; got {tick_deadline_s}")
        self.router = router
        self.tick_deadline_s = float(tick_deadline_s)
        self.export_timeout_s = float(export_timeout_s)
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self.unhealthy_total = reg.counter(
            "dttpu_watchdog_unhealthy_total",
            "Replicas declared unhealthy by the watchdog's "
            "tick-deadline policy and quarantined.")
        self._lock = threading.Lock()      # guards the audit log
        self.log: List[Tuple[int, str]] = []   # (replica_id, reason)

    # ------------------------------------------------------------ policy

    def verdict(self, stats, now: Optional[float] = None
                ) -> Optional[str]:
        """The tick-deadline policy on one ``EngineStats`` snapshot:
        a reason string when the replica is unhealthy, else None."""
        now = time.perf_counter() if now is None else now
        d = self.tick_deadline_s
        if stats.ticks_started > stats.ticks_completed:
            age = now - stats.last_tick_start_s
            if age > d:
                return (f"wedged: tick #{stats.ticks_started} in "
                        f"progress for {age:.3f}s (deadline {d:g}s)")
        elif stats.ticks_completed and stats.last_tick_duration_s > d:
            return (f"stalled: tick #{stats.ticks_completed} took "
                    f"{stats.last_tick_duration_s:.3f}s (deadline "
                    f"{d:g}s)")
        return None

    # ------------------------------------------------------------- drive

    def check(self, now: Optional[float] = None
              ) -> List[Tuple[int, str]]:
        """One sweep: read every replica's heartbeat, quarantine the
        unhealthy ones (their requests migrate to survivors), return
        [(replica_id, reason)] for this sweep's verdicts."""
        hits: List[Tuple[int, str]] = []
        for rid, stats in self.router.stats().items():
            reason = self.verdict(stats, now)
            if reason is None:
                continue
            # capture the victims' trace ids BEFORE the quarantine
            # exports them away — the forensic dump below snapshots
            # each span tree while the evidence is warm
            try:
                eng = self.router.replica(rid)
            except KeyError:
                continue        # raced another check()/operator action
            victims = getattr(eng, "inflight_trace_ids", lambda: [])()
            # each victim's critical-path accrual so far (obs.critpath)
            # — captured alongside the trace ids, for the same reason
            snaps = getattr(eng, "inflight_critpath", lambda: {})()
            try:
                self.router.quarantine_replica(
                    rid, reason=reason,
                    export_timeout_s=self.export_timeout_s)
            except KeyError:
                continue        # raced another check()/operator action
            self.unhealthy_total.inc()
            # the process goodput split at quarantine time: forensics
            # then show WHERE the wedged replica's wall-clock went
            # (a fat data_stall or checkpoint bucket vs a genuine hang)
            acct = goodput_lib.active()
            extra = ({"goodput_s": {k: round(v, 6) for k, v in
                                    acct.snapshot().items()}}
                     if acct is not None else {})
            # page-wire posture at quarantine time (fleet/pagewire.py,
            # getattr: routers predate the wire): how many of this
            # fleet's migrations shipped pages vs degraded to
            # re-prefill — the forensics answer to "did the victims'
            # KV travel or get recomputed"
            wire_m = getattr(self.router, "_m_wire_migrations", None)
            wire_d = getattr(self.router, "_m_wire_degraded", None)
            if wire_m is not None and wire_d is not None \
                    and self.router.page_wire is not None:
                extra = dict(extra, page_wire={
                    "shipped_total": wire_m.value,
                    "degraded_total": wire_d.value})
            for trace_id in victims:
                # the victim's own phase budget next to the process
                # goodput split: "this request spent 4 s behind other
                # tenants' prefills" is the verdict's request-level face
                cp = snaps.get(trace_id)
                per = dict(extra, critpath=cp) if cp is not None \
                    else extra
                reqtrace.forensic_dump(trace_id, "watchdog_quarantine",
                                       replica=rid, verdict=reason,
                                       **per)
            with self._lock:
                self.log.append((rid, reason))
            hits.append((rid, reason))
        return hits

    def watch(self, stop: threading.Event,
              interval_s: float = 0.5) -> None:
        """Run ``check()`` every ``interval_s`` until ``stop`` is set —
        the body for a caller-owned watchdog thread (the caller starts,
        names, and joins it; see the module example)."""
        while not stop.wait(interval_s):
            self.check()
