"""Seeded synthetic fleet workloads — the traffic half of the simulator.

A :class:`Trace` is a column-oriented request schedule (numpy arrays, one
row per request) plus a sparse list of fleet-level :class:`FleetEvent`\\ s
(correlated replica failures, wedges).  :func:`synthesize` generates one
deterministically from a seed:

* **arrivals** — an inhomogeneous Poisson process over ``horizon_s``
  virtual seconds: a diurnal sinusoid (amplitude ``diurnal_amplitude``
  around the mean rate) plus ``bursts`` Gaussian storm bumps of
  ``burst_magnitude``× the base rate at seeded times.  The total count
  is exactly ``n_requests`` (a multinomial split over time bins, then
  uniform jitter within each bin), so legs of different sizes stay
  comparable.
* **tenant mix** — categorical over ``tenants`` ``(name, share)`` pairs;
  the shares double as fair-share weights when building a
  ``TenantPolicy`` for the run.
* **shared prefixes** — ``prefix_populations`` populations with
  Zipf-like popularity; a ``prefix_fraction`` of requests carry a
  ``(prefix_id, prefix_len)`` pair whose length is drawn once per
  population, so the simulator's per-engine prefix cache sees the same
  hit structure the radix tree would.
* **adapter churn** — which of ``adapters`` LoRA adapters are hot
  drifts across the horizon (``adapter_churn`` full rotations), so
  placement sees realistic adapter locality decay.
* **correlated failures** — ``failures`` scheduled
  ``correlated_kill`` events (k victims within a pump window, seeded —
  the `resilience.faults` vocabulary), at seeded times in the middle
  80% of the horizon.

Everything downstream (sim, bench, tests) treats a Trace as read-only;
``fingerprint()`` hashes the full schedule so determinism tests can
assert bit-identical regeneration.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Tuple

import numpy as np

__all__ = ["FleetEvent", "Trace", "synthesize"]


@dataclasses.dataclass(frozen=True)
class FleetEvent:
    """One scheduled fleet-level incident in virtual time."""
    at_s: float
    kind: str = "correlated_kill"   # resilience.faults vocabulary
    k: int = 2                      # correlated_kill: victim count
    window: int = 64                # correlated_kill: pump window
    seconds: float = 5.0            # wedge-style events: stuck duration


@dataclasses.dataclass
class Trace:
    """A column-oriented request schedule (see module docstring)."""
    arrival_s: np.ndarray           # f8, sorted ascending
    plen: np.ndarray                # i4, prompt length in tokens
    new_tokens: np.ndarray          # i4, decode budget
    tenant: np.ndarray              # i2, index into ``tenants``
    prefix_id: np.ndarray           # i4, 0 = no shared prefix
    prefix_len: np.ndarray          # i4, 0 when prefix_id == 0
    adapter: np.ndarray             # i2, -1 = base model
    tenants: Tuple[Tuple[str, float], ...]
    events: Tuple[FleetEvent, ...]
    horizon_s: float
    seed: int

    def __len__(self) -> int:
        return int(self.arrival_s.shape[0])

    def subset(self, n: int) -> "Trace":
        """First ``n`` arrivals (arrival order), horizon truncated to the
        last kept arrival; events past the new horizon drop out."""
        n = min(int(n), len(self))
        horizon = float(self.arrival_s[n - 1]) if n else 0.0
        return Trace(
            arrival_s=self.arrival_s[:n], plen=self.plen[:n],
            new_tokens=self.new_tokens[:n], tenant=self.tenant[:n],
            prefix_id=self.prefix_id[:n], prefix_len=self.prefix_len[:n],
            adapter=self.adapter[:n], tenants=self.tenants,
            events=tuple(e for e in self.events if e.at_s <= horizon),
            horizon_s=horizon, seed=self.seed)

    def prefix_popularity(self) -> Tuple[Tuple[int, int], ...]:
        """Per-prefix popularity histogram: ``(prefix_id, count)``
        sorted by id, prefix-free requests excluded.  The Zipf
        structure the affinity ablation's win depends on, in a form
        two arms can compare directly."""
        ids, counts = np.unique(self.prefix_id[self.prefix_id > 0],
                                return_counts=True)
        return tuple((int(i), int(c)) for i, c in zip(ids, counts))

    def fingerprint(self) -> str:
        """SHA-256 over every column, event, and the per-prefix
        popularity histogram — the determinism pin.  Folding the
        histogram in makes fingerprint equality a direct proof that
        two ablation arms replay the identical prefix-sharing
        workload, not just identical per-request columns."""
        h = hashlib.sha256()
        for col in (self.arrival_s, self.plen, self.new_tokens,
                    self.tenant, self.prefix_id, self.prefix_len,
                    self.adapter):
            h.update(np.ascontiguousarray(col).tobytes())
        h.update(repr(self.events).encode())
        h.update(repr(self.tenants).encode())
        h.update(repr(self.prefix_popularity()).encode())
        return h.hexdigest()

    @property
    def total_tokens(self) -> int:
        return int(self.new_tokens.sum())


def synthesize(n_requests: int, *, seed: int = 0,
               horizon_s: float = 3600.0,
               tenants: Tuple[Tuple[str, float], ...] = (
                   ("interactive", 0.6), ("batch", 0.3), ("free", 0.1)),
               diurnal_amplitude: float = 0.6,
               bursts: int = 3, burst_magnitude: float = 5.0,
               burst_width_s: float = 0.0,
               plen_mean: float = 96.0, plen_sigma: float = 0.6,
               plen_max: int = 2048,
               new_tokens_mean: float = 48.0, new_tokens_sigma: float = 0.7,
               new_tokens_max: int = 512,
               prefix_populations: int = 32, prefix_fraction: float = 0.35,
               adapters: int = 8, adapter_fraction: float = 0.25,
               adapter_churn: float = 4.0,
               failures: int = 0, failure_k: int = 2,
               failure_window: int = 64) -> Trace:
    """Generate a seeded :class:`Trace` (see module docstring)."""
    if n_requests <= 0:
        raise ValueError("n_requests must be positive")
    rng = np.random.default_rng(seed)
    nbins = max(64, min(4096, n_requests // 8))
    edges = np.linspace(0.0, horizon_s, nbins + 1)
    centers = 0.5 * (edges[:-1] + edges[1:])
    dt = horizon_s / nbins

    # -- arrival intensity: diurnal sinusoid + burst storms -------------
    phase = rng.uniform(0.0, 2.0 * np.pi)
    rate = 1.0 + diurnal_amplitude * np.sin(
        2.0 * np.pi * centers / horizon_s + phase)
    rate = np.maximum(rate, 0.05)
    width = burst_width_s if burst_width_s > 0 else horizon_s / 60.0
    burst_at = rng.uniform(0.1 * horizon_s, 0.9 * horizon_s, size=bursts)
    for t0 in burst_at:
        rate = rate + burst_magnitude * np.exp(
            -0.5 * ((centers - t0) / width) ** 2)
    counts = rng.multinomial(n_requests, rate / rate.sum())
    arrival = np.repeat(edges[:-1], counts) + rng.random(n_requests) * dt
    arrival.sort(kind="stable")

    # -- per-request columns -------------------------------------------
    plen = np.clip(rng.lognormal(np.log(plen_mean), plen_sigma,
                                 size=n_requests), 4, plen_max)
    plen = plen.astype(np.int32)
    new_tokens = np.clip(rng.lognormal(np.log(new_tokens_mean),
                                       new_tokens_sigma, size=n_requests),
                         1, new_tokens_max).astype(np.int32)
    shares = np.array([s for _, s in tenants], dtype=np.float64)
    tenant = rng.choice(len(tenants), p=shares / shares.sum(),
                        size=n_requests).astype(np.int16)

    # -- shared-prefix populations (Zipf popularity, fixed lengths) ----
    prefix_id = np.zeros(n_requests, dtype=np.int32)
    prefix_len = np.zeros(n_requests, dtype=np.int32)
    if prefix_populations > 0 and prefix_fraction > 0:
        pop_len = np.clip(rng.lognormal(np.log(64.0), 0.5,
                                        size=prefix_populations),
                          8, plen_max // 2).astype(np.int32)
        ranks = np.arange(1, prefix_populations + 1, dtype=np.float64)
        pop_p = (1.0 / ranks) / (1.0 / ranks).sum()
        mask = rng.random(n_requests) < prefix_fraction
        picked = rng.choice(prefix_populations, p=pop_p,
                            size=int(mask.sum()))
        prefix_id[mask] = picked.astype(np.int32) + 1   # 0 = none
        # prefixed prompts = population prefix + their own suffix
        plen = np.where(
            mask, np.minimum(plen + pop_len[np.maximum(prefix_id - 1, 0)],
                             plen_max), plen).astype(np.int32)
        prefix_len[mask] = np.minimum(pop_len[picked], plen[mask] - 1)

    # -- adapter churn: the hot set drifts across the horizon ----------
    adapter = np.full(n_requests, -1, dtype=np.int16)
    if adapters > 0 and adapter_fraction > 0:
        amask = rng.random(n_requests) < adapter_fraction
        drift = (arrival[amask] / horizon_s) * adapter_churn * adapters
        local = rng.integers(0, max(1, adapters // 4), size=int(amask.sum()))
        adapter[amask] = ((drift.astype(np.int64) + local)
                          % adapters).astype(np.int16)

    # -- correlated failure schedule -----------------------------------
    events = tuple(
        FleetEvent(at_s=float(t), kind="correlated_kill", k=failure_k,
                   window=failure_window)
        for t in np.sort(rng.uniform(0.1 * horizon_s, 0.9 * horizon_s,
                                     size=failures)))

    return Trace(arrival_s=arrival, plen=plen, new_tokens=new_tokens,
                 tenant=tenant, prefix_id=prefix_id,
                 prefix_len=prefix_len, adapter=adapter,
                 tenants=tuple(tenants), events=events,
                 horizon_s=float(horizon_s), seed=int(seed))
