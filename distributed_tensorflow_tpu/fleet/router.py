"""Fleet router: spread requests over N Engine replicas, survive losses.

One ``serve.Engine`` is one mesh; a fleet is N of them behind a
``Router`` façade with the same ``submit() -> handle`` surface
(docs/SERVING.md §Fleet):

* **Prefix-affinity placement** — each submit reads every live
  replica's ``Engine.stats()`` snapshot (a cheap host-side read, never
  a ``/metrics`` text scrape) and scores candidates JOINTLY by load
  and expected prefix-cache reuse: effective load = ``inflight -
  affinity_weight * expected_pages_reused(prompt, fingerprint)``,
  where the fingerprint is the replica's bounded hot-radix-chain
  digest (``serve/pages.py``; mirrored by ``fleet.sim.SimEngine`` so
  sim and real fleets score identically) and the request side is
  :func:`expected_pages_reused` below.  Ties break by raw inflight
  then replica id, and empty fingerprints score 0 everywhere — the
  policy degrades EXACTLY to the original least-loaded order, so a
  replayed trace reproduces its placement decisions bit-for-bit
  (``router.placements``, pinned by tests/test_fleet.py and
  tests/test_fleet_affinity.py).  ``affinity_weight=0`` turns the
  policy off (the bench ablation's blind arm).
* **Retry within the deadline** — a submit REJECTED by one replica
  (queue full, tenant quota) tries the others in load order before the
  rejection reaches the caller; a request whose replica dies, drains,
  or is quarantined MIGRATES to a survivor as long as its deadline
  allows: the router exports a ``RequestSnapshot`` (progress intact)
  and imports it elsewhere, so decode work is preserved and the
  terminal tokens are bit-identical to an unmigrated run.  Every
  ``on_token`` the router attaches is an offset-deduplicating stream
  shim, so delivery is EXACTLY-ONCE across any number of hops — even
  on the raw-resubmit fallback when an export is impossible.
* **Rolling restarts** — ``drain_replica`` stops routing new traffic
  to a replica and (by default) migrates its in-flight requests to the
  survivors instead of waiting them out; ``remove_replica`` /
  ``add_replica`` / ``resume_replica`` swap replicas in and out with
  in-flight work migrated, turning the PR 5 backpressure/deadline/
  drain primitives into zero-downtime deploys.
* **Quarantine** — ``quarantine_replica`` takes a stuck-but-alive
  replica out of rotation (the fleet ``Watchdog``'s tick-deadline
  policy drives it; the PR 5 checkpoint-quarantine vocabulary, applied
  to replicas), force-exports what it can past the wedged pump, and
  migrates; the detached engine is kept in ``router.quarantined`` for
  the operator.
* **Chaos** — ``kill_replica`` raises at the router's pump site,
  ``stall_tick``/``wedge_replica`` (resilience.faults) bend the
  engine's own pump; the acceptance tests pin that every non-expired
  request completes on a survivor bit-identical to solo ``generate``
  with zero duplicated stream tokens (tests/test_migration.py).

The router is synchronous like the engine: the caller pumps ``step()``
(one tick of every live replica + the retry sweep) or ``drain()``.

Thread-safety: ``submit``/``cancel``/``stats``/replica management may
run on any thread concurrently with the pump.  One state lock guards
the replica table, the in-flight list, and the placement log; engines
are pumped OUTSIDE it (each engine serializes its own ticks), so a
slow tick never blocks a concurrent submit.  Lock order is strictly
router -> engine (scheduler/adapter locks) — no path takes them the
other way around.

Metrics (``registry=``): ``dttpu_router_replicas`` gauge,
``dttpu_router_requests_total`` / ``dttpu_router_retries_total`` /
``dttpu_router_replica_down_total`` / ``dttpu_router_rejected_total``
/ ``dttpu_migrations_total`` /
``dttpu_router_affinity_hits_total`` /
``dttpu_router_wire_migrations_total`` /
``dttpu_router_wire_degraded_total`` counters, the
``dttpu_router_affinity_score`` gauge, and per-replica
``dttpu_router_placed_total{replica=...}``.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import (Callable, Dict, List, Optional, Protocol, Tuple,
                    runtime_checkable)

from ..obs import metrics as metrics_lib
from ..obs import reqtrace
from ..resilience import faults as faults_lib
from ..serve import pages as pages_lib
from ..serve.engine import (Engine, QueueFullError, RequestHandle,
                            RequestSnapshot)
from .pagewire import WireError
from .tenancy import QuotaExceededError

log = logging.getLogger(__name__)

__all__ = ["EngineProtocol", "FleetHandle", "NoReplicaError", "Router",
           "expected_pages_reused", "request_chain_keys"]


def request_chain_keys(prompt, page_size: int):
    """``(fingerprint key, tokens covered)`` pairs for a request's
    prompt — the request-side half of the affinity scorer, dispatching
    on what a "prompt" is in each fleet:

    * a real token sequence -> the blake2b chain hashes of its full
      ``page_size`` chunks (``serve.pages.prompt_chain_keys``);
    * a ``fleet.sim`` prompt tuple ``(plen, prefix_id, prefix_len,
      arrival)`` -> the prefix id itself, covering the full chunks of
      ``prefix_len`` (``SimEngine`` fingerprints by prefix id — same
      key space on both sides of the score);
    * anything else (e.g. a bare int) -> no keys, affinity 0.
    """
    if type(prompt) is tuple:
        plen, prefix_id, prefix_len = prompt[0], prompt[1], prompt[2]
        covered = int(prefix_len) - int(prefix_len) % int(page_size)
        if prefix_id and covered > 0:
            return ((int(prefix_id), covered),)
        return ()
    if prompt is None or isinstance(prompt, (int, float)):
        return ()
    return pages_lib.prompt_chain_keys(prompt, page_size)


def expected_pages_reused(prompt, stats, manifest=None) -> int:
    """How many whole KV pages of ``prompt``'s prefix the replica
    behind ``stats`` (an ``EngineStats``-shaped snapshot carrying
    ``prefix_fingerprint`` + ``page_size``) would serve from its radix
    cache — the affinity term of the placement score.  The deepest
    fingerprint match wins; the cached length caps what a shallower
    cached chain can give.  0 when the replica publishes no
    fingerprint (contiguous engine, cold pool, prefix cache off) —
    which is what makes the blind fallback exact.

    ``manifest`` (a ``RequestSnapshot.shipped_pages`` tuple) overrides
    the prompt-derived keys: a migrating request scores by the chains
    its export actually handed off — prompt PLUS generated tokens —
    so a survivor already holding them (an earlier wire transfer, a
    shared prefix) outranks an equally-loaded cold one."""
    fp = getattr(stats, "prefix_fingerprint", None)
    pg = int(getattr(stats, "page_size", 0) or 0)
    if not fp or pg < 1:
        return 0
    keys = manifest if manifest else request_chain_keys(prompt, pg)
    best = 0
    for key, tokens in keys:
        cached = fp.get(key, 0)
        got = tokens if tokens < cached else cached
        if got > best:
            best = got
    return best // pg

# submit errors that mean "THIS replica won't take it right now" — safe
# to retry on another replica.  Anything else (validation, unknown
# adapter) is wrong everywhere and propagates to the caller.
_REJECTIONS = (QueueFullError, QuotaExceededError)


class NoReplicaError(RuntimeError):
    """No live replica can take this request (all dead or draining)."""


@runtime_checkable
class EngineProtocol(Protocol):
    """What the router actually requires of a replica.

    ``serve.Engine`` (a real mesh) and ``fleet.sim.SimEngine`` (the
    virtual-time cost-model replica) both conform — pinned by
    tests/test_fleet_sim.py — which is what lets one ``Router`` +
    ``Watchdog`` + ``Autoscaler`` stack run unchanged against either
    fleet.  ``add_replica`` enforces conformance with ``isinstance``
    (structural: a runtime-checkable Protocol checks member presence,
    not signatures), so a bogus replica fails loudly at registration
    instead of at first pump."""

    def submit(self, prompt, max_new_tokens=None, on_token=None,
               **kwargs): ...

    def stats(self): ...

    def step(self) -> bool: ...

    def drain(self, timeout_s=None) -> bool: ...

    def cancel(self, handle) -> bool: ...

    def export_request(self, handle, timeout_s=None): ...

    def import_request(self, snapshot, on_token=None): ...

    def load_adapter(self, adapter_id, adapter) -> None: ...

    @property
    def busy(self) -> bool: ...


class FleetHandle:
    """Caller-facing view of one fleet request across retries.

    Mirrors ``RequestHandle`` (tokens / done / status / error / ttft_s)
    but survives replica failures: after a migration or failover the
    handle simply tracks the replacement attempt.  ``replica_id`` is
    the current (or final) placement; ``attempts`` counts placements;
    ``migrations`` counts snapshot-based moves and
    ``tokens_preserved`` the decode work those moves salvaged (tokens
    carried over instead of regenerated)."""

    def __init__(self, rid: int, spec: dict,
                 deadline: Optional[float], retries_left: int,
                 router: "Router"):
        self.rid = rid
        self.spec = spec
        self.deadline = deadline            # absolute perf_counter or None
        self.retries_left = retries_left
        self.attempts = 0
        self.migrations = 0
        self.tokens_preserved = 0
        self.replica_id: Optional[int] = None
        self._router = router
        self._handle: Optional[RequestHandle] = None
        self._snapshot: Optional[RequestSnapshot] = None
        # captured page-wire records riding with an orphaned snapshot
        # (fleet/pagewire.py): host copies of the radix pages the
        # export handed off, shipped to whichever survivor imports
        self._wire_records: Optional[list] = None
        self._streamed = 0                  # tokens forwarded to the user
        self._ttft: Optional[float] = None  # pinned at first placement
        self._status = "pending"
        self.error: Optional[BaseException] = None

    @property
    def tokens(self) -> List[int]:
        if self._handle is not None:
            return self._handle.tokens
        if self._snapshot is not None:      # orphaned mid-migration
            return list(self._snapshot.generated)
        return []

    @property
    def status(self) -> str:
        return self._status

    @property
    def done(self) -> bool:
        return self._status != "pending"

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit-to-first-token of the FIRST placement that produced a
        token — migration does not reset it (the caller saw the stream
        start exactly once)."""
        if self._ttft is not None:
            return self._ttft
        return self._handle.ttft_s if self._handle is not None else None

    @property
    def tenant(self) -> str:
        return self.spec["tenant"]

    @property
    def critpath(self) -> Optional[dict]:
        """The finished critical-path breakdown (``obs.critpath``) of
        the placement that retired the request.  Migration carries the
        accrual on the snapshot, so the breakdown spans every hop; None
        while in flight or with no ledger active at submit."""
        return self._handle.critpath if self._handle is not None else None

    def _attempt_stream(self, base: int):
        """An ``on_token`` shim for one placement: forwards only tokens
        the user has not seen yet, making delivery exactly-once across
        migrations AND raw-resubmit retries.  ``base`` is the stream
        position where this attempt starts emitting (a snapshot
        import's ``stream_offset``; 0 for a fresh submit).  A raising
        user callback propagates BEFORE ``_streamed`` advances, so a
        retried attempt re-delivers exactly the tokens the user never
        accepted."""
        user = self.spec["on_token"]
        pos = [base]

        def shim(toks: List[int]) -> None:
            start = pos[0]
            pos[0] = start + len(toks)
            fresh = toks[max(0, self._streamed - start):]
            if not fresh:
                return
            if user is not None:
                user(fresh)
            self._streamed = max(self._streamed, pos[0])

        return shim

    def result(self) -> List[int]:
        """Pump the fleet until this request finishes; return its
        tokens (synchronous router: waiting IS driving)."""
        while not self.done:
            if not self._router.step():
                break
        return self.tokens

    def _finalize(self, status: str,
                  error: Optional[BaseException] = None) -> None:
        self._status = status
        self.error = error


class Router:
    """Spread ``submit()`` traffic over N ``serve.Engine`` replicas.

    Args:
      replicas: engines to start with (``add_replica`` adds more; each
        gets the next integer replica id).
      registry: obs registry for the router metrics (default: the
        process registry).
      max_retries: placements a request may consume AFTER its first
        (failover budget; rejected-at-submit probing of other replicas
        does not count).
      export_timeout_s: how long failure-path exports wait for a dead/
        quarantined replica's pump mutex before falling back to a
        forced (``clean=False``) export — the wedged-pump escape hatch.
      affinity_weight: inflight-units of load one expected reused KV
        page is worth when scoring placement candidates (see module
        doc).  0 disables prefix affinity (pure least-loaded — the
        ablation's blind arm); the default 1.0 means "prefer a replica
        holding my prefix until it is that many requests busier".
      page_wire: a ``fleet.pagewire.PageWire`` — migrations then SHIP
        the victim's radix-cached KV pages to the destination instead
        of re-prefilling them (export captures host copies, the wire
        chunks/CRCs/retries, the import radix-matches the shipped
        chain).  None (default) keeps plain re-prefill migration; any
        unrecoverable wire failure degrades to it per-migration
        (``dttpu_router_wire_degraded_total``), so correctness never
        rides the wire.
    """

    def __init__(self, replicas=(), *,
                 registry: Optional[metrics_lib.Registry] = None,
                 max_retries: int = 2,
                 export_timeout_s: float = 1.0,
                 affinity_weight: float = 1.0,
                 page_wire=None):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0; got {max_retries}")
        if affinity_weight < 0:
            raise ValueError(
                f"affinity_weight must be >= 0; got {affinity_weight}")
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self.registry = reg
        self.max_retries = int(max_retries)
        self.export_timeout_s = float(export_timeout_s)
        self.affinity_weight = float(affinity_weight)
        self.page_wire = page_wire
        # guards the replica table, draining set, in-flight list, and
        # placement log; never held while pumping an engine tick
        self._lock = threading.Lock()
        self._replicas: Dict[int, Engine] = {}
        self._draining: set = set()
        # replicas the watchdog (or operator) pulled for being unhealthy:
        # {replica_id: (engine, reason)} — detached, kept for inspection
        self.quarantined: Dict[int, Tuple[Engine, str]] = {}
        self._next_replica = 0
        self._next_rid = 0
        self._inflight: List[FleetHandle] = []
        self.placements: List[tuple] = []      # (fleet rid, replica id)
        self._m_replicas = reg.gauge(
            "dttpu_router_replicas", "Live engine replicas behind the "
            "router (draining replicas still count until empty).")
        self._m_requests = reg.counter(
            "dttpu_router_requests_total",
            "Requests accepted by the router.")
        self._m_retries = reg.counter(
            "dttpu_router_retries_total",
            "Failover resubmissions (replica death or failed handle).")
        self._m_down = reg.counter(
            "dttpu_router_replica_down_total",
            "Replicas removed after a pump failure.")
        self._m_rejected = reg.counter(
            "dttpu_router_rejected_total",
            "Submits rejected by EVERY live replica (fleet-wide "
            "backpressure surfaced to the caller).")
        self._m_migrations = reg.counter(
            "dttpu_migrations_total",
            "In-flight requests moved live (RequestSnapshot export -> "
            "import on a survivor) across failover, drain, removal, or "
            "quarantine.")
        self._m_affinity_hits = reg.counter(
            "dttpu_router_affinity_hits_total",
            "Placements that landed on a replica already holding part "
            "of the request's prefix (expected_pages_reused > 0).")
        self._m_affinity_score = reg.gauge(
            "dttpu_router_affinity_score",
            "Expected KV pages reused by the most recent placement "
            "(0 = blind landing).")
        self._m_wire_migrations = reg.counter(
            "dttpu_router_wire_migrations_total",
            "Migrations whose KV pages were shipped over the page "
            "wire and adopted by the destination pool (the skipped "
            "re-prefill windows show up in the destination's "
            "EngineStats.prefill_windows_skipped_total).")
        self._m_wire_degraded = reg.counter(
            "dttpu_router_wire_degraded_total",
            "Migrations that fell back to re-prefill after an "
            "unrecoverable page-wire failure (link down, chunk "
            "retries exhausted).")
        self._m_placed: Dict[int, metrics_lib.Counter] = {}
        for engine in replicas:
            self.add_replica(engine)

    # -------------------------------------------------------- replicas

    def add_replica(self, engine: Engine) -> int:
        if not isinstance(engine, EngineProtocol):
            missing = [m for m in ("submit", "stats", "step", "drain",
                                   "cancel", "export_request",
                                   "import_request", "load_adapter",
                                   "busy")
                       if not hasattr(engine, m)]
            raise TypeError(
                f"replica {type(engine).__name__} does not implement "
                f"the router's EngineProtocol (missing: {missing})")
        with self._lock:
            rid = self._next_replica
            self._next_replica += 1
            self._replicas[rid] = engine
            # chaos identity: engine-targeted fault kinds (stall_tick,
            # wedge_replica) address this replica by its fleet id
            engine.chaos_tag = rid
            self._m_placed[rid] = self.registry.counter(
                "dttpu_router_placed_total",
                "Requests placed, by replica.",
                labels={"replica": str(rid)})
            self._m_replicas.set(len(self._replicas))
        return rid

    @property
    def replica_ids(self):
        with self._lock:
            return tuple(self._replicas)

    def replica(self, replica_id: int) -> Engine:
        with self._lock:
            return self._replicas[replica_id]

    def stats(self) -> Dict[int, object]:
        """{replica_id: EngineStats} for every live replica.  Paged-KV
        engines carry their page-pool occupancy and radix prefix-cache
        counters in the same snapshot (``pages_free``,
        ``prefix_hits_total``, ... — serve/pages.py), so fleet-level
        capacity dashboards read one surface, not N /metrics scrapes."""
        with self._lock:
            live = list(self._replicas.items())
        return {rid: eng.stats() for rid, eng in live}

    def pages_free(self) -> int:
        """Fleet-wide free KV pages (sum over live paged replicas) —
        the admission-headroom signal a capacity autoscaler would act
        on; 0 when every replica runs the contiguous layout."""
        return sum(s.pages_free for s in self.stats().values())

    def load_adapter(self, adapter_id: str, adapter) -> None:
        """Register a LoRA adapter on EVERY live replica (each holds its
        own device table) so placement stays adapter-agnostic."""
        with self._lock:
            live = list(self._replicas.values())
        for eng in live:
            eng.load_adapter(adapter_id, adapter)

    # ---------------------------------------------------------- intake

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable[[List[int]], None]] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default",
               adapter_id: Optional[str] = None) -> FleetHandle:
        """Place one request on the best-scoring live replica (load
        net of prefix affinity — see module doc) -> handle.  Replicas
        that reject (queue full, tenant quota) are skipped for the
        next-scored one; if EVERY live replica rejects, the last
        rejection propagates (fleet-wide backpressure).  ``deadline_s``
        is a FLEET deadline: retries submit with the remaining budget."""
        deadline = (None if deadline_s is None
                    else time.perf_counter() + deadline_s)
        # mint the request trace id at the FLEET front door, so every
        # placement attempt and migration hop shares one lane; None
        # (tracing off) costs a module check per request
        trace_id = reqtrace.mint()
        with self._lock:
            fh = FleetHandle(
                rid=self._next_rid,
                spec=dict(prompt=prompt, max_new_tokens=max_new_tokens,
                          on_token=on_token, tenant=tenant,
                          adapter_id=adapter_id, trace_id=trace_id),
                deadline=deadline, retries_left=self.max_retries,
                router=self)
            self._next_rid += 1
            self._place(fh, raise_rejection=True)
            self._m_requests.inc()
            self._inflight.append(fh)
        return fh

    def _candidates(self, fh: Optional[FleetHandle] = None
                    ) -> Tuple[List[int], Dict[int, int]]:
        """Live, non-draining replica ids in placement order, plus each
        candidate's affinity score (expected pages reused; all 0 when
        scoring is off or no prompt is given).  Order: effective load
        ``inflight - affinity_weight * affinity`` first, ties by raw
        inflight then replica id — with no fingerprints anywhere this
        is EXACTLY the original least-loaded (inflight, id) order, so
        blind-fleet placement replays unchanged.  Called with the
        router lock held."""
        ids = [rid for rid in self._replicas
               if rid not in self._draining]
        stats = {rid: self._replicas[rid].stats() for rid in ids}
        if fh is None or not self.affinity_weight:
            ids.sort(key=lambda rid: (stats[rid].inflight, rid))
            return ids, {rid: 0 for rid in ids}
        prompt = fh.spec["prompt"]
        manifest = getattr(fh._snapshot, "shipped_pages", None)
        aff = {rid: expected_pages_reused(prompt, stats[rid],
                                          manifest=manifest)
               for rid in ids}
        ids.sort(key=lambda rid: (
            stats[rid].inflight - self.affinity_weight * aff[rid],
            stats[rid].inflight, rid))
        return ids, aff

    def _place(self, fh: FleetHandle, raise_rejection: bool) -> bool:
        """Try to place ``fh`` on each candidate replica in score order
        — a snapshot-carrying handle is IMPORTED (progress intact), a
        fresh one submitted.  True on placement; False when every
        candidate rejected (or none exists) and ``raise_rejection`` is
        off.  Fresh submits, rejection probing, AND migration/failover
        re-placement all pass through here, so the affinity scorer
        covers every path a request can take onto a replica — a
        migrated request whose old replica published its pages via
        ``handoff`` scores the survivor holding them.  Called with the
        router lock held (engine submits take the engine's own state
        lock — lock order router -> engine, never reversed)."""
        remaining = None
        if fh.deadline is not None:
            remaining = fh.deadline - time.perf_counter()
            if remaining <= 0:
                fh._finalize("deadline_exceeded")
                return False
        candidates, affinity = self._candidates(fh)
        if not candidates:
            err = NoReplicaError("no live replica available")
            if raise_rejection:
                raise err
            fh._finalize("failed", error=fh.error or err)
            return False
        snap = fh._snapshot
        if snap is not None and fh.deadline is not None:
            # the fleet deadline stays authoritative across the
            # export->import gap (the snapshot froze its remaining
            # budget at export time); an engine-level default deadline
            # in the snapshot is left alone
            snap.deadline_remaining_s = remaining
        last: Optional[BaseException] = None
        for rid in candidates:
            eng = self._replicas[rid]
            try:
                if snap is not None:
                    # pre-warm: ship the exported radix pages into THIS
                    # candidate's pool first, so the import below
                    # radix-matches and skips the shipped prefill
                    # windows.  Purely best-effort — every wire failure
                    # shape ends with a plain re-prefill import.
                    self._ship_wire_pages(fh, eng, snap)
                    h = eng.import_request(
                        snap,
                        on_token=fh._attempt_stream(snap.stream_offset))
                else:
                    h = eng.submit(
                        fh.spec["prompt"], fh.spec["max_new_tokens"],
                        on_token=fh._attempt_stream(0),
                        deadline_s=remaining,
                        tenant=fh.spec["tenant"],
                        adapter_id=fh.spec["adapter_id"],
                        trace_id=fh.spec.get("trace_id"))
            except _REJECTIONS as e:
                last = e
                continue
            except Exception as e:
                # not backpressure: this request cannot be placed
                # anywhere (validation/compat error).  Surface it
                # instead of spinning forever in the sweep.
                if raise_rejection:
                    raise
                fh._finalize("failed", error=e)
                return False
            if snap is not None:
                # consumed: further failovers re-export from the new
                # replica, which now owns the freshest progress
                fh._snapshot = None
                fh._wire_records = None
                fh.migrations += 1
                fh.tokens_preserved += len(snap.generated)
                self._m_migrations.inc()
            fh._handle = h
            fh.replica_id = rid
            fh.attempts += 1
            self.placements.append((fh.rid, rid))
            self._m_placed[rid].inc()
            score = affinity.get(rid, 0)
            if score > 0:
                self._m_affinity_hits.inc()
            self._m_affinity_score.set(score)
            return True
        if raise_rejection:
            self._m_rejected.inc()
            raise last
        return False                    # stays pending; retried next step

    def _ship_wire_pages(self, fh: FleetHandle, eng: Engine,
                         snap: RequestSnapshot) -> None:
        """Ship an orphan's captured radix pages into candidate ``eng``
        before its import (``_place``).  Outcomes: pages adopted (the
        import skips their prefill windows), destination refused (0
        adopted — records kept for the next candidate), or the wire
        failed unrecoverably (``WireError`` — records dropped, this
        migration re-prefills: ``dttpu_router_wire_degraded_total``)."""
        if self.page_wire is None or not fh._wire_records:
            return
        try:
            adopted = self.page_wire.ship(fh._wire_records, eng, snap)
        except WireError as e:
            log.warning("page wire failed for fleet rid %d — "
                        "degrading to re-prefill migration: %s",
                        fh.rid, e)
            fh._wire_records = None
            self._m_wire_degraded.inc()
            return
        if adopted:
            self._m_wire_migrations.inc()

    # ----------------------------------------------------------- drive

    @property
    def busy(self) -> bool:
        with self._lock:
            live = list(self._replicas.values())
            pending = any(not fh.done for fh in self._inflight)
        return pending or any(eng.busy for eng in live)

    def step(self) -> bool:
        """One fleet tick: pump every live replica (a replica whose pump
        RAISES is declared dead and its in-flight requests rerouted),
        then sweep handles — finalize finished ones, resubmit failed or
        orphaned ones that still have deadline and retry budget.

        Engines are pumped WITHOUT the router lock (each engine's pump
        mutex serializes its ticks), so submit/cancel/stats on other
        threads never stall behind a device dispatch."""
        did = False
        plan = faults_lib.active()
        with self._lock:
            live = list(self._replicas.items())
        for rid, eng in live:
            try:
                if plan is not None:
                    plan.on_replica_step(rid)
                did = eng.step() or did
            except Exception as e:
                self._replica_down(rid, e)
                did = True
        with self._lock:
            did = self._sweep() or did
        return did

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Pump until every request reached a terminal status; with
        ``timeout_s``, stop at the budget and return False."""
        deadline = (None if timeout_s is None
                    else time.perf_counter() + timeout_s)
        while self.busy:
            if deadline is not None and time.perf_counter() >= deadline:
                return False
            self.step()
        return True

    def cancel(self, fh: FleetHandle) -> bool:
        """Abort one fleet request; False if already terminal."""
        with self._lock:
            if fh.done:
                return False
            handle, eng = fh._handle, self._replicas.get(fh.replica_id)
            fh._finalize("cancelled")
        if handle is not None and eng is not None:
            eng.cancel(handle)
        return True

    # ----------------------------------------------- rolling restarts

    def drain_replica(self, replica_id: int,
                      timeout_s: Optional[float] = None,
                      migrate: bool = True) -> bool:
        """Stop routing NEW traffic to ``replica_id`` and empty it.
        With ``migrate=True`` (the default) its in-flight requests are
        exported and re-placed on the survivors with their progress
        intact — the drain completes in one export/import round instead
        of waiting out every decode.  ``migrate=False`` keeps the
        legacy wait-drain (pump the fleet until the replica empties).
        Returns False on timeout (the replica stays draining — call
        again, ``remove_replica`` to force, or ``resume_replica`` to
        put it back in rotation)."""
        with self._lock:
            if replica_id not in self._replicas:
                raise KeyError(f"unknown replica {replica_id}")
            self._draining.add(replica_id)
            eng = self._replicas[replica_id]
            if migrate and not any(
                    r != replica_id and r not in self._draining
                    for r in self._replicas):
                # no survivor to migrate to: fall back to wait-drain
                # rather than failing the in-flight requests
                migrate = False
            victims = (self._victims_locked(replica_id) if migrate
                       else [])
        if migrate:
            # blocking clean exports: a draining replica's pump is
            # healthy, so each export just waits out the running tick
            self._export_and_orphan(victims, eng, timeout_s=None)
            with self._lock:
                self._sweep()       # re-place on survivors immediately
        deadline = (None if timeout_s is None
                    else time.perf_counter() + timeout_s)
        while True:
            with self._lock:
                waiting = any(fh.replica_id == replica_id
                              for fh in self._inflight if not fh.done)
            if not (eng.busy or waiting):
                break
            if deadline is not None and time.perf_counter() >= deadline:
                return False
            if not self.step():
                break
        return not eng.busy

    def resume_replica(self, replica_id: int) -> None:
        """Put a draining replica back into rotation (the rolling-
        restart counterpart of ``drain_replica`` when the restart is
        done in place)."""
        with self._lock:
            if replica_id not in self._replicas:
                raise KeyError(f"unknown replica {replica_id}")
            self._draining.discard(replica_id)

    def remove_replica(self, replica_id: int) -> Engine:
        """Take ``replica_id`` out of the fleet.  In-flight requests on
        it are exported and MIGRATED to the survivors with their
        progress intact (deadline/retry budget permitting).  Returns
        the detached engine (restart it, then ``add_replica`` it
        back)."""
        with self._lock:
            eng = self._replicas.pop(replica_id)
            self._draining.discard(replica_id)
            self._m_replicas.set(len(self._replicas))
            victims = self._victims_locked(replica_id)
        self._export_and_orphan(victims, eng,
                                timeout_s=self.export_timeout_s)
        with self._lock:
            self._sweep()
        return eng

    def quarantine_replica(self, replica_id: int,
                           reason: str = "unhealthy",
                           export_timeout_s: Optional[float] = None
                           ) -> Engine:
        """Pull a stuck-but-alive replica out of rotation (the fleet
        ``Watchdog``'s action; same vocabulary as the PR 5 checkpoint
        quarantine): the engine moves to ``router.quarantined`` with
        its ``reason``, its requests are exported — past a wedged pump
        if need be (``export_timeout_s``, default the router's) — and
        migrated to the survivors.  Returns the detached engine for
        inspection; ``add_replica`` re-admits it after repair."""
        with self._lock:
            if replica_id not in self._replicas:
                raise KeyError(f"unknown replica {replica_id}")
            eng = self._replicas.pop(replica_id)
            self._draining.discard(replica_id)
            self.quarantined[replica_id] = (eng, str(reason))
            self._m_replicas.set(len(self._replicas))
            victims = self._victims_locked(replica_id)
        timeout = (self.export_timeout_s if export_timeout_s is None
                   else export_timeout_s)
        self._export_and_orphan(victims, eng, timeout_s=timeout)
        with self._lock:
            self._sweep()
        return eng

    # ------------------------------------------------------- internals

    def _victims_locked(self, replica_id: int
                        ) -> List[Tuple[FleetHandle,
                                        Optional[RequestHandle]]]:
        """(handle, engine handle) pairs still pending on a replica —
        router lock held."""
        return [(fh, fh._handle) for fh in self._inflight
                if fh.replica_id == replica_id and not fh.done]

    def _export_and_orphan(self, victims, eng: Engine,
                           timeout_s: Optional[float],
                           error: Optional[BaseException] = None) -> None:
        """Export each victim's live state from ``eng`` and mark the
        fleet handle orphaned-with-snapshot (the sweep imports it on a
        survivor).  An export that fails — the request finished
        concurrently, or the engine is too far gone — falls back to
        cancel + raw resubmit, which the stream shim still keeps
        exactly-once.  Called WITHOUT the router lock (exports take the
        engine's pump/state locks; order router -> engine holds)."""
        for fh, h in victims:
            snap: Optional[RequestSnapshot] = None
            recs: Optional[list] = None
            if h is not None:
                if h.done:
                    continue            # sweep finalizes from the handle
                try:
                    snap = eng.export_request(h, timeout_s=timeout_s)
                except Exception:
                    snap = None
                if snap is None:
                    if h.done:
                        continue        # finished during the export race
                    eng.cancel(h)       # stop the doomed attempt
                elif self.page_wire is not None \
                        and getattr(snap, "shipped_pages", None):
                    # page-wire capture: host copies of the pages the
                    # export just handed off, while the source is still
                    # reachable.  Best-effort — a source too far gone
                    # to read simply ships nothing (re-prefill).
                    try:
                        recs = eng.export_wire_pages(
                            snap, timeout_s=timeout_s) or None
                    except Exception:
                        recs = None
            with self._lock:
                if fh.done:
                    continue
                if fh._ttft is None and h is not None:
                    fh._ttft = h.ttft_s
                fh._snapshot = snap
                fh._wire_records = recs
                if error is not None:
                    fh.error = error
                fh._handle = None       # orphaned: the sweep re-places
                fh.replica_id = None
                self._m_retries.inc()

    def _replica_down(self, replica_id: int, error: BaseException) -> None:
        with self._lock:
            eng = self._replicas.pop(replica_id, None)
            self._draining.discard(replica_id)
            self._m_down.inc()
            self._m_replicas.set(len(self._replicas))
            victims = self._victims_locked(replica_id)
        if eng is not None:
            # the pump raised but the engine's HOST state is intact (the
            # scheduler's locks were released with the failing tick), so
            # in-flight progress is still exportable — the kill loses a
            # replica, not the decode work on it
            self._export_and_orphan(victims, eng,
                                    timeout_s=self.export_timeout_s,
                                    error=error)

    def _sweep(self) -> bool:
        """Called with the router lock held."""
        did = False
        still: List[FleetHandle] = []
        for fh in self._inflight:
            if fh.done:
                continue
            h = fh._handle
            if h is None:               # orphaned (death/removal/retry)
                did = True
                self._place(fh, raise_rejection=False)
            elif h.done:
                did = True
                if h.status == "failed" and fh.retries_left > 0 \
                        and self._deadline_ok(fh):
                    fh.retries_left -= 1
                    fh._handle = None
                    fh.replica_id = None
                    self._m_retries.inc()
                    self._place(fh, raise_rejection=False)
                elif h.status == "failed":
                    fh._finalize("failed", error=h.error)
                else:                   # ok | deadline_exceeded | cancelled
                    fh._finalize(h.status, error=h.error)
            if not fh.done:
                still.append(fh)
        self._inflight = still
        return did

    @staticmethod
    def _deadline_ok(fh: FleetHandle) -> bool:
        return fh.deadline is None or time.perf_counter() < fh.deadline
