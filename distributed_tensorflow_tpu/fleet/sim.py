"""Virtual-time fleet simulator: the REAL Router, synthetic engines.

"Heavy traffic from millions of users" cannot be validated by replaying
tens of requests through real engines — but every fleet policy we ship
(least-loaded placement, fair-share admission, snapshot migration,
watchdog quarantine, autoscaling) is HOST-side logic that never touches
a device.  This module replays millions of :mod:`.workload` requests
through the unmodified :class:`fleet.Router` at virtual-time speed:

* :class:`SimEngine` implements the engine surface the router consumes
  (``submit/stats/step/busy/cancel/export_request/import_request`` —
  :data:`fleet.router.EngineProtocol`) with the serve scheduler's tick
  shape — admit into free slots, one prefill window per prompt per
  tick, ``tick_steps`` decode tokens per active slot per tick, last
  prefill window fused with the first emitted token — but each tick
  advances a per-engine VIRTUAL clock instead of running a device
  program.  Tick durations come from a :class:`CostModel`.
* :class:`CostModel` prices one prefill window and one decode tick in
  seconds.  It can be built three ways: ``analytic`` (closed-form
  transformer FLOPs, no JAX needed), ``from_targets`` (the PR 10 graph
  tier: ``analysis.graph.target_cost`` over the REAL scheduler's
  ``graph_targets()`` specs — prices the actual hot executables with
  zero device work), or ``calibrate`` (solve an effective-FLOPs +
  dispatch-overhead point from two measured wall times, then price any
  shape through the same roofline — bench.py's validation leg).
* :class:`FleetSim` is the discrete-event driver: it advances a shared
  :class:`SimClock`, flushes trace arrivals into ``Router.submit``
  (each request carries its TRUE arrival time, so queueing delay is
  measured from arrival even when submits are batched), arms
  ``correlated_kill`` faults on the active ``resilience.faults`` plan
  as the trace schedule comes due, runs the real ``fleet.Watchdog``
  against virtual heartbeats, and lets an ``autoscaler.Autoscaler``
  add/drain replicas mid-run.  ``Router.step()`` stays the one pump:
  a ``SimEngine`` ticks only when the shared clock has caught up to
  its virtual clock, and catches up over multiple ticks in one pump
  (placement/migration/sweep decisions between ticks are unchanged —
  the router only intervenes at submits, failures, and scaling, all of
  which happen between driver rounds).

Deliberate modeling simplifications (documented in docs/FLEET_SIM.md):
decode ticks cost the fixed-batch executable price regardless of how
many slots are live (matching the real padded program), shared-prefix
reuse is a per-engine seen-set over full chunks (no radix eviction),
and token VALUES are not simulated (streams carry zeros; stream
offsets, dedup, and counts are exact).

Determinism: every decision derives from the seeded trace, the seeded
fault plan, and the cost model — two runs of the same config produce
bit-identical event logs, placements, and SLO numbers (pinned by
tests/test_fleet_sim.py).
"""
from __future__ import annotations

import collections
import math
from array import array
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis import graph as graph_lib
from ..obs import metrics as metrics_lib
from ..obs import reqtrace
from ..resilience import faults as faults_lib
from ..serve.engine import QueueFullError, RequestSnapshot
from . import watchdog as watchdog_lib
from .autoscaler import SLO, Autoscaler
from .router import NoReplicaError, Router
from .tenancy import TenantPolicy
from .workload import Trace

__all__ = ["CostModel", "FleetSim", "HardwarePoint", "SimClock",
           "SimEngine", "SimMetrics"]

_EPS = 1e-12


# ------------------------------------------------------------ cost model


class HardwarePoint:
    """One roofline operating point: sustained FLOP/s, HBM bandwidth,
    and per-dispatch host overhead.  The default is a mid-size
    inference accelerator; ``CostModel.calibrate`` replaces it with a
    measured point."""
    __slots__ = ("peak_flops", "peak_bw", "overhead_s")

    def __init__(self, peak_flops: float = 180e12,
                 peak_bw: float = 820e9, overhead_s: float = 50e-6):
        self.peak_flops = float(peak_flops)
        self.peak_bw = float(peak_bw)
        self.overhead_s = float(overhead_s)


class CostModel:
    """Virtual-seconds prices for the two serve-tier tick phases.

    A tick costs ``overhead_s`` (host dispatch) + ``decode_tick_s``
    (when any slot is decoding; the fixed-batch executable price —
    batch occupancy does not change it, exactly like the real padded
    program) + ``prefill_window_s`` per prefilling request (one window
    each per tick)."""
    __slots__ = ("prefill_window_s", "decode_tick_s", "overhead_s",
                 "provenance")

    def __init__(self, prefill_window_s: float, decode_tick_s: float,
                 overhead_s: float = 50e-6,
                 provenance: str = "explicit"):
        if not prefill_window_s > 0 or not decode_tick_s > 0:
            raise ValueError("phase costs must be positive")
        if overhead_s < 0:
            raise ValueError("overhead_s must be >= 0")
        self.prefill_window_s = float(prefill_window_s)
        self.decode_tick_s = float(decode_tick_s)
        self.overhead_s = float(overhead_s)
        self.provenance = provenance

    def __repr__(self):
        return (f"CostModel(window={self.prefill_window_s:.3e}s, "
                f"tick={self.decode_tick_s:.3e}s, "
                f"overhead={self.overhead_s:.3e}s, {self.provenance})")

    @classmethod
    def from_costs(cls, window: graph_lib.Cost, tick: graph_lib.Cost,
                   hw: Optional[HardwarePoint] = None,
                   provenance: str = "graph") -> "CostModel":
        """Price two graph-tier :class:`analysis.graph.Cost`\\ s on a
        roofline point."""
        hw = hw or HardwarePoint()
        return cls(window.time_s(hw.peak_flops, hw.peak_bw),
                   tick.time_s(hw.peak_flops, hw.peak_bw),
                   overhead_s=hw.overhead_s, provenance=provenance)

    @classmethod
    def from_targets(cls, targets, hw: Optional[HardwarePoint] = None
                     ) -> "CostModel":
        """Price the REAL scheduler's hot executables: ``targets`` is
        ``SlotScheduler.graph_targets()`` (abstract specs; tracing via
        ``analysis.graph.target_cost`` does no device work)."""
        costs = {t.name: graph_lib.target_cost(t) for t in targets}
        return cls.from_costs(costs["prefill_window"],
                              costs["decode_tick"], hw,
                              provenance="graph_targets")

    @classmethod
    def analytic(cls, *, n_params: float, prefill_chunk: int,
                 num_slots: int, tick_steps: int,
                 hw: Optional[HardwarePoint] = None,
                 dtype_bytes: int = 4) -> "CostModel":
        """Closed-form transformer price (2·P FLOPs per token, one
        parameter read per pass) — no JAX import; the pure-sim default
        for tests and the million-request bench legs."""
        hw = hw or HardwarePoint()
        window = graph_lib.Cost(
            flops=2.0 * n_params * prefill_chunk,
            bytes=n_params * dtype_bytes, peak_bytes=0.0)
        tick = graph_lib.Cost(
            flops=2.0 * n_params * num_slots * tick_steps,
            bytes=n_params * dtype_bytes * tick_steps, peak_bytes=0.0)
        return cls.from_costs(window, tick, hw, provenance="analytic")

    @classmethod
    def calibrate(cls, window: graph_lib.Cost, tick: graph_lib.Cost,
                  measured_window_s: float, measured_tick_s: float
                  ) -> "CostModel":
        """Two-point calibration: solve ``t0 + flops/F_eff = T`` from
        the measured wall times of the two executables whose static
        Costs the graph tier provides, then price through the same
        roofline.  The fit is REJECTED — falling back to the measured
        times directly — when it cannot explain the measurements:
        degenerate inputs (equal times, inverted order) or an implied
        negative host overhead, which happens when the two executables'
        flops are too close for their time difference to be a compute
        effect (tiny CPU models: dispatch count, not flops, separates
        them — a clamped t0 there silently inflates both prices)."""
        df = tick.flops - window.flops
        dt = measured_tick_s - measured_window_s
        if df > 0 and dt > 0:
            f_eff = df / dt
            t0 = measured_window_s - window.flops / f_eff
            if t0 >= 0:
                return cls(t0 + window.flops / f_eff,
                           t0 + tick.flops / f_eff,
                           overhead_s=0.0, provenance="calibrated")
        return cls(measured_window_s, measured_tick_s, overhead_s=0.0,
                   provenance="measured")


# ------------------------------------------------------------ sim engine


class SimClock:
    """The fleet's shared virtual clock (driver-owned)."""
    __slots__ = ("now",)

    def __init__(self, now: float = 0.0):
        self.now = float(now)


class _SimStats:
    """Mutable, engine-owned stats snapshot with the attribute surface
    the router/watchdog/autoscaler read from ``EngineStats``.  One
    object per engine, updated in place — ``stats()`` at fleet-sim call
    rates cannot afford a frozen dataclass per call."""
    __slots__ = ("queued", "prefilling", "active", "num_slots",
                 "inflight", "inflight_per_tenant",
                 "tokens_inflight_per_tenant", "pages_total",
                 "pages_free", "pages_per_request",
                 "prefix_lookups_total", "prefix_hits_total",
                 "prefix_tokens_reused_total", "ticks_started",
                 "ticks_completed", "last_tick_start_s",
                 "last_tick_end_s", "last_tick_duration_s",
                 "page_size", "prefix_fingerprint")

    def __init__(self, num_slots: int):
        self.queued = 0
        self.prefilling = 0
        self.active = 0
        self.num_slots = num_slots
        self.inflight = 0
        self.inflight_per_tenant: Dict[str, int] = {}
        self.tokens_inflight_per_tenant: Dict[str, int] = {}
        self.pages_total = 0
        self.pages_free = 0
        self.pages_per_request = 0.0
        self.prefix_lookups_total = 0
        self.prefix_hits_total = 0
        self.prefix_tokens_reused_total = 0
        # prefix-affinity mirror of the real pool's fingerprint
        # (serve/pages.py): {prefix_id: cached tokens}, keyed by the
        # trace's prefix id — the same keys request_chain_keys yields
        # for a sim prompt tuple, so the router scores sim and real
        # fleets through one code path
        self.page_size = 0
        self.prefix_fingerprint: Dict[int, int] = {}
        self.ticks_started = 0
        self.ticks_completed = 0
        self.last_tick_start_s = 0.0
        self.last_tick_end_s = 0.0
        self.last_tick_duration_s = 0.0

    @property
    def free_slots(self) -> int:
        return self.num_slots - self.prefilling - self.active


class _SimRequest:
    """One in-flight simulated request; doubles as its own engine
    handle (``tokens/done/status/error/ttft_s`` — what ``FleetHandle``
    reads)."""
    __slots__ = ("rid", "prompt_ref", "plen", "context", "budget",
                 "max_new_tokens", "tenant", "adapter_id", "prefix_id",
                 "prefix_len", "on_token", "arrival_vt", "first_vt",
                 "span_base", "span_start_vt", "emitted",
                 "windows_left", "status", "error", "deadline_vt",
                 "trace_id", "enqueue_vt", "cp_queue", "cp_prefill",
                 "cp_decode", "cp_interf", "cp_migr")

    def __init__(self):
        self.error: Optional[BaseException] = None
        self.first_vt: Optional[float] = None
        self.span_start_vt: Optional[float] = None
        self.status = "pending"
        self.trace_id: Optional[str] = None
        # critical-path accrual on virtual time, mirroring the serve
        # scheduler's obs.critpath phase vocabulary (queue wait is
        # measured from ENGINE enqueue, not true arrival, so a migrated
        # request never double-counts its pre-migration span)
        self.cp_queue = 0.0
        self.cp_prefill = 0.0
        self.cp_decode = 0.0
        self.cp_interf = 0.0
        self.cp_migr = 0.0

    @property
    def done(self) -> bool:
        return self.status != "pending"

    @property
    def tokens(self) -> List[int]:
        # token VALUES are not simulated; counts/offsets are exact
        return [0] * self.emitted

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_vt is None:
            return None
        return self.first_vt - self.arrival_vt

    @property
    def critpath(self) -> Dict[str, float]:
        """The serve handle's breakdown surface (``FleetHandle.critpath``
        reads this), in the obs.critpath phase vocabulary.  Backpressure
        requeue cannot happen in the sim (queue caps reject at submit),
        so that phase is structurally zero here."""
        return {"queue_wait": self.cp_queue,
                "prefill_compute": self.cp_prefill,
                "prefill_interference": self.cp_interf,
                "decode_compute": self.cp_decode,
                "migration": self.cp_migr,
                "backpressure_requeue": 0.0}


class SimEngine:
    """A virtual-time replica conforming to ``EngineProtocol``.

    Prompts may be plain ints/sequences (length = token count) or the
    fleet-sim tuple ``(plen, prefix_id, prefix_len, arrival_vt)`` —
    carrying the TRUE arrival time through ``Router.submit`` and
    ``RequestSnapshot.prompt`` keeps queueing delay and TTFT honest
    across batched submits and migrations."""

    def __init__(self, cost_model: CostModel, *, num_slots: int = 8,
                 prefill_chunk: int = 32, tick_steps: int = 8,
                 policy: Optional[TenantPolicy] = None,
                 clock: Optional[SimClock] = None,
                 metrics: Optional["SimMetrics"] = None,
                 max_queue_depth: Optional[int] = None,
                 default_max_new_tokens: int = 16,
                 trace_sample: int = 64):
        self.cost = cost_model
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.tick_steps = int(tick_steps)
        self.policy = policy
        self.clock = clock
        self.metrics = metrics
        self.max_queue_depth = max_queue_depth
        self.default_max_new_tokens = int(default_max_new_tokens)
        # request tracing on VIRTUAL time: a million-request sim cannot
        # afford a lane per request, so only 1-in-``trace_sample``
        # router-minted trace ids are kept (<=1 keeps all); a migrated
        # request's sampling verdict rides its snapshot, lane intact
        self.trace_sample = int(trace_sample)
        self._trace_seen = 0
        self.vt = clock.now if clock is not None else 0.0
        # how far past clock.now one step() may pre-run: the fleet
        # driver sets this to its round quantum so a busy engine
        # simulates the whole upcoming window tick-exactly instead of
        # one tick per round (admission is quantised anyway)
        self.lookahead_s = 0.0
        self.chaos_tag = 0
        self._queue = (policy.make_queue() if policy is not None
                       else collections.deque())
        self._prefilling: List[_SimRequest] = []
        self._active: List[_SimRequest] = []
        self._stats = _SimStats(self.num_slots)
        # the sim's "page" is its prefill chunk: reuse is granted in
        # whole chunks, so that is the granularity the router's
        # affinity scorer must divide by
        self._stats.page_size = self.prefill_chunk
        self._prefix_seen: set = set()
        self._adapters: set = set()
        self._next_rid = 0
        self._wedged_until: Optional[float] = None
        # shared zero-token payloads, one per emission size (stream
        # shims only slice them)
        self._zeros = [[0] * k for k in range(self.tick_steps + 1)]

    # ------------------------------------------------------ intake

    def _parse_prompt(self, prompt) -> Tuple[int, int, int, float]:
        if type(prompt) is tuple:
            return prompt
        now = self.clock.now if self.clock is not None else self.vt
        if isinstance(prompt, (int, np.integer)):
            return int(prompt), 0, 0, now
        return len(prompt), 0, 0, now

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default",
               adapter_id: Optional[str] = None,
               trace_id: Optional[str] = None) -> _SimRequest:
        plen, prefix_id, prefix_len, arrival = self._parse_prompt(prompt)
        budget = (self.default_max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if budget < 1:
            raise ValueError(f"max_new_tokens must be >= 1; got {budget}")
        st = self._stats
        if self.max_queue_depth is not None \
                and st.queued >= self.max_queue_depth:
            raise QueueFullError(
                f"sim queue full ({st.queued}/{self.max_queue_depth})")
        if self.policy is not None:
            self.policy.check_admission(
                tenant, budget,
                inflight=st.inflight_per_tenant.get(tenant, 0),
                tokens_inflight=st.tokens_inflight_per_tenant.get(
                    tenant, 0))
        r = _SimRequest()
        r.rid = self._next_rid
        self._next_rid += 1
        r.prompt_ref = prompt
        r.plen = plen
        r.context = plen
        r.budget = budget
        r.max_new_tokens = budget        # DeficitFairQueue's cost field
        r.tenant = tenant
        r.adapter_id = adapter_id
        r.prefix_id = prefix_id
        r.prefix_len = prefix_len
        r.on_token = on_token
        r.arrival_vt = arrival
        r.span_base = 0
        r.emitted = 0
        r.windows_left = 0
        now = self.clock.now if self.clock is not None else self.vt
        r.enqueue_vt = now
        r.deadline_vt = None if deadline_s is None else now + deadline_s
        if trace_id is not None:
            self._trace_seen += 1
            if self.trace_sample <= 1 \
                    or self._trace_seen % self.trace_sample == 1:
                r.trace_id = trace_id
        self._queue.append(r)
        st.queued += 1
        st.inflight += 1
        t = st.inflight_per_tenant
        t[tenant] = t.get(tenant, 0) + 1
        t = st.tokens_inflight_per_tenant
        t[tenant] = t.get(tenant, 0) + budget
        if r.trace_id:
            reqtrace.submitted(r.trace_id, ts_us=now * 1e6, rid=r.rid,
                               tenant=tenant, plen=plen,
                               max_new_tokens=budget)
        return r

    def import_request(self, snap: RequestSnapshot,
                       on_token: Optional[Callable] = None
                       ) -> _SimRequest:
        """Re-admit a migrated request: its full context (prompt +
        generated-so-far) is re-prefilled, then decode resumes at the
        remaining budget — the serve-tier import semantics."""
        resumed = int(snap.stream_offset)
        r = self.submit(snap.prompt, snap.max_new_tokens,
                        on_token=on_token, tenant=snap.tenant,
                        adapter_id=snap.adapter_id,
                        deadline_s=snap.deadline_remaining_s)
        r.emitted = resumed
        r.span_base = resumed
        r.context = r.plen + resumed
        if resumed > 0:
            # the caller saw the stream start on the source replica
            r.first_vt = r.arrival_vt
        carry = getattr(snap, "critpath", None)
        if carry:
            # resume the source replica's phase accrual; the export ->
            # import gap is charged to the migration phase on virtual
            # time, exactly like the serve scheduler's carry
            src = carry.get("phases") or {}
            r.cp_queue = float(src.get("queue_wait", 0.0))
            r.cp_prefill = float(src.get("prefill_compute", 0.0))
            r.cp_interf = float(src.get("prefill_interference", 0.0))
            r.cp_decode = float(src.get("decode_compute", 0.0))
            r.cp_migr = float(src.get("migration", 0.0))
            r.cp_migr += max(0.0, r.enqueue_vt
                             - float(carry.get("exported_at",
                                               r.enqueue_vt)))
        if snap.trace_id is not None:
            # the source's sampling verdict rides the snapshot — the
            # lane continues here, not a fresh submitted()
            r.trace_id = snap.trace_id
            now = self.clock.now if self.clock is not None else self.vt
            reqtrace.imported(r.trace_id, ts_us=now * 1e6, rid=r.rid,
                              resumed=resumed)
        return r

    def export_request(self, handle: _SimRequest,
                       timeout_s: Optional[float] = None
                       ) -> RequestSnapshot:
        r = handle
        if r.status != "pending":
            raise RuntimeError(f"request {r.rid} is terminal "
                               f"({r.status}); nothing to export")
        now = self.clock.now if self.clock is not None else self.vt
        if r in self._queue:
            # close the open queue wait at export so the carried
            # breakdown stays monotone across hops
            r.cp_queue += max(0.0, now - r.enqueue_vt)
        self._forget(r)
        r.status = "exported"
        if r.trace_id:
            reqtrace.exported(r.trace_id, ts_us=now * 1e6, rid=r.rid,
                              generated=r.emitted,
                              clean=self._wedged_until is None)
        snap = RequestSnapshot(
            rid=r.rid, prompt=r.prompt_ref,
            generated=[0] * r.emitted, max_new_tokens=r.budget,
            stream_offset=r.emitted, tenant=r.tenant,
            adapter_id=r.adapter_id, deadline_remaining_s=None,
            sampling=None, clean=self._wedged_until is None,
            trace_id=r.trace_id,
            critpath={"phases": r.critpath,
                      "elapsed_s": max(0.0, now - r.arrival_vt),
                      "exported_at": now})
        # page-wire manifest mirror (serve: the chains the export
        # handed off; sim: the cached prefix id + its covered tokens).
        # Only when this engine actually holds the prefix — a request
        # exported before admission shipped nothing.
        if r.prefix_id and r.prefix_id in self._prefix_seen:
            covered = r.prefix_len - r.prefix_len % self.prefill_chunk
            if covered > 0:
                snap.shipped_pages = ((r.prefix_id, covered),)
                snap.page_size = self.prefill_chunk
        return snap

    def export_inflight(self, timeout_s: Optional[float] = None
                        ) -> List[RequestSnapshot]:
        pending = (list(self._queue) + list(self._prefilling)
                   + list(self._active))
        return [self.export_request(r, timeout_s=timeout_s)
                for r in pending]

    def export_wire_pages(self, snap: RequestSnapshot,
                          timeout_s: Optional[float] = None) -> list:
        """Page-wire capture mirror (serve: host copies of device
        pages; sim: payload-free records — a shipped "page" is a
        fingerprint entry, keyed by prefix id instead of chain hash)."""
        manifest = getattr(snap, "shipped_pages", None)
        if not manifest:
            return []
        return [(j, key, {}) for j, (key, _tok) in enumerate(manifest)]

    def import_wire_pages(self, snap: RequestSnapshot, records,
                          timeout_s: Optional[float] = None) -> int:
        """Page-wire splice mirror: adopting a shipped record marks its
        prefix id cached here, so the subsequent ``import_request``'s
        admission radix-hits and its re-prefill pays only the
        uncovered windows — the sim twin of the serve pool's
        pre-warm.  Returns prefill chunks adopted."""
        chunk = self.prefill_chunk
        if int(getattr(snap, "page_size", 0) or 0) != chunk \
                or not records:
            return 0                 # chunking differs: keys are alien
        st = self._stats
        adopted = 0
        for rec in records:
            covered = int(rec.tokens)
            if covered < chunk or not rec.chain:
                continue
            self._prefix_seen.add(rec.chain)
            if covered > st.prefix_fingerprint.get(rec.chain, 0):
                st.prefix_fingerprint[rec.chain] = covered
            adopted += covered // chunk
        return adopted

    def cancel(self, handle: _SimRequest) -> bool:
        if handle.status != "pending":
            return False
        self._forget(handle)
        handle.status = "cancelled"
        if handle.trace_id:
            now = self.clock.now if self.clock is not None else self.vt
            reqtrace.retired(handle.trace_id, "cancelled",
                             ts_us=now * 1e6, tokens=handle.emitted)
        if self.metrics is not None:
            self.metrics.cancelled += 1
        return True

    def _forget(self, r: _SimRequest) -> None:
        """Remove a pending request from whichever stage holds it and
        settle the counters (export/cancel path)."""
        st = self._stats
        if r in self._active:
            self._active.remove(r)
            st.active -= 1
        elif r in self._prefilling:
            self._prefilling.remove(r)
            st.prefilling -= 1
        else:
            self._queue.remove(r)
            st.queued -= 1
        st.inflight -= 1
        t = st.inflight_per_tenant
        t[r.tenant] = t.get(r.tenant, 1) - 1
        t = st.tokens_inflight_per_tenant
        t[r.tenant] = t.get(r.tenant, r.budget) - r.budget

    def load_adapter(self, adapter_id: str, adapter: Any = None) -> None:
        self._adapters.add(adapter_id)

    def inflight_trace_ids(self) -> List[str]:
        """Trace ids of every in-flight (sampled) request — the same
        pre-quarantine forensics surface the real engine exposes."""
        pending = (list(self._queue) + list(self._prefilling)
                   + list(self._active))
        return [r.trace_id for r in pending if r.trace_id]

    def stats(self) -> _SimStats:
        return self._stats

    # ------------------------------------------------------- pump

    @property
    def busy(self) -> bool:
        return bool(self._queue or self._prefilling or self._active
                    or self._wedged_until is not None)

    def wedge(self, until_vt: float) -> None:
        """Model a stuck pump: a tick starts and never completes until
        ``until_vt`` — the stuck-but-alive heartbeat shape the real
        ``Watchdog`` quarantines (virtual ``now`` in, same verdict
        logic)."""
        st = self._stats
        st.ticks_started += 1
        st.last_tick_start_s = max(
            self.vt, self.clock.now if self.clock is not None else self.vt)
        self._wedged_until = float(until_vt)

    def step(self) -> bool:
        clock = self.clock
        if self._wedged_until is not None:
            now = clock.now if clock is not None else self._wedged_until
            if now + _EPS < self._wedged_until:
                return False
            st = self._stats
            end = self._wedged_until
            self._wedged_until = None
            st.ticks_completed += 1
            st.last_tick_end_s = end
            st.last_tick_duration_s = end - st.last_tick_start_s
            self.vt = max(self.vt, end)
        if clock is None:
            if not (self._queue or self._prefilling or self._active):
                return False
            self._tick_once()
            return True
        did = False
        horizon = clock.now + self.lookahead_s + _EPS
        # catch up: the router only intervenes between driver rounds
        # (submits, faults, scaling), so consecutive ticks commute
        while (self._queue or self._prefilling or self._active) \
                and self.vt <= horizon:
            self._tick_once()
            did = True
        return did

    def _tick_once(self) -> None:
        st = self._stats
        cm = self.cost
        clock = self.clock
        t0 = self.vt
        if clock is not None and clock.now > t0:
            t0 = clock.now
        dur = cm.overhead_s
        active = self._active
        prefilling = self._prefilling
        queue = self._queue
        chunk = self.prefill_chunk
        if active:
            dur += cm.decode_tick_s
        # admit from the (fair-share) queue into free slots
        free = self.num_slots - len(active) - len(prefilling)
        while free > 0 and len(queue):
            r = queue.popleft()
            free -= 1
            r.cp_queue += max(0.0, t0 - r.enqueue_vt)
            reused = 0
            if r.prefix_id:
                st.prefix_lookups_total += 1
                if r.prefix_id in self._prefix_seen:
                    st.prefix_hits_total += 1
                    reused = min(r.prefix_len - (r.prefix_len % chunk),
                                 r.context - 1)
                    st.prefix_tokens_reused_total += reused
                else:
                    self._prefix_seen.add(r.prefix_id)
                    # fingerprint mirror (serve/pages.py): the sim's
                    # cache never evicts (_prefix_seen's documented
                    # simplification), so the fingerprint only grows
                    st.prefix_fingerprint[r.prefix_id] = (
                        r.prefix_len - r.prefix_len % chunk)
            need = r.context - reused
            r.windows_left = (need + chunk - 1) // chunk if need > 0 else 1
            prefilling.append(r)
            st.queued -= 1
            st.prefilling += 1
            if r.trace_id:
                reqtrace.stage(r.trace_id, "prefill", ts_us=t0 * 1e6,
                               windows=r.windows_left)
        prefill_wall = len(prefilling) * cm.prefill_window_s
        dur += prefill_wall
        t1 = t0 + dur
        self.vt = t1
        metrics = self.metrics
        # decode: every slot active at tick start emits up to
        # tick_steps tokens at tick end
        if active:
            tick_steps = self.tick_steps
            zeros = self._zeros
            decode_s = cm.decode_tick_s
            still: List[_SimRequest] = []
            for r in active:
                # head-of-line attribution, same charging rule as the
                # serve scheduler: a slot already decoding at tick
                # start experiences the whole prefill wall as stretch;
                # requests admitted THIS tick sit in `prefilling`, so
                # they are structurally exempt
                r.cp_decode += decode_s
                r.cp_interf += prefill_wall
                k = r.budget - r.emitted
                if k > tick_steps:
                    k = tick_steps
                r.emitted += k
                cb = r.on_token
                if cb is not None:
                    cb(zeros[k])
                if r.emitted >= r.budget:
                    self._retire(r, t1, "ok")
                elif r.deadline_vt is not None and t1 > r.deadline_vt:
                    self._retire(r, t1, "deadline_exceeded")
                else:
                    still.append(r)
            self._active = active = still
            st.active = len(still)
        # prefill: one window each; the last window is fused with the
        # first emitted token (the serve scheduler's admit executable)
        if prefilling:
            still_p: List[_SimRequest] = []
            for r in prefilling:
                r.cp_prefill += cm.prefill_window_s
                r.windows_left -= 1
                if r.windows_left > 0:
                    still_p.append(r)
                    continue
                r.emitted += 1
                r.span_start_vt = t1
                if r.trace_id:
                    reqtrace.mark(r.trace_id, "first_token",
                                  ts_us=t1 * 1e6,
                                  ttft_s=t1 - r.arrival_vt)
                    reqtrace.stage(r.trace_id, "decode", ts_us=t1 * 1e6)
                if r.first_vt is None:
                    r.first_vt = t1
                    if metrics is not None:
                        metrics.record_ttft(t1 - r.arrival_vt, r.tenant)
                cb = r.on_token
                if cb is not None:
                    cb(self._zeros[1])
                if r.emitted >= r.budget:
                    self._retire(r, t1, "ok", in_prefill=True)
                elif r.deadline_vt is not None and t1 > r.deadline_vt:
                    self._retire(r, t1, "deadline_exceeded",
                                 in_prefill=True)
                else:
                    active.append(r)
                    st.active += 1
                    st.prefilling -= 1
            self._prefilling = still_p
        st.ticks_started += 1
        st.ticks_completed += 1
        st.last_tick_start_s = t0
        st.last_tick_end_s = t1
        st.last_tick_duration_s = dur

    def _retire(self, r: _SimRequest, now_vt: float, status: str,
                in_prefill: bool = False) -> None:
        st = self._stats
        if in_prefill:
            st.prefilling -= 1
        # active-list membership is settled by the caller's rebuild
        st.inflight -= 1
        t = st.inflight_per_tenant
        t[r.tenant] = t.get(r.tenant, 1) - 1
        t = st.tokens_inflight_per_tenant
        t[r.tenant] = t.get(r.tenant, r.budget) - r.budget
        r.status = status
        if r.trace_id:
            reqtrace.retired(r.trace_id, status, ts_us=now_vt * 1e6,
                             tokens=r.emitted)
        release = getattr(self._queue, "release", None)
        if release is not None:
            release(r)
        if self.metrics is not None:
            self.metrics.record_retire(r, now_vt, status)

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Standalone pump-to-empty (protocol surface; the fleet driver
        drains through the router instead)."""
        steps = 0
        limit = None if timeout_s is None else max(
            1, int(timeout_s * 1e6))
        while self._queue or self._prefilling or self._active:
            self._tick_once()
            steps += 1
            if limit is not None and steps >= limit:
                return False
        return True


# --------------------------------------------------------- SLO metrics


class SimMetrics:
    """Streaming SLO collector shared by every SimEngine of a run.

    TTFT is recorded once per request at its first emitted token
    (measured from TRUE arrival, surviving migration via the prompt
    tuple); the inter-token metric is the per-request mean gap (TPOT)
    over its final decode span, recorded at retirement.  Attainment
    counters update incrementally so the autoscaler's sliding window
    needs no array scans."""

    def __init__(self, slo: Optional[SLO] = None):
        self.slo = slo
        self.ttft = array("d")
        self.tpot = array("d")
        # per-request interference share (cp_interf / e2e) at
        # retirement — the fleet-wide head-of-line distribution the
        # critpath bench leg reports (docs/OBSERVABILITY.md)
        self.interference = array("d")
        self.completed = 0
        self.deadline_exceeded = 0
        self.cancelled = 0
        self.tokens_out = 0
        self.ttft_ok = 0
        self.itl_ok = 0
        self.itl_n = 0
        self.per_tenant: Dict[str, int] = {}
        self.autoscaler: Optional[Autoscaler] = None
        # optional obs.federate.FederatedMetrics: per-tenant latency
        # samples and SLO verdicts stream into its dttpu_slo_* gauges
        self.federation: Optional[Any] = None

    @property
    def finished(self) -> int:
        return self.completed + self.deadline_exceeded

    def record_ttft(self, v: float, tenant: str = "default") -> None:
        self.ttft.append(v)
        ok = self.slo is None or v <= self.slo.ttft_s
        if ok:
            self.ttft_ok += 1
        a = self.autoscaler
        if a is not None:
            a.record(ttft_ok=ok)
        f = self.federation
        if f is not None:
            f.ingest(tenant, ttft_s=v, ttft_ok=ok)

    def record_retire(self, r: _SimRequest, now_vt: float,
                      status: str) -> None:
        # interference is recorded for EVERY retirement (deadline
        # blow-ups are exactly the requests most likely to have been
        # stretched behind other tenants' prefills)
        e2e = now_vt - r.arrival_vt
        if e2e > 0:
            self.interference.append(r.cp_interf / e2e)
        if status != "ok":
            self.deadline_exceeded += 1
            return
        self.completed += 1
        span = r.emitted - r.span_base
        self.tokens_out += span
        t = self.per_tenant
        t[r.tenant] = t.get(r.tenant, 0) + 1
        if span > 1 and r.span_start_vt is not None:
            tpot = (now_vt - r.span_start_vt) / (span - 1)
        else:
            tpot = 0.0
        self.tpot.append(tpot)
        self.itl_n += 1
        ok = True
        if self.slo is not None:
            ok = tpot <= self.slo.itl_s
        if ok:
            self.itl_ok += 1
        a = self.autoscaler
        if a is not None:
            a.record(itl_ok=ok)
        f = self.federation
        if f is not None:
            f.ingest(r.tenant, tpot_s=tpot, itl_ok=ok)

    # ------------------------------------------------------- report

    def _pct(self, arr: array, q: float) -> float:
        if not len(arr):
            return 0.0
        return float(np.percentile(np.frombuffer(arr, dtype=np.float64),
                                   q))

    def report(self) -> Dict[str, Any]:
        n_ttft = len(self.ttft)
        att_ttft = self.ttft_ok / n_ttft if n_ttft else 1.0
        att_itl = self.itl_ok / self.itl_n if self.itl_n else 1.0
        return {
            "completed": self.completed,
            "deadline_exceeded": self.deadline_exceeded,
            "cancelled": self.cancelled,
            "tokens_generated": self.tokens_out,
            "ttft_p50_ms": round(self._pct(self.ttft, 50) * 1e3, 4),
            "ttft_p95_ms": round(self._pct(self.ttft, 95) * 1e3, 4),
            "ttft_p99_ms": round(self._pct(self.ttft, 99) * 1e3, 4),
            "itl_p99_ms": round(self._pct(self.tpot, 99) * 1e3, 4),
            "attainment_ttft": round(att_ttft, 6),
            "attainment_itl": round(att_itl, 6),
            "slo_attainment": round(min(att_ttft, att_itl), 6),
            "interference_share_p50": round(
                self._pct(self.interference, 50), 6),
            "interference_share_p95": round(
                self._pct(self.interference, 95), 6),
        }


# ------------------------------------------------------------ the driver


class FleetSim:
    """Discrete-event driver: a seeded :class:`workload.Trace` through
    the real :class:`fleet.Router` on virtual time (module docstring).

    ``autoscaler=`` takes a kwargs dict for :class:`Autoscaler` (built
    against this run's router/factory/SLO); ``watchdog=`` a kwargs dict
    for the real :class:`fleet.Watchdog` (checked on virtual time).
    ``inflight_cap`` bounds the router-side backlog: arrivals past the
    cap wait in the driver with their TRUE arrival time intact, so the
    queueing delay still lands in TTFT while ``Router._sweep`` stays
    affordable at millions of requests.

    The driver advances in ``quantum_s`` virtual-second rounds — the
    router pumps once per round while each engine ticks internally to
    exact sub-quantum times, so retire/TTFT timestamps are tick-exact
    and only ADMISSION is quantised: a request can sit in the driver up
    to one quantum past its true arrival, adding at most ``quantum_s``
    of apparent queueing to its TTFT.  Shrink ``quantum_s`` when that
    bias matters more than wall-clock speed."""

    def __init__(self, trace: Trace, cost_model: CostModel, *,
                 replicas: int = 2, slo: Optional[SLO] = None,
                 engine: Optional[Dict[str, Any]] = None,
                 policy: Optional[TenantPolicy] = None,
                 autoscaler: Optional[Dict[str, Any]] = None,
                 watchdog: Optional[Dict[str, Any]] = None,
                 registry: Optional[metrics_lib.Registry] = None,
                 quantum_s: float = 0.05,
                 inflight_cap_per_replica: Optional[int] = None,
                 seed: int = 0,
                 affinity_weight: float = 1.0):
        self.trace = trace
        self.cost_model = cost_model
        self.slo = slo or SLO()
        self.engine_kwargs = dict(engine or {})
        self.policy = policy
        self.registry = (registry if registry is not None
                         else metrics_lib.Registry())
        self.quantum_s = float(quantum_s)
        self.clock = SimClock(0.0)
        self.metrics = SimMetrics(self.slo)
        self.router = Router(registry=self.registry,
                             affinity_weight=affinity_weight)
        self.event_log: List[tuple] = []
        self._rng = np.random.default_rng(seed)
        self._engines: List[SimEngine] = []
        slots = int(self.engine_kwargs.get("num_slots", 8))
        cap = (inflight_cap_per_replica if inflight_cap_per_replica
               is not None else 8 * slots)
        self.inflight_cap_per_replica = int(cap)
        for _ in range(int(replicas)):
            self.router.add_replica(self.make_engine())
        self.autoscaler: Optional[Autoscaler] = None
        if autoscaler is not None:
            self.autoscaler = Autoscaler(
                self.router, self.make_engine, self.slo,
                registry=self.registry, **autoscaler)
            self.metrics.autoscaler = self.autoscaler
        self.watchdog = None
        self._wd_interval = math.inf
        if watchdog is not None:
            kw = dict(watchdog)
            self._wd_interval = kw.pop(
                "check_interval_s", kw.get("tick_deadline_s", 5.0) / 2)
            self.watchdog = watchdog_lib.Watchdog(
                self.router, registry=self.registry, **kw)
        self.replica_seconds = 0.0

    def make_engine(self) -> SimEngine:
        eng = SimEngine(self.cost_model, policy=self.policy,
                        clock=self.clock, metrics=self.metrics,
                        **self.engine_kwargs)
        eng.vt = self.clock.now
        eng.lookahead_s = self.quantum_s
        self._engines.append(eng)
        return eng

    # ------------------------------------------------------------- run

    def run(self, max_rounds: Optional[int] = None) -> Dict[str, Any]:
        trace = self.trace
        clock = self.clock
        router = self.router
        metrics = self.metrics
        auto = self.autoscaler
        n = len(trace)
        arrivals = trace.arrival_s.tolist()
        plens = trace.plen.tolist()
        budgets = trace.new_tokens.tolist()
        prefix_ids = trace.prefix_id.tolist()
        prefix_lens = trace.prefix_len.tolist()
        names = [name for name, _ in trace.tenants]
        tenant_of = [names[t] for t in trace.tenant.tolist()]
        ad_label = {-1: None}
        adapter_of = [ad_label.setdefault(a, f"ad{a}")
                      for a in trace.adapter.tolist()]
        events = list(trace.events)
        submit = router.submit
        plan = faults_lib.FaultPlan([], seed=trace.seed,
                                    registry=self.registry)
        next_eval = (auto.eval_interval_s if auto is not None
                     else math.inf)
        next_wd = self._wd_interval
        quantum = self.quantum_s
        kills = quarantines = 0
        i = 0
        rounds = 0
        lost = 0
        log = self.event_log
        cap_per = self.inflight_cap_per_replica
        with faults_lib.activated(plan):
            while True:
                inflight = i - metrics.finished - metrics.cancelled \
                    - lost
                if i >= n and inflight <= 0:
                    break
                rounds += 1
                if max_rounds is not None and rounds > max_rounds:
                    log.append(("aborted", round(clock.now, 9), rounds))
                    break
                rids = router.replica_ids
                if not rids and auto is None:
                    # dead fleet with nothing to heal it: everything
                    # still outstanding is lost
                    lost += (n - i) + inflight
                    log.append(("dead_fleet", round(clock.now, 9),
                                n - i, inflight))
                    break
                cap_total = cap_per * max(1, len(rids))
                # --- next interesting virtual instant: one quantum
                # ahead, clipped by due events and the policy cadences.
                # Engines tick internally to exact sub-quantum times,
                # so arrivals/wedge releases only need quantum-level
                # granularity (admission quantisation, class docstring).
                t_next = clock.now + quantum
                if events and events[0].at_s < t_next:
                    t_next = events[0].at_s
                if next_eval < t_next:
                    t_next = next_eval
                if next_wd < t_next:
                    t_next = next_wd
                if t_next > clock.now:
                    live = len(rids)
                    dt = t_next - clock.now
                    self.replica_seconds += dt * live
                    if auto is not None:
                        auto.charge(dt, live)
                    clock.now = t_next
                now = clock.now
                # --- flush due arrivals (true arrival time rides the
                # prompt tuple), up to the backlog cap
                while i < n and rids and arrivals[i] <= now \
                        and inflight < cap_total:
                    try:
                        submit((plens[i], prefix_ids[i], prefix_lens[i],
                                arrivals[i]),
                               budgets[i], tenant=tenant_of[i],
                               adapter_id=adapter_of[i])
                    except NoReplicaError:
                        if auto is not None:
                            # defer: the autoscaler heals the fleet at
                            # its next evaluation; arrival time rides
                            # the prompt tuple so TTFT stays honest.
                            break
                        lost += 1
                        log.append(("rejected", round(now, 9), i))
                        i += 1
                        continue
                    i += 1
                    inflight += 1
                # --- due fleet events -> the shared fault vocabulary
                while events and events[0].at_s <= now:
                    ev = events.pop(0)
                    if ev.kind == "correlated_kill":
                        plan.add(faults_lib.Fault(
                            kind="correlated_kill",
                            at=plan.global_pump_index, k=ev.k,
                            window=ev.window))
                        kills += 1
                        log.append(("correlated_kill",
                                    round(now, 9), ev.k, ev.window))
                    elif ev.kind == "wedge_replica":
                        live_rids = router.replica_ids
                        if live_rids:
                            victim = int(self._rng.choice(
                                sorted(live_rids)))
                            router.replica(victim).wedge(
                                now + ev.seconds)
                            log.append(("wedge", round(now, 9), victim))
                # --- autoscaler / watchdog on virtual time
                if auto is not None and now + _EPS >= next_eval:
                    action = auto.evaluate(now)
                    next_eval = now + auto.eval_interval_s
                    if action is not None:
                        log.append((action[0], round(now, 9),
                                    action[1]))
                if self.watchdog is not None and now + _EPS >= next_wd:
                    pulled = self.watchdog.check(now=now)
                    next_wd = now + self._wd_interval
                    for rid, reason in (pulled or ()):
                        quarantines += 1
                        log.append(("quarantine", round(now, 9),
                                    rid, reason))
                # --- the one real pump
                router.step()
                inflight = i - metrics.finished - metrics.cancelled \
                    - lost
                if i >= n and inflight > 0 and not router.busy:
                    # requests that went fleet-terminal without retiring
                    # on an engine (failed past the retry budget)
                    lost += inflight
                    log.append(("lost", round(now, 9), inflight))
        rep = metrics.report()
        rep.update({
            "simulated_requests": i,
            "virtual_time_s": round(clock.now, 6),
            "driver_rounds": rounds,
            "replicas_final": len(router.replica_ids),
            "replica_seconds": round(self.replica_seconds, 6),
            "migrations": int(self.registry.get(
                "dttpu_migrations_total").value),
            "correlated_kills_armed": kills,
            "quarantines": quarantines,
            "lost": lost,
            "events": len(log),
        })
        # fleet-wide radix reuse, summed over EVERY engine this run
        # created (including replicas the autoscaler later retired) —
        # the number the affinity ablation gates on
        lookups = sum(e._stats.prefix_lookups_total
                      for e in self._engines)
        hits = sum(e._stats.prefix_hits_total for e in self._engines)
        rep["fleet_prefix_lookups"] = lookups
        rep["fleet_prefix_hit_rate"] = round(
            hits / lookups if lookups else 0.0, 6)
        if self.replica_seconds > 0:
            rep["attainment_per_kilo_replica_second"] = round(
                rep["slo_attainment"]
                / (self.replica_seconds / 1e3), 6)
        if auto is not None:
            rep["scale_outs"] = auto.scale_outs
            rep["scale_ins"] = auto.scale_ins
        return rep
