"""Supervised multi-host launcher: spawn, watch, restart, re-elect.

The reference stack got its process tree for free — a cluster manager
started one ``tf.train.Server`` per host and ``MonitoredTrainingSession``
survived worker churn (PAPER.md §0).  This repo's ``parallel/cluster.py``
expects the same shape (env-var topology: ``COORDINATOR_ADDRESS`` /
``NUM_PROCESSES`` / ``PROCESS_ID``) but until now the processes were
forked by hand in tests and benches.  ``Launcher`` is the missing
supervisor: it spawns one child per ``HostSpec``, polls liveness, and
applies ``resilience.Supervisor``'s restart discipline — transient vs
fatal classification, bounded restarts with seeded exponential backoff,
an audit trail — to PROCESSES instead of in-process sessions.

Classification of an exit code:

* ``None`` — running;
* ``0`` — clean completion (terminal, success);
* ``cluster.LEGACY_PS_EXIT_CODE`` — **fatal with reason**: a legacy
  ``JOB_NAME=ps`` role refused to start (parallel/cluster.py).  The old
  behavior — warn, exit 0 — read as success and silently ran the fleet
  one host short; now the report names the misconfiguration;
* ``< 0`` (killed by signal) or listed in ``transient_exit_codes`` —
  transient: restart with backoff until ``max_restarts`` is spent,
  then fatal ("restart budget exhausted");
* anything else — fatal (a crash backoff-restarts cannot fix).

Liveness beyond exit codes: each child gets ``DTTPU_HEARTBEAT_FILE``
and is expected to touch it (call ``launcher.heartbeat()`` in its
loop); a file stale past ``heartbeat_timeout_s`` means the process is
alive-but-stuck — the launcher kills it and the kill classifies as a
transient signal exit (restart).  ``heartbeat_timeout_s=None`` (the
default) trusts exit codes alone.

Chief re-election: the chief is the lowest-id LIVE host (the
coordinator-address convention of ``parallel/cluster.py``).  When the
chief dies the title moves to the next live host and the election is
counted + logged — host 0's death must not orphan checkpoint/summary
duties forever.

Chaos: an armed ``kill_host`` fault (resilience/faults.py) matching a
host's poll SIGKILLs the child — the restart path is driven by the
same fault plan the page-wire tests use, so "the host died mid-
transfer" is one scenario, not two harnesses.

Threadless by design: all state changes happen inside ``poll()`` on
the caller's thread (``wait()`` is just a poll-sleep loop), so there is
no lock to leak and no watcher thread to join.

Series: ``dttpu_launcher_*`` (docs/OBSERVABILITY.md §Launcher).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import metrics as metrics_lib
from ..parallel import cluster as cluster_lib
from ..resilience import faults as faults_lib

log = logging.getLogger(__name__)

__all__ = ["HostSpec", "Launcher", "heartbeat", "local_topology"]

# exit-code classifications (Launcher._classify)
_RUNNING, _DONE, _TRANSIENT, _FATAL = "running", "done", "transient", \
    "fatal"


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One supervised host process: its integer id (== the topology's
    ``PROCESS_ID``), the argv to exec, and the env vars to merge over
    the parent's (the topology: coordinator address, process count,
    plus ``DTTPU_LAUNCHER=1`` so children know a supervisor is
    classifying their exits)."""
    host_id: int
    argv: Sequence[str]
    env: Dict[str, str] = dataclasses.field(default_factory=dict)


def heartbeat(environ=None) -> None:
    """Child-side liveness tick: touch ``DTTPU_HEARTBEAT_FILE`` (no-op
    when unset — the same child runs unsupervised).  Call it from the
    host process's main loop; the launcher reads the mtime."""
    env = os.environ if environ is None else environ
    path = env.get("DTTPU_HEARTBEAT_FILE")
    if not path:
        return
    with open(path, "a"):
        os.utime(path, None)


def local_topology(num_hosts: int, argv: Sequence[str], port: int,
                   extra_env: Optional[Dict[str, str]] = None,
                   heartbeat_dir: Optional[str] = None
                   ) -> List[HostSpec]:
    """``HostSpec``s for an N-process single-machine bring-up: the
    env-var topology ``parallel/cluster.py`` resolves (host 0 is the
    coordinator — the chief convention), one heartbeat file per host
    under ``heartbeat_dir`` when liveness polling is wanted."""
    specs = []
    for hid in range(num_hosts):
        env = {
            "COORDINATOR_ADDRESS": f"localhost:{int(port)}",
            "NUM_PROCESSES": str(int(num_hosts)),
            "PROCESS_ID": str(hid),
            "DTTPU_LAUNCHER": "1",
        }
        if heartbeat_dir is not None:
            env["DTTPU_HEARTBEAT_FILE"] = os.path.join(
                heartbeat_dir, f"host{hid}.hb")
        if extra_env:
            env.update(extra_env)
        specs.append(HostSpec(host_id=hid, argv=tuple(argv), env=env))
    return specs


class _Host:
    """Mutable supervision state for one HostSpec (launcher-internal)."""

    __slots__ = ("spec", "proc", "status", "reason", "restarts",
                 "due_at", "exit_history", "last_hb")

    def __init__(self, spec: HostSpec):
        self.spec = spec
        self.proc: Any = None
        self.status = _RUNNING          # running|backoff|done|fatal
        self.reason: Optional[str] = None
        self.restarts = 0
        self.due_at: Optional[float] = None   # backoff: restart time
        self.exit_history: List[int] = []
        self.last_hb: Optional[float] = None


def _default_popen(spec: HostSpec):
    env = dict(os.environ)
    env.update(spec.env)
    return subprocess.Popen(list(spec.argv), env=env)


class Launcher:
    """Spawn/monitor/restart the fleet's host processes (module doc).

    ``popen`` is the injectable process backend — ``spec ->`` an object
    with ``poll() -> Optional[int]``, ``kill()``, ``wait(timeout=)`` —
    defaulting to ``subprocess.Popen`` with the spec's env merged over
    the parent's.  ``sleep``/``clock`` are injectable the same way
    (tests drive fake time; ``resilience.Supervisor`` idiom).

    Lifecycle: ``start()`` spawns everyone, ``poll()`` runs ONE
    supervision pass (liveness + classification + due restarts + chief
    election) and returns True while any host is running or pending
    restart, ``wait()`` loops poll/sleep until the fleet is terminal,
    ``report()`` returns the per-host verdicts, ``stop()`` kills
    whatever still runs (terminal state ``done``, reason "stopped")."""

    def __init__(self, hosts: Sequence[HostSpec], *,
                 max_restarts: int = 3,
                 backoff_base_s: float = 0.1,
                 backoff_factor: float = 2.0,
                 backoff_max_s: float = 5.0,
                 jitter: float = 0.5,
                 seed: int = 0,
                 transient_exit_codes: Sequence[int] = (),
                 heartbeat_timeout_s: Optional[float] = None,
                 heartbeat_grace_s: float = 5.0,
                 poll_interval_s: float = 0.05,
                 registry: Optional[metrics_lib.Registry] = None,
                 popen: Callable[[HostSpec], Any] = _default_popen,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if not hosts:
            raise ValueError("Launcher needs at least one HostSpec")
        ids = [int(s.host_id) for s in hosts]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids: {sorted(ids)}")
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.transient_exit_codes = frozenset(
            int(c) for c in transient_exit_codes)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_grace_s = float(heartbeat_grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self.popen = popen
        self.sleep = sleep
        self.clock = clock
        self._rng = np.random.default_rng(seed)
        self._hosts: Dict[int, _Host] = {
            int(s.host_id): _Host(s) for s in hosts}
        self.chief_id: Optional[int] = None
        self.elections: List[tuple] = []     # (old chief, new chief)
        self.restart_log: List[tuple] = []   # (host, attempt, reason)
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self._m_hosts = reg.gauge(
            "dttpu_launcher_hosts",
            "Host processes currently live under the launcher.")
        self._m_restarts = reg.counter(
            "dttpu_launcher_restarts_total",
            "Host processes restarted after a transient exit (signal "
            "kill, missed heartbeat, or a listed transient code).")
        self._m_hb_missed = reg.counter(
            "dttpu_launcher_heartbeat_missed_total",
            "Host processes killed for a heartbeat stale past the "
            "liveness timeout (alive-but-stuck).")
        self._m_elections = reg.counter(
            "dttpu_launcher_chief_elections_total",
            "Chief re-elections after the lowest-id live host "
            "changed.")
        self._m_fatal = reg.counter(
            "dttpu_launcher_fatal_total",
            "Host processes declared fatal (unrecoverable exit code, "
            "refused role, or restart budget exhausted).")

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        for h in self._hosts.values():
            self._spawn(h)
        self._elect()
        self._m_hosts.set(self._live_count())

    def _spawn(self, h: _Host) -> None:
        h.proc = self.popen(h.spec)
        h.status = _RUNNING
        h.due_at = None
        h.last_hb = self.clock()      # grace starts at spawn

    def _delay(self, attempt: int) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s
                   * self.backoff_factor ** (attempt - 1))
        return base * (1.0 + self.jitter * float(self._rng.random()))

    # ------------------------------------------------------ supervision

    def _classify(self, h: _Host, rc: Optional[int]) -> str:
        if rc is None:
            return _RUNNING
        if rc == 0:
            return _DONE
        if rc == cluster_lib.LEGACY_PS_EXIT_CODE:
            h.reason = ("legacy JOB_NAME=ps role refused to start "
                        "(no parameter-server role exists; "
                        "parallel/cluster.py) — fix the topology env")
            return _FATAL
        if rc < 0 or rc in self.transient_exit_codes:
            return _TRANSIENT
        h.reason = f"unrecoverable exit code {rc}"
        return _FATAL

    def _heartbeat_stale(self, h: _Host, now: float) -> bool:
        if self.heartbeat_timeout_s is None:
            return False
        path = h.spec.env.get("DTTPU_HEARTBEAT_FILE")
        if not path:
            return False
        # mtime lives on the wall clock (children touch the file with
        # utime); staleness is judged there.  Before the first touch
        # the spawn instant (launcher clock) anchors a grace window so
        # a slow-starting child is not killed for being slow.
        try:
            age = time.time() - os.path.getmtime(path)
        except OSError:
            started_ago = now - (h.last_hb if h.last_hb is not None
                                 else now)
            return started_ago > (self.heartbeat_timeout_s
                                  + self.heartbeat_grace_s)
        return age > self.heartbeat_timeout_s

    def poll(self) -> bool:
        """One supervision pass; True while any host is running or due
        a restart.  All classification and restart work happens here,
        on the caller's thread."""
        now = self.clock()
        plan = faults_lib.active()
        for hid, h in sorted(self._hosts.items()):
            if h.status == "backoff":
                if h.due_at is not None and now >= h.due_at:
                    self._spawn(h)
                continue
            if h.status in (_DONE, _FATAL) or h.proc is None:
                continue
            # chaos: an armed kill_host matching this host's poll
            # SIGKILLs the child; the kill is classified below like
            # any real signal death (restart path)
            if plan is not None and h.proc.poll() is None \
                    and plan.on_host_poll(hid) is not None:
                h.proc.kill()
                h.proc.wait(timeout=10)
            rc = h.proc.poll()
            verdict = self._classify(h, rc)
            if verdict == _RUNNING and self._heartbeat_stale(h, now):
                self._m_hb_missed.inc()
                log.warning("host %d heartbeat stale past %.1fs — "
                            "killing for restart", hid,
                            self.heartbeat_timeout_s)
                h.proc.kill()
                h.proc.wait(timeout=10)
                verdict = self._classify(h, h.proc.poll())
            if verdict == _RUNNING:
                continue
            h.exit_history.append(int(rc if rc is not None else -9))
            if verdict == _DONE:
                h.status = _DONE
                h.reason = "completed"
            elif verdict == _FATAL:
                h.status = _FATAL
                self._m_fatal.inc()
                log.error("host %d fatal: %s", hid, h.reason)
            else:                                   # transient
                if h.restarts >= self.max_restarts:
                    h.status = _FATAL
                    h.reason = (f"restart budget exhausted "
                                f"({self.max_restarts}) after exit "
                                f"{rc}")
                    self._m_fatal.inc()
                    log.error("host %d fatal: %s", hid, h.reason)
                else:
                    h.restarts += 1
                    h.status = "backoff"
                    delay = self._delay(h.restarts)
                    h.due_at = now + delay
                    h.reason = f"transient exit {rc}"
                    self.restart_log.append((hid, h.restarts,
                                             h.reason))
                    self._m_restarts.inc()
                    log.warning(
                        "host %d transient exit %s — restart %d/%d "
                        "in %.2fs", hid, rc, h.restarts,
                        self.max_restarts, delay)
        self._elect()
        self._m_hosts.set(self._live_count())
        return any(h.status in (_RUNNING, "backoff")
                   for h in self._hosts.values())

    def _live_count(self) -> int:
        return sum(1 for h in self._hosts.values()
                   if h.status == _RUNNING)

    def _elect(self) -> None:
        """Chief = lowest-id host still running or pending restart (a
        restarting chief keeps the title — topology env pins process
        ids, so the restarted process IS the same participant)."""
        live = [hid for hid, h in sorted(self._hosts.items())
                if h.status in (_RUNNING, "backoff")]
        new = live[0] if live else None
        if new != self.chief_id:
            # a fleet draining to zero live hosts is completion (or
            # total failure), not an election — only a live successor
            # counts as the title moving
            if self.chief_id is not None and new is not None:
                self.elections.append((self.chief_id, new))
                self._m_elections.inc()
                log.warning("chief re-election: host %s -> %s",
                            self.chief_id, new)
            self.chief_id = new

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Poll until every host is terminal (True) or the budget runs
        out (False — the fleet keeps whatever state it has; call
        ``stop()`` to tear down)."""
        deadline = (None if timeout_s is None
                    else self.clock() + timeout_s)
        while self.poll():
            if deadline is not None and self.clock() >= deadline:
                return False
            self.sleep(self.poll_interval_s)
        return True

    def stop(self) -> None:
        """Kill every still-running child (terminal ``done``, reason
        "stopped" — an operator teardown is not a failure)."""
        for h in self._hosts.values():
            if h.status in (_RUNNING, "backoff") and h.proc is not None:
                if h.proc.poll() is None:
                    h.proc.kill()
                    try:
                        h.proc.wait(timeout=10)
                    except Exception:
                        pass
            if h.status in (_RUNNING, "backoff"):
                h.status = _DONE
                h.reason = "stopped"
        self._elect()
        self._m_hosts.set(self._live_count())

    # --------------------------------------------------------- reporting

    def report(self) -> Dict[int, dict]:
        """Per-host verdicts: ``{host_id: {status, reason, restarts,
        exit_history}}`` plus chief/election history under the
        launcher-wide key ``-1`` — the surface the CI smoke job and the
        chaos tests assert on."""
        out: Dict[int, dict] = {
            hid: {"status": h.status, "reason": h.reason,
                  "restarts": h.restarts,
                  "exit_history": list(h.exit_history)}
            for hid, h in sorted(self._hosts.items())}
        out[-1] = {"chief": self.chief_id,
                   "elections": list(self.elections),
                   "restart_log": list(self.restart_log)}
        return out

    @property
    def succeeded(self) -> bool:
        """True when every host completed cleanly (status ``done``)."""
        return all(h.status == _DONE for h in self._hosts.values())
