"""fleet — multi-replica serving: router, tenancy, zero-downtime ops.

The tier above ``serve``: one ``Engine`` is one mesh, a fleet is N of
them behind one façade (docs/SERVING.md §Fleet):

* ``fleet.router`` — ``Router``: least-loaded placement fed by the
  ``Engine.stats()`` snapshot, in-deadline retry of rejected requests,
  LIVE MIGRATION of in-flight requests (``RequestSnapshot`` export ->
  import, progress intact, exactly-once streaming via the router's
  dedup shim) across failover/drain/removal/quarantine,
  ``drain_replica``/``remove_replica``/``add_replica``/
  ``resume_replica`` rolling restarts, ``dttpu_router_*`` +
  ``dttpu_migrations_total`` metrics.
* ``fleet.watchdog`` — ``Watchdog``: a tick-deadline health policy
  over the pump heartbeat in ``Engine.stats()``; wedged or stalled
  replicas are quarantined (``router.quarantined``) and their requests
  migrated — driven deterministically by the ``stall_tick``/
  ``wedge_replica`` fault kinds.
* ``fleet.tenancy`` — per-tenant admission policy: ``TenantQuota``
  ceilings (max in-flight, token budgets) rejected loudly at submit,
  and a deficit-weighted fair-share queue (`DeficitFairQueue`) that
  drops into the scheduler so one tenant's burst cannot starve others.

* ``fleet.sim`` — the million-request FLEET SIMULATOR: ``SimEngine``
  (a replica priced by the PR 10 graph-tier cost model instead of a
  mesh — same ``EngineProtocol`` surface) and ``FleetSim`` (seeded
  discrete-event driver on virtual time) run the REAL router /
  watchdog / tenancy / faults stack at millions of requests per
  wall-minute (docs/FLEET_SIM.md).
* ``fleet.workload`` — seeded synthetic traces: diurnal + burst
  arrivals, tenant mix, Zipf shared prefixes, adapter churn,
  correlated-failure schedules.
* ``fleet.autoscaler`` — ``Autoscaler``: the SLO-attainment scaling
  policy (scale-out on missed attainment/backlog, migrate-based
  scale-in, heal below the floor) that drives sim and real fleets
  identically; ``dttpu_autoscaler_*`` metrics.
* ``fleet.pagewire`` — ``PageWire``: fault-tolerant cross-host KV-page
  transport for migrations (CRC32C-checked chunks keyed by radix chain
  hashes, bounded retry + seeded backoff, idempotent re-send, graceful
  degradation to re-prefill); ``Router(page_wire=...)`` ships a
  victim's cached pages so the destination skips those prefill
  windows; ``dttpu_wire_*`` metrics.
* ``fleet.launcher`` — ``Launcher``: supervised multi-host process
  tree for ``parallel/cluster.py``'s env-var topology — spawn/monitor/
  restart with Supervisor-style transient/fatal classification, seeded
  backoff, heartbeat liveness, chief re-election on host loss;
  ``dttpu_launcher_*`` metrics.

LoRA adapter hot-swap rides the serve/model layers
(``serve.AdapterTable``, ``GPT.init_lora``); ``Router.load_adapter``
broadcasts an adapter to every replica.  Chaos coverage: the
``kill_replica`` fault (resilience.faults) drops a replica mid-traffic
and the router migrates — measured by ``bench.py --config=fleet``;
``correlated_kill`` drops K replicas inside one pump window —
measured by ``bench.py --config=fleet_sim``.
"""
from . import (autoscaler, launcher, pagewire, router, sim, tenancy,
               watchdog, workload)
from .autoscaler import SLO, Autoscaler
from .launcher import HostSpec, Launcher
from .pagewire import InProcessLink, PageWire, WireError
from .router import EngineProtocol, FleetHandle, NoReplicaError, Router
from .sim import CostModel, FleetSim, HardwarePoint, SimEngine
from .tenancy import (DeficitFairQueue, QuotaExceededError, TenantPolicy,
                      TenantQuota)
from .watchdog import Watchdog
from .workload import FleetEvent, Trace, synthesize

__all__ = ["Autoscaler", "CostModel", "DeficitFairQueue",
           "EngineProtocol", "FleetEvent", "FleetHandle", "FleetSim",
           "HardwarePoint", "HostSpec", "InProcessLink", "Launcher",
           "NoReplicaError", "PageWire", "QuotaExceededError",
           "Router", "SLO", "SimEngine", "TenantPolicy", "TenantQuota",
           "Trace", "Watchdog", "WireError", "autoscaler", "launcher",
           "pagewire", "router", "sim", "synthesize", "tenancy",
           "watchdog", "workload"]
