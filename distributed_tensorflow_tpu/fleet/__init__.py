"""fleet — multi-replica serving: router, tenancy, zero-downtime ops.

The tier above ``serve``: one ``Engine`` is one mesh, a fleet is N of
them behind one façade (docs/SERVING.md §Fleet):

* ``fleet.router`` — ``Router``: least-loaded placement fed by the
  ``Engine.stats()`` snapshot, in-deadline retry of rejected/failed
  requests on a surviving replica, ``drain_replica``/``remove_replica``
  /``add_replica`` rolling restarts, ``dttpu_router_*`` metrics.
* ``fleet.tenancy`` — per-tenant admission policy: ``TenantQuota``
  ceilings (max in-flight, token budgets) rejected loudly at submit,
  and a deficit-weighted fair-share queue (`DeficitFairQueue`) that
  drops into the scheduler so one tenant's burst cannot starve others.

LoRA adapter hot-swap rides the serve/model layers
(``serve.AdapterTable``, ``GPT.init_lora``); ``Router.load_adapter``
broadcasts an adapter to every replica.  Chaos coverage: the
``kill_replica`` fault (resilience.faults) drops a replica mid-traffic
and the router reroutes — measured by ``bench.py --config=fleet``.
"""
from . import router, tenancy
from .router import FleetHandle, NoReplicaError, Router
from .tenancy import (DeficitFairQueue, QuotaExceededError, TenantPolicy,
                      TenantQuota)

__all__ = ["DeficitFairQueue", "FleetHandle", "NoReplicaError",
           "QuotaExceededError", "Router", "TenantPolicy", "TenantQuota",
           "router", "tenancy"]
