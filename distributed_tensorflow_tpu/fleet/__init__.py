"""fleet — multi-replica serving: router, tenancy, zero-downtime ops.

The tier above ``serve``: one ``Engine`` is one mesh, a fleet is N of
them behind one façade (docs/SERVING.md §Fleet):

* ``fleet.router`` — ``Router``: least-loaded placement fed by the
  ``Engine.stats()`` snapshot, in-deadline retry of rejected requests,
  LIVE MIGRATION of in-flight requests (``RequestSnapshot`` export ->
  import, progress intact, exactly-once streaming via the router's
  dedup shim) across failover/drain/removal/quarantine,
  ``drain_replica``/``remove_replica``/``add_replica``/
  ``resume_replica`` rolling restarts, ``dttpu_router_*`` +
  ``dttpu_migrations_total`` metrics.
* ``fleet.watchdog`` — ``Watchdog``: a tick-deadline health policy
  over the pump heartbeat in ``Engine.stats()``; wedged or stalled
  replicas are quarantined (``router.quarantined``) and their requests
  migrated — driven deterministically by the ``stall_tick``/
  ``wedge_replica`` fault kinds.
* ``fleet.tenancy`` — per-tenant admission policy: ``TenantQuota``
  ceilings (max in-flight, token budgets) rejected loudly at submit,
  and a deficit-weighted fair-share queue (`DeficitFairQueue`) that
  drops into the scheduler so one tenant's burst cannot starve others.

LoRA adapter hot-swap rides the serve/model layers
(``serve.AdapterTable``, ``GPT.init_lora``); ``Router.load_adapter``
broadcasts an adapter to every replica.  Chaos coverage: the
``kill_replica`` fault (resilience.faults) drops a replica mid-traffic
and the router migrates — measured by ``bench.py --config=fleet``.
"""
from . import router, tenancy, watchdog
from .router import FleetHandle, NoReplicaError, Router
from .tenancy import (DeficitFairQueue, QuotaExceededError, TenantPolicy,
                      TenantQuota)
from .watchdog import Watchdog

__all__ = ["DeficitFairQueue", "FleetHandle", "NoReplicaError",
           "QuotaExceededError", "Router", "TenantPolicy", "TenantQuota",
           "Watchdog", "router", "tenancy", "watchdog"]
