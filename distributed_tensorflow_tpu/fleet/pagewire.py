"""Fault-tolerant cross-host KV-page wire: ship pages, not FLOPs.

Live migration (serve/scheduler.py ``export``/``import_snapshot``)
moves a request's HOST state and re-prefills its KV cache on the
destination — correct, but it burns prefill windows recomputing K/V
the source already holds.  ``PageWire`` is the transport that ships
those pages instead: the radix-cached device pages behind a
``RequestSnapshot``'s shipped-pages manifest (the chain hashes its
export handed off, serve/pages.py) travel device -> host -> wire ->
device in CRC-checked chunks, and the receiver splices them straight
into its ``PagePool`` through the same ``begin``/``handoff`` seam
every request uses — so the resumed request's prefill radix-matches
and skips the shipped windows.

Failure is the common case, so the transfer state machine is built
around it:

* every chunk frame carries a CRC32C (``summary.crc32c`` — the
  TFRecord checksum, reused) over its records AND each record's chain
  hash; a corrupt frame is NAKed by the receiver and re-sent;
* bounded retries with seeded exponential backoff + a per-chunk
  timeout: a dropped frame costs one timeout, a late (stalled) frame
  is re-sent and the receiver dedups by chain key — re-send is
  idempotent end to end because the splice itself is (a chain already
  in the destination's radix tree is matched, not rewritten);
* **graceful degradation**: any unrecoverable failure (link down —
  the host died mid-transfer) raises ``WireError`` and the caller
  (``fleet.Router._place``) falls back to today's re-prefill
  migration.  Correctness NEVER depends on the wire; it only saves
  destination prefill windows.

The chaos kinds ``drop_chunk``/``corrupt_chunk``/``stall_wire``/
``kill_host`` (resilience/faults.py) act inside ``InProcessLink`` —
the loopback link the in-process fleet uses — so every failure mode
above is directly injectable and tested (tests/test_pagewire.py).

Series: ``dttpu_wire_*`` (docs/OBSERVABILITY.md §Page wire).
"""
from __future__ import annotations

import dataclasses
import logging
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as metrics_lib
from ..resilience import faults as faults_lib
from ..summary.crc32c import crc32c

log = logging.getLogger(__name__)

__all__ = ["InProcessLink", "PageRecord", "PageWire", "WireError",
           "WireFrameError", "frame_chunk", "parse_frame"]

_MAGIC = b"DTPW"
_VERSION = 1
# chain key type tags: the serve tier keys by blake2b chain hash
# (bytes), the fleet sim by prefix id (int) — frames carry either
_KEY_BYTES = 0
_KEY_INT = 1


class WireError(RuntimeError):
    """Unrecoverable transfer failure (link down, retries exhausted).
    The caller degrades to re-prefill migration — never fatal to the
    request."""


class WireFrameError(WireError):
    """A frame that failed parse or CRC verification — the receiver's
    NAK.  Retryable: the sender re-frames and re-sends."""


@dataclasses.dataclass
class PageRecord:
    """One shipped page: the ``index``-th full chunk of the migrated
    request's context, its radix chain key, the tokens the chain
    covers through this chunk, and the host copy of the device page
    (one ``[L, page_size, ...]`` array per pool leaf; int8 pools ship
    their scale planes as ordinary leaves).  The fleet sim ships
    payload-free records — its "page" is a fingerprint entry."""
    index: int
    chain: Any                           # bytes (serve) | int (sim)
    tokens: int
    payload: Dict[str, np.ndarray]


def _wire_dtype(dt: np.dtype) -> bytes:
    """Dtype -> wire string.  Extension dtypes (bfloat16, float8_*)
    stringify to an opaque void under ``.str`` — the one thing that
    must NOT go on the wire, since ``np.dtype("<V2")`` parses back as
    raw void and the receiver's dtype check would refuse the splice.
    Their registered NAME round-trips instead (via ml_dtypes)."""
    return (dt.name if dt.kind == "V" else dt.str).encode()


def _resolve_dtype(s: str) -> np.dtype:
    """Wire string -> dtype; NAKs (``WireFrameError``) on a dtype this
    host cannot represent rather than splicing mistyped pages."""
    try:
        dt = np.dtype(s)
    except TypeError:
        dt = None
    if dt is not None and dt.kind != "V":
        return dt
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, s))
    except (ImportError, AttributeError, TypeError) as e:
        raise WireFrameError(f"unknown wire dtype {s!r}") from e


def _pack_key(chain: Any) -> Tuple[int, bytes]:
    if isinstance(chain, bytes):
        return _KEY_BYTES, chain
    return _KEY_INT, struct.pack(">q", int(chain))


def _unpack_key(tag: int, raw: bytes) -> Any:
    if tag == _KEY_BYTES:
        return raw
    return struct.unpack(">q", raw)[0]


def frame_chunk(seq: int, records: Sequence[PageRecord]) -> bytes:
    """Serialize one wire chunk: header, each record's (index, chain
    key, tokens, payload leaves with dtype/shape), CRC32C trailer over
    everything before it.  Real bytes on purpose: corruption has a
    byte to flip and the CRC has bytes to cover, and the framing cost
    is what the bench's chunk-size sweep measures."""
    parts = [_MAGIC, struct.pack(">BIH", _VERSION, seq, len(records))]
    for r in records:
        tag, key = _pack_key(r.chain)
        parts.append(struct.pack(">IBB", int(r.index), tag, len(key)))
        parts.append(key)
        parts.append(struct.pack(">IB", int(r.tokens), len(r.payload)))
        for name in sorted(r.payload):
            leaf = np.ascontiguousarray(r.payload[name])
            dt = _wire_dtype(leaf.dtype)
            parts.append(struct.pack(">B", len(name)))
            parts.append(name.encode())
            parts.append(struct.pack(">B", len(dt)))
            parts.append(dt)
            parts.append(struct.pack(">B", leaf.ndim))
            parts.append(struct.pack(f">{leaf.ndim}I", *leaf.shape))
            raw = leaf.tobytes()
            parts.append(struct.pack(">Q", len(raw)))
            parts.append(raw)
    body = b"".join(parts)
    return body + struct.pack(">I", crc32c(body))


def parse_frame(frame: bytes) -> Tuple[int, List[PageRecord]]:
    """Decode one chunk frame, verifying magic, version, and the
    CRC32C trailer.  Raises ``WireFrameError`` on any mismatch — the
    receiver NAKs instead of splicing corrupt pages."""
    if len(frame) < len(_MAGIC) + 7 + 4:
        raise WireFrameError(f"short frame ({len(frame)} bytes)")
    body, (crc,) = frame[:-4], struct.unpack(">I", frame[-4:])
    if crc32c(body) != crc:
        raise WireFrameError("CRC32C mismatch")
    if body[:4] != _MAGIC:
        raise WireFrameError(f"bad magic {body[:4]!r}")
    ver, seq, n = struct.unpack(">BIH", body[4:11])
    if ver != _VERSION:
        raise WireFrameError(f"wire version {ver} != {_VERSION}")
    off = 11
    out: List[PageRecord] = []
    try:
        for _ in range(n):
            index, tag, klen = struct.unpack(">IBB", body[off:off + 6])
            off += 6
            chain = _unpack_key(tag, body[off:off + klen])
            off += klen
            tokens, nleaves = struct.unpack(">IB", body[off:off + 5])
            off += 5
            payload: Dict[str, np.ndarray] = {}
            for _ in range(nleaves):
                (ln,) = struct.unpack(">B", body[off:off + 1])
                name = body[off + 1:off + 1 + ln].decode()
                off += 1 + ln
                (ln,) = struct.unpack(">B", body[off:off + 1])
                dt = _resolve_dtype(body[off + 1:off + 1 + ln].decode())
                off += 1 + ln
                (ndim,) = struct.unpack(">B", body[off:off + 1])
                shape = struct.unpack(f">{ndim}I",
                                      body[off + 1:off + 1 + 4 * ndim])
                off += 1 + 4 * ndim
                (nraw,) = struct.unpack(">Q", body[off:off + 8])
                raw = body[off + 8:off + 8 + nraw]
                off += 8 + nraw
                payload[name] = np.frombuffer(
                    raw, dt).reshape(shape).copy()
            out.append(PageRecord(index=index, chain=chain,
                                  tokens=tokens, payload=payload))
    except (struct.error, ValueError) as e:
        raise WireFrameError(f"truncated frame: {e}") from e
    return seq, out


class InProcessLink:
    """Loopback wire link for the in-process fleet: delivery returns
    the frame as the receiver would see it.  ``latency_s`` is paid
    once per ``deliver`` call (a flight of frames amortizes it — the
    overlap knob's physical meaning), and the active ``FaultPlan``'s
    wire site acts per frame: ``drop_chunk`` vanishes it (None — the
    sender sees a per-chunk timeout), ``corrupt_chunk`` flips one
    payload byte (the receiver's CRC NAKs), ``stall_wire`` sleeps the
    fault's ``seconds`` in-line (a late frame), ``kill_host`` raises
    ``ConnectionError`` (the host died mid-transfer; unrecoverable).

    A real deployment would substitute a socket-backed link with the
    same ``deliver`` contract; everything above it — framing, CRC,
    retry, dedup, degradation — is transport-agnostic."""

    def __init__(self, wire_id: int = 0, latency_s: float = 0.0,
                 sleep=time.sleep):
        self.wire_id = int(wire_id)
        self.latency_s = float(latency_s)
        self.sleep = sleep

    def deliver(self, frames: Sequence[bytes]
                ) -> List[Optional[bytes]]:
        if self.latency_s:
            self.sleep(self.latency_s)
        plan = faults_lib.active()
        out: List[Optional[bytes]] = []
        for frame in frames:
            action = (plan.on_wire_chunk(self.wire_id)
                      if plan is not None else None)
            if action == "drop":
                out.append(None)
            elif action == "corrupt":
                bad = bytearray(frame)
                bad[len(bad) // 2] ^= 0xFF
                out.append(bytes(bad))
            else:
                out.append(bytes(frame))
        return out


class PageWire:
    """The sender-side transfer state machine (module docstring).

    ``chunk_pages`` records per frame and ``overlap`` frames per
    flight are the two wire-shaping knobs ``bench.py --config=fleet``
    sweeps.  ``timeout_s`` is the per-flight delivery budget: a flight
    that exceeds it is re-sent even if frames arrived (late == lost to
    the sender; the receiver's chain-key dedup makes the duplicate
    harmless).  Retries back off exponentially with seeded jitter
    (``resilience.Supervisor``'s discipline) so a congested link is
    not hammered in lockstep.  ``sleep`` is injectable for tests."""

    def __init__(self, *, chunk_pages: int = 2, overlap: int = 1,
                 max_retries: int = 4, timeout_s: float = 0.5,
                 backoff_base_s: float = 0.002,
                 backoff_factor: float = 2.0,
                 backoff_max_s: float = 0.05, jitter: float = 0.5,
                 seed: int = 0, link: Optional[InProcessLink] = None,
                 registry: Optional[metrics_lib.Registry] = None,
                 sleep=time.sleep):
        if chunk_pages < 1:
            raise ValueError(f"chunk_pages must be >= 1; "
                             f"got {chunk_pages}")
        if overlap < 1:
            raise ValueError(f"overlap must be >= 1; got {overlap}")
        self.chunk_pages = int(chunk_pages)
        self.overlap = int(overlap)
        self.max_retries = int(max_retries)
        self.timeout_s = float(timeout_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.sleep = sleep
        self.link = link if link is not None else InProcessLink()
        self._rng = np.random.default_rng(seed)
        reg = registry if registry is not None else metrics_lib.REGISTRY
        self._m_transfers = reg.counter(
            "dttpu_wire_transfers_total",
            "Completed page-wire transfers (pages adopted by the "
            "destination pool).")
        self._m_failures = reg.counter(
            "dttpu_wire_failures_total",
            "Unrecoverable page-wire transfers (link down or chunk "
            "retries exhausted) — each degraded to re-prefill "
            "migration.")
        self._m_chunks = reg.counter(
            "dttpu_wire_chunks_total",
            "Chunk frames sent over the page wire (re-sends "
            "included).")
        self._m_retries = reg.counter(
            "dttpu_wire_chunk_retries_total",
            "Chunk frames re-sent after a drop, CRC NAK, or per-chunk "
            "timeout.")
        self._m_bytes = reg.counter(
            "dttpu_wire_bytes_total",
            "Framed bytes sent over the page wire (re-sends "
            "included).")
        self._m_pages = reg.counter(
            "dttpu_wire_pages_shipped_total",
            "KV pages adopted by destination pools via the wire.")
        self._m_seconds = reg.histogram(
            "dttpu_wire_transfer_seconds",
            "Wall clock of one completed page-wire transfer (device "
            "read to destination splice).")

    # ------------------------------------------------------------ ship

    def _delay(self, attempt: int) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s
                   * self.backoff_factor ** (attempt - 1))
        return base * (1.0 + self.jitter * float(self._rng.random()))

    def ship(self, records: Sequence[Tuple[int, Any, dict]],
             dest, snap) -> int:
        """Transfer ``records`` — ``(chunk_index, chain key, payload)``
        from the source engine's ``export_wire_pages`` — to ``dest``
        (anything with ``import_wire_pages(snap, records)``); returns
        pages the destination adopted (0 = nothing usable shipped: the
        import re-prefills those windows).

        Raises ``WireError`` on unrecoverable failure; the caller MUST
        treat that as "migrate by re-prefill", never as request
        failure."""
        if not records:
            return 0
        if not hasattr(dest, "import_wire_pages"):
            return 0                      # contiguous engine: degrade
        t0 = time.perf_counter()
        # tokens covered comes from the snapshot manifest when present
        # (authoritative), else from chunk order x page size
        manifest = dict(getattr(snap, "shipped_pages", None) or ())
        pg = int(getattr(snap, "page_size", 0) or 0)
        recs = [PageRecord(index=int(i), chain=c,
                           tokens=int(manifest.get(c, (int(i) + 1) * pg)),
                           payload=dict(p))
                for i, c, p in records]
        frames = [frame_chunk(seq, recs[seq * self.chunk_pages:
                                        (seq + 1) * self.chunk_pages])
                  for seq in range(-(-len(recs) // self.chunk_pages))]
        accepted: Dict[Any, PageRecord] = {}
        try:
            for base in range(0, len(frames), self.overlap):
                self._send_flight(
                    list(enumerate(frames))[base:base + self.overlap],
                    accepted)
        except WireError:
            self._m_failures.inc()
            raise
        # splice the contiguous prefix (chunk 0..n-1): a gap means a
        # chain the source no longer held — everything past it must
        # re-prefill anyway
        ordered = sorted(accepted.values(), key=lambda r: r.index)
        take: List[PageRecord] = []
        for j, r in enumerate(ordered):
            if r.index != j:
                break
            take.append(r)
        if not take:
            return 0
        try:
            adopted = int(dest.import_wire_pages(snap, take))
        except Exception as e:
            # a refusing destination (pool exhausted, incompatible
            # layout) is degradation, not transfer failure
            log.warning("page-wire splice refused by destination: %r", e)
            self._m_failures.inc()
            return 0
        if adopted:
            self._m_pages.inc(adopted)
            self._m_transfers.inc()
            self._m_seconds.observe(time.perf_counter() - t0)
        return adopted

    def _send_flight(self, flight: List[Tuple[int, bytes]],
                     accepted: Dict[Any, PageRecord]) -> None:
        """Deliver one flight of frames with bounded per-chunk retry.
        A frame is settled when it parses and CRC-verifies within the
        per-flight timeout; drops, NAKs, and timeouts re-send only the
        unsettled frames."""
        pending = list(flight)
        for attempt in range(self.max_retries + 1):
            self._m_chunks.inc(len(pending))
            for _, frame in pending:
                self._m_bytes.inc(len(frame))
            sent = time.perf_counter()
            try:
                outs = self.link.deliver([f for _, f in pending])
            except ConnectionError as e:
                raise WireError(f"page-wire link down: {e}") from e
            late = (time.perf_counter() - sent) > self.timeout_s
            failed: List[Tuple[int, bytes]] = []
            for (seq, frame), out in zip(pending, outs):
                ok = False
                if out is not None:
                    try:
                        _, recs = parse_frame(out)
                    except WireFrameError:
                        recs = None       # receiver NAK
                    if recs is not None:
                        # idempotent re-send: dedup by chain key — a
                        # late duplicate lands here as a no-op
                        for r in recs:
                            accepted.setdefault(r.chain, r)
                        ok = not late
                if not ok:
                    failed.append((seq, frame))
            if not failed:
                return
            if attempt >= self.max_retries:
                raise WireError(
                    f"chunk retries exhausted "
                    f"(seqs {[s for s, _ in failed]} after "
                    f"{self.max_retries} retries)")
            self._m_retries.inc(len(failed))
            self.sleep(self._delay(attempt + 1))
            pending = failed
