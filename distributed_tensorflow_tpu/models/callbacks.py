"""Keras-style callbacks for the high-level ``fit`` API.

Parity target: the reference passes ``callbacks=[TensorBoard(log_dir=...)]``
to ``model.fit`` (reference example2.py:6,197,200).  Callbacks see epoch
boundaries; per-step observability belongs to ``train.hooks``.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from ..summary import SummaryWriter

__all__ = ["Callback", "TensorBoard", "History", "EarlyStopping",
           "ModelCheckpoint"]


class Callback:
    def on_train_begin(self, model) -> None:
        pass

    def on_epoch_begin(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int, logs: Dict[str, float]) -> None:
        pass

    def on_train_end(self, model) -> None:
        pass


class TensorBoard(Callback):
    """Writes epoch metrics as TB scalars (reference example2.py:197)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._writer: Optional[SummaryWriter] = None

    def on_train_begin(self, model) -> None:
        self._writer = SummaryWriter(self.log_dir)

    def on_epoch_end(self, model, epoch, logs) -> None:
        if self._writer and logs:
            self._writer.add_scalars(logs, epoch)
            self._writer.flush()

    def on_train_end(self, model) -> None:
        if self._writer:
            self._writer.close()


class History(Callback):
    """Accumulates per-epoch logs; ``fit`` returns it (Keras convention)."""

    def __init__(self):
        self.history: Dict[str, list] = {}
        self.epochs: list = []

    def on_epoch_end(self, model, epoch, logs) -> None:
        self.epochs.append(epoch)
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


def _monitor_sign(mode: str, monitor: str) -> float:
    """+1 = lower is better.  Keras modes: min / max / auto (auto infers
    max for accuracy-ish monitors); anything else is an error, not a
    silent max."""
    if mode == "auto":
        mode = "max" if ("acc" in monitor or monitor.startswith("fmeasure")) \
            else "min"
    if mode == "min":
        return 1.0
    if mode == "max":
        return -1.0
    raise ValueError(f"mode must be 'min', 'max', or 'auto'; got {mode!r}")


class ModelCheckpoint(Callback):
    """Per-epoch weights save, optionally only on metric improvement
    (Keras ``ModelCheckpoint`` parity).  Writes the same
    ``{params, model_state}`` payload as ``Sequential.save_weights``, so
    ``load_weights`` reads these checkpoints back."""

    def __init__(self, ckpt_dir: str, monitor: str = "val_loss",
                 save_best_only: bool = False, mode: str = "auto",
                 max_to_keep: int = 5):
        self.ckpt_dir = ckpt_dir
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.sign = _monitor_sign(mode, monitor)
        self.max_to_keep = max_to_keep
        self.best = float("inf")

    def on_epoch_end(self, model, epoch, logs) -> None:
        import math
        if self.save_best_only:
            value = logs.get(self.monitor)
            if value is None or not math.isfinite(float(value)):
                return     # a NaN epoch must never become "best"
            score = self.sign * float(value)
            if score >= self.best:
                return
            self.best = score
        from ..train import checkpoint as ck
        ck.save(self.ckpt_dir, int(model.state.step),
                {"params": model.state.params,
                 "model_state": model.state.model_state},
                max_to_keep=self.max_to_keep)


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "val_loss", patience: int = 3,
                 min_delta: float = 0.0, mode: str = "auto"):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.sign = _monitor_sign(mode, monitor)
        self.best = float("inf")
        self.wait = 0

    def on_epoch_end(self, model, epoch, logs) -> None:
        value = logs.get(self.monitor)
        if value is None:
            return
        score = self.sign * float(value)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                model.stop_training = True
