"""Keras-style callbacks for the high-level ``fit`` API.

Parity target: the reference passes ``callbacks=[TensorBoard(log_dir=...)]``
to ``model.fit`` (reference example2.py:6,197,200).  Callbacks see epoch
boundaries; per-step observability belongs to ``train.hooks``.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..summary import SummaryWriter

__all__ = ["Callback", "TensorBoard", "History", "EarlyStopping",
           "ModelCheckpoint", "LearningRateScheduler", "ReduceLROnPlateau",
           "CSVLogger", "TerminateOnNaN", "LambdaCallback"]


class Callback:
    def on_train_begin(self, model) -> None:
        pass

    def on_epoch_begin(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int, logs: Dict[str, float]) -> None:
        pass

    def on_train_end(self, model) -> None:
        pass


class TensorBoard(Callback):
    """Writes epoch metrics as TB scalars (reference example2.py:197)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._writer: Optional[SummaryWriter] = None

    def on_train_begin(self, model) -> None:
        self._writer = SummaryWriter(self.log_dir)
        # graph topology event (reference example.py:195 add_graph parity);
        # only a model without an ordered layer list skips it — real
        # serialization errors must propagate
        if getattr(model, "layers", None) is not None:
            self._writer.add_graph(model)

    def on_epoch_end(self, model, epoch, logs) -> None:
        if self._writer and logs:
            self._writer.add_scalars(logs, epoch)
            self._writer.flush()

    def on_train_end(self, model) -> None:
        if self._writer:
            self._writer.close()


class History(Callback):
    """Accumulates per-epoch logs; ``fit`` returns it (Keras convention)."""

    def __init__(self):
        self.history: Dict[str, list] = {}
        self.epochs: list = []

    def on_epoch_end(self, model, epoch, logs) -> None:
        self.epochs.append(epoch)
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


def _monitor_sign(mode: str, monitor: str) -> float:
    """+1 = lower is better.  Keras modes: min / max / auto (auto infers
    max for accuracy-ish monitors); anything else is an error, not a
    silent max."""
    if mode == "auto":
        mode = "max" if ("acc" in monitor or monitor.startswith("fmeasure")) \
            else "min"
    if mode == "min":
        return 1.0
    if mode == "max":
        return -1.0
    raise ValueError(f"mode must be 'min', 'max', or 'auto'; got {mode!r}")


class ModelCheckpoint(Callback):
    """Per-epoch weights save, optionally only on metric improvement
    (Keras ``ModelCheckpoint`` parity).  Writes the same
    ``{params, model_state}`` payload as ``Sequential.save_weights``, so
    ``load_weights`` reads these checkpoints back."""

    def __init__(self, ckpt_dir: str, monitor: str = "val_loss",
                 save_best_only: bool = False, mode: str = "auto",
                 max_to_keep: int = 5):
        self.ckpt_dir = ckpt_dir
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.sign = _monitor_sign(mode, monitor)
        self.max_to_keep = max_to_keep
        self.best = float("inf")

    def on_epoch_end(self, model, epoch, logs) -> None:
        import math
        if self.save_best_only:
            value = logs.get(self.monitor)
            if value is None or not math.isfinite(float(value)):
                return     # a NaN epoch must never become "best"
            score = self.sign * float(value)
            if score >= self.best:
                return
            self.best = score
        from ..train import checkpoint as ck
        ck.save(self.ckpt_dir, int(model.state.step),
                {"params": model.state.params,
                 "model_state": model.state.model_state},
                max_to_keep=self.max_to_keep)


class LearningRateScheduler(Callback):
    """Epoch-indexed LR control (Keras ``LearningRateScheduler`` analogue).

    ``schedule(epoch) -> multiplier`` of the COMPILED base learning rate
    (the functional twist on Keras's absolute-LR setter: the base LR is
    baked into the jitted step; the callback moves the ``with_lr_scale``
    device scalar, which costs nothing and recompiles nothing).
    """

    def __init__(self, schedule, verbose: int = 0):
        self.schedule = schedule
        self.verbose = verbose

    def on_epoch_begin(self, model, epoch) -> None:
        scale = float(self.schedule(epoch))
        model.lr_scale = scale
        if self.verbose:
            print(f"LearningRateScheduler: epoch {epoch} lr_scale={scale:g}",
                  flush=True)


class ReduceLROnPlateau(Callback):
    """Shrink the LR multiplier when the monitored metric stalls (Keras
    ``ReduceLROnPlateau`` parity: factor/patience/cooldown/min)."""

    def __init__(self, monitor: str = "val_loss", factor: float = 0.1,
                 patience: int = 10, min_delta: float = 1e-4,
                 cooldown: int = 0, min_scale: float = 0.0,
                 mode: str = "auto", verbose: int = 0):
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau needs factor < 1.0")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_scale = min_scale
        self.sign = _monitor_sign(mode, monitor)
        self.verbose = verbose
        self.best = float("inf")
        self.wait = 0
        self.cooldown_left = 0

    def on_epoch_end(self, model, epoch, logs) -> None:
        value = logs.get(self.monitor)
        if value is None:
            return
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            self.wait = 0
        score = self.sign * float(value)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
        elif self.cooldown_left == 0:
            self.wait += 1
            if self.wait >= self.patience:
                new = max(model.lr_scale * self.factor, self.min_scale)
                if new < model.lr_scale:
                    model.lr_scale = new
                    if self.verbose:
                        print(f"ReduceLROnPlateau: epoch {epoch} "
                              f"lr_scale -> {new:g}", flush=True)
                self.cooldown_left = self.cooldown
                self.wait = 0


class CSVLogger(Callback):
    """Append per-epoch logs to a CSV file (Keras ``CSVLogger`` parity).
    The column set is fixed by the first logged epoch.

    With ``append=True``, a pre-existing file whose header does not start
    with ``epoch,`` raises ``ValueError`` at ``on_train_begin`` — appending
    rows under a foreign header would silently corrupt the log, so reusing
    a log path across tools is an explicit error, not a degradation."""

    def __init__(self, filename: str, append: bool = False):
        self.filename = filename
        self.append = append
        self._file = None
        self._keys = None

    def on_train_begin(self, model) -> None:
        import os
        os.makedirs(os.path.dirname(self.filename) or ".", exist_ok=True)
        if self.append:
            # appending to a file with content: its header already exists —
            # never write a second one mid-file (Keras CSVLogger behavior)
            if self._keys is None and os.path.exists(self.filename) \
                    and os.path.getsize(self.filename) > 0:
                with open(self.filename) as f:
                    header = f.readline().strip()
                if header.startswith("epoch,"):
                    self._keys = header.split(",")[1:]
                else:
                    # Appending rows under a foreign header would interleave
                    # two incompatible tables in one file; refuse instead.
                    raise ValueError(
                        f"CSVLogger(append=True): {self.filename} has an "
                        f"incompatible header {header!r} (expected it to "
                        "start with 'epoch,'); pass append=False to "
                        "overwrite or point at a fresh file")
        else:
            self._keys = None   # truncated file needs its header rewritten
        self._file = open(self.filename, "a" if self.append else "w")

    def on_epoch_end(self, model, epoch, logs) -> None:
        if self._file is None:
            return
        if self._keys is None:
            self._keys = sorted(logs)
            self._file.write(",".join(["epoch"] + self._keys) + "\n")
        row = [str(epoch)] + [f"{logs.get(k, float('nan'))}"
                              for k in self._keys]
        self._file.write(",".join(row) + "\n")
        self._file.flush()

    def on_train_end(self, model) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class TerminateOnNaN(Callback):
    """Stop training when the epoch loss goes non-finite (Keras parity;
    the per-step fail-fast variant is ``train.NaNHook``)."""

    def on_epoch_end(self, model, epoch, logs) -> None:
        import math
        loss = logs.get("loss")
        if loss is not None and not math.isfinite(float(loss)):
            print(f"TerminateOnNaN: non-finite loss at epoch {epoch}, "
                  "stopping", flush=True)
            model.stop_training = True


class LambdaCallback(Callback):
    """Ad-hoc callbacks from plain functions (Keras ``LambdaCallback``)."""

    def __init__(self, on_train_begin=None, on_epoch_begin=None,
                 on_epoch_end=None, on_train_end=None):
        if on_train_begin:
            self.on_train_begin = on_train_begin
        if on_epoch_begin:
            self.on_epoch_begin = on_epoch_begin
        if on_epoch_end:
            self.on_epoch_end = on_epoch_end
        if on_train_end:
            self.on_train_end = on_train_end


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "val_loss", patience: int = 3,
                 min_delta: float = 0.0, mode: str = "auto"):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.sign = _monitor_sign(mode, monitor)
        self.best = float("inf")
        self.wait = 0

    def on_epoch_end(self, model, epoch, logs) -> None:
        value = logs.get(self.monitor)
        if value is None:
            return
        score = self.sign * float(value)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                model.stop_training = True
