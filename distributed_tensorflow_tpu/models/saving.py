"""Full-model persistence for the Sequential tier.

Keras-2 capability parity: the reference era's ``model.save`` /
``load_model`` / ``model.to_json`` (architecture + weights + training
config in one artifact).  Layout (a directory, not HDF5 — weights ride the
framework's own checkpoint format so sharded/async machinery keeps
working):

    <dir>/model.json     architecture + compile config + input shape
    <dir>/ckpt-*/        {params, model_state} weights checkpoint

Only registry-name configs serialize (a callable activation/initializer
can't round-trip JSON); ``Layer.get_config`` raises a descriptive error
otherwise.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from ..ops import layers as layer_lib

__all__ = ["model_to_config", "model_from_config", "save_model",
           "load_model", "build_layer", "LAYER_CLASSES"]

# Every serializable layer class, keyed by class name (the Keras
# ``class_name`` convention).
LAYER_CLASSES = {
    cls.__name__: cls
    for cls in (layer_lib.Dense, layer_lib.Dropout, layer_lib.Flatten,
                layer_lib.Activation, layer_lib.Conv2D, layer_lib.MaxPool2D,
                layer_lib.AvgPool2D, layer_lib.GlobalAvgPool,
                layer_lib.BatchNorm, layer_lib.LayerNorm,
                layer_lib.Embedding, layer_lib.LSTM, layer_lib.GRU,
                layer_lib.Conv1D, layer_lib.DepthwiseConv2D,
                layer_lib.SeparableConv2D)
}


def _check_spec(spec: Dict[str, Any]) -> None:
    name = spec["class_name"]
    if name == "Stack":
        for sub in spec["config"]["layers"]:
            _check_spec(sub)
        return
    if name not in LAYER_CLASSES:
        raise ValueError(
            f"{name} is not a registered serializable layer "
            f"(known: {sorted(LAYER_CLASSES)} + Stack)")


def build_layer(spec: Dict[str, Any]):
    """One layer from its {class_name, config} spec; Stack recurses (zoo
    models are Stacks, so they serialize through Sequential too)."""
    name, cfg = spec["class_name"], spec["config"]
    if name == "Stack":
        return layer_lib.Stack([build_layer(s) for s in cfg["layers"]],
                               name=cfg.get("name"))
    return LAYER_CLASSES[name](**cfg)


def model_to_config(model) -> Dict[str, Any]:
    """Sequential -> JSON-able dict (architecture + compile + input shape)."""
    layers = [layer_lib.layer_spec(l) for l in model._layers]
    for spec in layers:
        _check_spec(spec)
    cfg: Dict[str, Any] = {"format": "dttpu-sequential-v1",
                           "name": model.name, "layers": layers}
    if model._compile_config is not None:
        cfg["compile"] = model._compile_config
    if model._in_shape is not None:
        cfg["in_shape"] = list(model._in_shape)
    return cfg


def model_from_config(cfg: Dict[str, Any]):
    """Rebuild a Sequential (uncompiled unless the config carries a
    string-based compile section)."""
    from .sequential import Sequential
    if cfg.get("format") != "dttpu-sequential-v1":
        raise ValueError(f"not a saved Sequential config: "
                         f"format={cfg.get('format')!r}")
    layers = [build_layer(spec) for spec in cfg["layers"]]
    model = Sequential(layers, name=cfg.get("name", "sequential"))
    compile_cfg = cfg.get("compile")
    if compile_cfg is not None:
        model.compile(**compile_cfg)
    return model


def save_model(model, path: str) -> str:
    """Write architecture + weights under ``path`` (a directory)."""
    if model.state is None:
        raise RuntimeError("model has no state; call fit or build before "
                           "save_model")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "model.json"), "w") as f:
        json.dump(model_to_config(model), f, indent=1)
    model.save_weights(path)
    return path


def load_model(path: str, compile: bool = True):
    """Rebuild the model saved at ``path``: architecture from model.json,
    weights from the latest checkpoint under it.

    The saved weights load on EVERY path that recorded an ``in_shape``:
    when the saved compile config is absent (it wasn't JSON-able — mesh or
    callables) or ``compile=False``, a throwaway compile/build initializes
    the param structure, the checkpoint restores into it, and the model is
    handed back uncompiled — the user's own ``compile()`` then keeps the
    weights and re-creates the optimizer state (Keras recompile
    semantics)."""
    with open(os.path.join(path, "model.json")) as f:
        cfg = json.load(f)
    if not compile:
        cfg = dict(cfg)
        cfg.pop("compile", None)
    model = model_from_config(cfg)
    in_shape: Optional[list] = cfg.get("in_shape")
    if in_shape is None:
        return model
    compiled = model._compiled is not None
    if not compiled:
        model.compile(loss="mse", optimizer="sgd")   # throwaway, see above
    model.build(tuple(in_shape))
    model.load_weights(path)
    if not compiled:
        model._compiled = None
        model._compile_config = None
    return model
