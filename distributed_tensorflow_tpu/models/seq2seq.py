"""Encoder-decoder transformer (seq2seq) — completes the transformer
family next to BERT (encoder-only, models/bert.py) and GPT (decoder-only,
models/gpt.py).

Vanilla pre-LN architecture (Vaswani et al.; T5-style tied embeddings,
learned absolute positions): a bidirectional encoder over the source, a
causal decoder with cross-attention into the encoder memory, teacher-forced
next-token training, and a jittable greedy/sampling ``generate``.

TPU design notes: both stacks scan one vmap-initialized layer pytree
(weights stay stacked [L, ...] — one XLA while-loop per stack, no
per-layer unrolled HLO); projections keep the TP-ready [d, heads, head_dim]
layout shared with BERT/GPT so one ``partition_rules`` table serves the
whole transformer family.  The reference has no attention at all
(64-bit MLP, reference example.py:149-155) — this family is part of the
"complete framework" surface, not reference parity.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import attention as attn_lib
from ..ops import initializers as init_lib
from ..ops import losses as loss_lib
from ..parallel.sharding import PartitionRules
from .bert import _dropout, _layer_norm  # one LN/dropout impl family-wide

__all__ = ["Seq2SeqConfig", "Seq2Seq", "seq2seq_tiny"]


@dataclass
class Seq2SeqConfig:
    vocab_size: int = 32128
    hidden_size: int = 512
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    intermediate_size: int = 2048
    max_position: int = 512
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def seq2seq_tiny(**kw) -> "Seq2Seq":
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_encoder_layers", 2)
    kw.setdefault("num_decoder_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position", 64)
    return Seq2Seq(Seq2SeqConfig(**kw))


def _ln_params(d):
    return {"gamma": jnp.ones((d,), jnp.float32),
            "beta": jnp.zeros((d,), jnp.float32)}


class Seq2Seq:
    """Functional encoder-decoder: ``init(key) -> params``;
    ``encode`` / ``decode`` / ``seq2seq_loss_fn`` / ``generate``."""

    def __init__(self, config: Seq2SeqConfig):
        self.config = config

    # -- init -------------------------------------------------------------
    def init(self, key) -> Dict[str, Any]:
        c = self.config
        trunc = init_lib.truncated_normal(0.02)
        d, h, hd, i = c.hidden_size, c.num_heads, c.head_dim, \
            c.intermediate_size
        k_emb, k_enc, k_dec = jax.random.split(key, 3)
        ke = jax.random.split(k_emb, 3)

        def attn(k):
            ks = jax.random.split(k, 4)
            return {
                "query": {"kernel": trunc(ks[0], (d, h, hd)),
                          "bias": jnp.zeros((h, hd), jnp.float32)},
                "key": {"kernel": trunc(ks[1], (d, h, hd)),
                        "bias": jnp.zeros((h, hd), jnp.float32)},
                "value": {"kernel": trunc(ks[2], (d, h, hd)),
                          "bias": jnp.zeros((h, hd), jnp.float32)},
                "out": {"kernel": trunc(ks[3], (h, hd, d)),
                        "bias": jnp.zeros((d,), jnp.float32)},
            }

        def ffn(k):
            k1, k2 = jax.random.split(k)
            return {"w_in": {"kernel": trunc(k1, (d, i)),
                             "bias": jnp.zeros((i,), jnp.float32)},
                    "w_out": {"kernel": trunc(k2, (i, d)),
                              "bias": jnp.zeros((d,), jnp.float32)}}

        def enc_layer(k):
            k1, k2 = jax.random.split(k)
            return {"ln_1": _ln_params(d), "attention": attn(k1),
                    "ln_2": _ln_params(d), "ffn": ffn(k2)}

        def dec_layer(k):
            k1, k2, k3 = jax.random.split(k, 3)
            return {"ln_1": _ln_params(d), "self_attention": attn(k1),
                    "ln_x": _ln_params(d), "cross_attention": attn(k2),
                    "ln_2": _ln_params(d), "ffn": ffn(k3)}

        return {
            "embeddings": {
                "word": trunc(ke[0], (c.vocab_size, d)),
                "enc_position": trunc(ke[1], (c.max_position, d)),
                "dec_position": trunc(ke[2], (c.max_position, d)),
            },
            "encoder": jax.vmap(enc_layer)(
                jax.random.split(k_enc, c.num_encoder_layers)),
            "decoder": jax.vmap(dec_layer)(
                jax.random.split(k_dec, c.num_decoder_layers)),
            "ln_enc_f": _ln_params(d),
            "ln_dec_f": _ln_params(d),
        }

    # -- blocks -----------------------------------------------------------
    def _enc_block(self, p, x, src_mask, rng, train):
        c = self.config
        r1, r2, r3 = jax.random.split(rng, 3)
        a = attn_lib.attention_core(
            p["attention"], _layer_norm(p["ln_1"], x, c.layer_norm_eps),
            mask=src_mask, dropout_rate=c.dropout_rate, rng=r1, train=train)
        x = x + _dropout(a, c.dropout_rate, r2, train)
        f = attn_lib.ffn_core(p["ffn"],
                              _layer_norm(p["ln_2"], x, c.layer_norm_eps))
        return x + _dropout(f, c.dropout_rate, r3, train)

    def _dec_block(self, p, x, memory, self_mask, cross_mask, rng, train):
        c = self.config
        r1, r2, r3, r4, r5 = jax.random.split(rng, 5)
        a = attn_lib.attention_core(
            p["self_attention"],
            _layer_norm(p["ln_1"], x, c.layer_norm_eps),
            mask=self_mask, dropout_rate=c.dropout_rate, rng=r1, train=train)
        x = x + _dropout(a, c.dropout_rate, r2, train)
        ca = attn_lib.attention_core(
            p["cross_attention"],
            _layer_norm(p["ln_x"], x, c.layer_norm_eps),
            kv=memory, mask=cross_mask, dropout_rate=c.dropout_rate,
            rng=r3, train=train)
        x = x + _dropout(ca, c.dropout_rate, r4, train)
        f = attn_lib.ffn_core(p["ffn"],
                              _layer_norm(p["ln_2"], x, c.layer_norm_eps))
        return x + _dropout(f, c.dropout_rate, r5, train)

    # -- forward ----------------------------------------------------------
    def encode(self, params, src_ids, src_valid=None, *, train=False,
               rng=None):
        """-> memory [b, s, d].  ``src_valid``: [b, s] 1/0 padding mask."""
        c = self.config
        if rng is None:
            if train:
                raise ValueError("encode(train=True) requires rng")
            rng = jax.random.PRNGKey(0)
        b, s = src_ids.shape
        emb = params["embeddings"]
        x = jnp.take(emb["word"], src_ids, axis=0)
        x = x + emb["enc_position"][None, :s, :]
        r_emb, r_layers = jax.random.split(rng)
        x = _dropout(x, c.dropout_rate, r_emb, train).astype(c.dtype)
        mask = None if src_valid is None else attn_lib.padding_mask(src_valid)

        layer_fn = self._enc_block
        if c.remat:
            layer_fn = jax.checkpoint(layer_fn, static_argnums=(4,))

        def body(carry, inputs):
            lp, lk = inputs
            return layer_fn(lp, carry, mask, lk, train), None

        keys = jax.random.split(r_layers, c.num_encoder_layers)
        x, _ = lax.scan(body, x, (params["encoder"], keys))
        return _layer_norm(params["ln_enc_f"], x, c.layer_norm_eps)

    def decode(self, params, memory, tgt_ids, src_valid=None, *,
               train=False, rng=None):
        """-> hidden [b, t, d]; causal self-attention + cross-attention."""
        c = self.config
        if rng is None:
            if train:
                raise ValueError("decode(train=True) requires rng")
            rng = jax.random.PRNGKey(0)
        b, t = tgt_ids.shape
        emb = params["embeddings"]
        x = jnp.take(emb["word"], tgt_ids, axis=0)
        x = x + emb["dec_position"][None, :t, :]
        r_emb, r_layers = jax.random.split(rng)
        x = _dropout(x, c.dropout_rate, r_emb, train).astype(c.dtype)
        self_mask = attn_lib.causal_mask(t)
        cross_mask = (None if src_valid is None
                      else attn_lib.padding_mask(src_valid))

        layer_fn = self._dec_block
        if c.remat:
            layer_fn = jax.checkpoint(layer_fn, static_argnums=(6,))

        def body(carry, inputs):
            lp, lk = inputs
            return layer_fn(lp, carry, memory, self_mask, cross_mask, lk,
                            train), None

        keys = jax.random.split(r_layers, c.num_decoder_layers)
        x, _ = lax.scan(body, x, (params["decoder"], keys))
        return _layer_norm(params["ln_dec_f"], x, c.layer_norm_eps)

    def logits(self, params, hidden):
        """Tied head -> [b, t, vocab] f32."""
        w = params["embeddings"]["word"].T.astype(hidden.dtype)
        return (hidden @ w).astype(jnp.float32)

    # -- training ---------------------------------------------------------
    def seq2seq_loss_fn(self):
        """``make_custom_train_step`` contract.  Batch dict:
        ``src_ids`` [b, s], ``tgt_ids`` [b, t] (BOS-prefixed; next-token
        targets are the shifted ids), optional ``src_valid`` [b, s] and
        ``loss_mask`` [b, t-1]."""

        def loss_fn(params, model_state, batch, rng, train):
            # rng passes through untouched: encode/decode raise on
            # (train=True, rng=None) — never silently reuse a fixed key
            r_enc = r_dec = None
            if rng is not None:
                r_enc, r_dec = jax.random.split(rng)
            memory = self.encode(params, batch["src_ids"],
                                 batch.get("src_valid"), train=train,
                                 rng=r_enc)
            hidden = self.decode(params, memory, batch["tgt_ids"][:, :-1],
                                 batch.get("src_valid"), train=train,
                                 rng=r_dec)
            logits = self.logits(params, hidden)
            targets = batch["tgt_ids"][:, 1:]
            mask = batch.get("loss_mask")
            loss = loss_lib.softmax_cross_entropy_with_integer_labels(
                logits, targets, where=mask)
            hits = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
            if mask is not None:
                acc = jnp.sum(hits * mask) / jnp.maximum(jnp.sum(mask), 1.0)
                metrics = {"token_accuracy": acc,
                           "loss_weight": jnp.sum(mask).astype(jnp.float32)}
            else:
                metrics = {"token_accuracy": jnp.mean(hits)}
            return loss, (metrics, model_state)

        return loss_fn

    # -- generation -------------------------------------------------------
    def generate(self, params, src_ids, max_new_tokens: int,
                 bos_id: int = 0, temperature: float = 0.0, rng=None,
                 src_valid=None, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 pad_id: Optional[int] = None) -> jnp.ndarray:
        """Greedy/sampled decode: encode once, then one ``lax.scan`` over
        target positions (full decoder recompute per step — O(t²) but
        cache-free and jittable at any length; fine at eval scale).
        Returns [b, max_new_tokens] (BOS not included).

        ``eos_id``: rows that emit EOS are finished — they pad with
        ``pad_id`` (default: ``eos_id``) and the loop becomes a
        ``lax.while_loop`` exiting once every row finished (the GPT
        ``generate`` early-exit, see models/gpt.py).
        """
        c = self.config
        if max_new_tokens > c.max_position:
            raise ValueError(f"max_new_tokens {max_new_tokens} exceeds "
                             f"max_position {c.max_position}")
        from ..ops import decoding as dec
        pad = dec.resolve_pad(eos_id, pad_id)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        b = src_ids.shape[0]
        memory = self.encode(params, src_ids, src_valid)
        # BOS everywhere keeps the scan path identical; the eos path's
        # untouched tail positions are overwritten with pad on the fly.
        tgt = jnp.full((b, max_new_tokens + 1), bos_id, jnp.int32)

        def advance(tgt, rng, finished, i):
            hidden = self.decode(params, memory, tgt[:, :-1], src_valid)
            # select the d-wide row FIRST, project only it to vocab
            row = jnp.take_along_axis(
                hidden, i[None, None, None], axis=1)
            logits = self.logits(params, row)[:, 0, :]
            rng, sub = jax.random.split(rng)
            nxt = dec.sample_logits(sub, logits, temperature,
                                    top_k=top_k, top_p=top_p)
            if eos_id is not None:
                nxt, finished = dec.finish_step(nxt, finished, eos_id, pad)
            tgt = lax.dynamic_update_slice_in_dim(
                tgt, nxt[:, None], i + 1, axis=1)
            return tgt, rng, finished

        no_finish = jnp.zeros((b,), bool)
        if eos_id is None:
            def step(carry, i):
                tgt, rng = carry
                tgt, rng, _ = advance(tgt, rng, no_finish, i)
                return (tgt, rng), None

            (tgt, _), _ = lax.scan(step, (tgt, rng),
                                   jnp.arange(max_new_tokens))
            return tgt[:, 1:]

        (tgt, _, finished), stop_i = dec.decode_loop(
            lambda carry, i: advance(*carry, i),
            (tgt, rng, no_finish), max_new_tokens)
        # early exit leaves the tail at bos_id — pad it explicitly
        pos = jnp.arange(1, max_new_tokens + 1)[None, :]
        tgt = tgt.at[:, 1:].set(
            jnp.where(pos > stop_i, pad, tgt[:, 1:]))
        return tgt[:, 1:]

    def beam_search(self, params, src_ids, max_new_tokens: int,
                    beam_size: int = 4, bos_id: int = 0,
                    eos_id: Optional[int] = None,
                    length_penalty: float = 0.6,
                    src_valid=None) -> jnp.ndarray:
        """Jittable beam search: one loop over target positions, beams
        flattened into the batch dim for the decoder — a ``lax.scan``
        without ``eos_id``, or an early-exit ``lax.while_loop``
        (``ops.decoding.decode_loop``) that stops once every beam
        finished, with the unwritten tail filled with EOS.

        Scores are sum-of-logprobs; finished beams (emitted ``eos_id``)
        freeze their score and can only extend with EOS.  Final ranking
        divides by ``length^length_penalty`` (GNMT convention).  Returns
        the best sequence per batch row, [b, max_new_tokens]."""
        c = self.config
        if max_new_tokens > c.max_position:
            raise ValueError(f"max_new_tokens {max_new_tokens} exceeds "
                             f"max_position {c.max_position}")
        b = src_ids.shape[0]
        k = beam_size
        V = c.vocab_size
        memory = self.encode(params, src_ids, src_valid)
        mem_k = jnp.repeat(memory, k, axis=0)           # [b*k, s, d]
        valid_k = (None if src_valid is None
                   else jnp.repeat(src_valid, k, axis=0))

        from ..ops import decoding as dec

        T = max_new_tokens
        seqs = jnp.full((b, k, T + 1), bos_id, jnp.int32)
        scores = dec.init_beam_scores(b, k)
        finished = jnp.zeros((b, k), bool)

        def advance(carry, i):
            seqs, scores, finished = carry
            flat = seqs.reshape(b * k, T + 1)[:, :-1]
            hidden = self.decode(params, mem_k, flat, valid_k)
            row = jnp.take_along_axis(hidden, i[None, None, None], axis=1)
            logits = self.logits(params, row)[:, 0, :]      # [b*k, V]
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(b, k, V)
            logp = dec.freeze_finished(logp, finished, eos_id)
            scores, beam, tok = dec.expand_beams(scores, logp)
            seqs = jnp.take_along_axis(seqs, beam[:, :, None], axis=1)
            seqs = lax.dynamic_update_slice_in_dim(
                seqs, tok[:, :, None], i + 1, axis=2)
            finished = jnp.take_along_axis(finished, beam, axis=1)
            if eos_id is not None:
                finished = finished | (tok == eos_id)
            return (seqs, scores, finished)

        if eos_id is None:
            (seqs, scores, finished), _ = lax.scan(
                lambda carry, i: (advance(carry, i), None),
                (seqs, scores, finished), jnp.arange(T))
        else:
            # early exit once every beam finished; unwritten tail = EOS
            # (what frozen beams keep emitting on the full run)
            (seqs, scores, finished), steps = dec.decode_loop(
                advance, (seqs, scores, finished), T)
            pos = jnp.arange(T + 1)[None, None, :]
            seqs = jnp.where(pos > steps, eos_id, seqs)
        best = dec.rank_beams(scores, seqs[:, :, 1:], eos_id, T,
                              length_penalty)
        return jnp.take_along_axis(
            seqs[:, :, 1:], best[:, None, None], axis=1)[:, 0, :]

    # -- sharding ---------------------------------------------------------
    def partition_rules(self, fsdp: bool = False) -> PartitionRules:
        """Megatron TP over heads/intermediate, same table shape as
        BERT/GPT; ``fsdp=True`` adds the ZeRO axis on the other dim."""
        f = "fsdp" if fsdp else None
        return PartitionRules([
            (r"embeddings/word", P("tensor", f)),
            (r"embeddings/(enc|dec)_position", P(None, None)),
            (r"(self_|cross_)?attention/(query|key|value)/kernel",
             P(None, f, "tensor", None)),
            (r"(self_|cross_)?attention/(query|key|value)/bias",
             P(None, "tensor", None)),
            (r"(self_|cross_)?attention/out/kernel",
             P(None, "tensor", None, f)),
            (r"ffn/w_in/kernel", P(None, f, "tensor")),
            (r"ffn/w_in/bias", P(None, "tensor")),
            (r"ffn/w_out/kernel", P(None, "tensor", f)),
        ])
