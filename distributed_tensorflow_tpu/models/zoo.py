"""Model zoo: the baseline-config architectures (BASELINE.md #1-#3).

Builders return ``ops.Stack``s compatible with both API tiers (low-level
``make_train_step`` and ``Sequential``-style training via
``Sequential(stack.layers)``).
"""
from __future__ import annotations

from .. import ops

__all__ = ["xor_mlp", "mnist_mlp", "cifar_cnn"]


def xor_mlp(bits: int = 32) -> ops.Stack:
    """The reference's model, verbatim capability (reference
    example.py:149-155): 2*bits -> 128 relu -> drop .3 -> 128 relu ->
    drop .3 -> bits sigmoid."""
    return ops.serial(
        ops.Dense(128, activation="relu"),
        ops.Dropout(0.3),
        ops.Dense(128, activation="relu"),
        ops.Dropout(0.3),
        ops.Dense(bits, activation="sigmoid"),
    )


def mnist_mlp(num_classes: int = 10) -> ops.Stack:
    """BASELINE config #1/#2: 2-layer MLP over flattened 28x28 images."""
    return ops.serial(
        ops.Dense(128, activation="relu"),
        ops.Dropout(0.2),
        ops.Dense(num_classes),
    )


def cifar_cnn(num_classes: int = 10) -> ops.Stack:
    """BASELINE config #3: small conv net for 32x32x3 images (the
    ``outline_keras.py`` model).  NHWC, all convs lower to the MXU."""
    return ops.serial(
        ops.Conv2D(32, 3, activation="relu"),
        ops.Conv2D(32, 3, activation="relu"),
        ops.MaxPool2D(2),
        ops.Conv2D(64, 3, activation="relu"),
        ops.Conv2D(64, 3, activation="relu"),
        ops.MaxPool2D(2),
        ops.Flatten(),
        ops.Dense(256, activation="relu"),
        ops.Dropout(0.5),
        ops.Dense(num_classes),
    )
