"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy speculative decoding (Leviathan et al. 2023; Stern et al. 2018's
blockwise verification): per round the DRAFT model autoregressively
proposes ``gamma`` tokens with its own KV cache, then the TARGET model
scores the window ``[current, d_1..d_gamma]`` in ONE ``decode_window``
dispatch.  The longest prefix of draft tokens matching the target's
greedy choices is accepted, followed by one target-chosen token (the
correction at the first divergence, or the BONUS token after a clean
sweep) — so every round emits 1..gamma+1 tokens for ONE target forward.

Output guarantee: the emitted sequence is the target model's greedy
decode — the acceptance rule only ever keeps tokens the target itself
chose, so the speedup comes from the draft's proposals amortizing
target dispatches, never from changing the answer.  One numerical
caveat: corrections/bonus tokens argmax ``decode_window`` logits while
``generate`` argmaxes ``decode_step`` logits — two XLA reductions that
agree to ~1e-4, so a vocab pair tied closer than that at an emitted
position can in principle flip a token between the two paths (same
class of tie-noise as the int8 row's greedy-agreement metric).
tests/test_speculative.py asserts bit-equality against ``GPT.generate``
at fixed seeds on the CPU backend, where this is deterministic.

Cache rollback costs nothing: rejected positions stay in the KV cache
but are masked (attention reads columns ``<= pos + row``) and are
overwritten by the next round's window write.

Scope: batch size 1 (speculative decoding is the LATENCY play — at large
batch the accelerator is throughput-bound and verification wastes the
rejected columns' FLOPs) and greedy only; temperature sampling needs the
rejection-sampling acceptance rule, a documented follow-up.  The
reference has no serving tier at all (SURVEY.md §2 — framework-native
scope, like the KV cache itself).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate_speculative"]


def generate_speculative(target_model, target_params, draft_model,
                         draft_params, prompt_ids, max_new_tokens: int,
                         gamma: int = 4,
                         max_len: Optional[int] = None):
    """Greedy speculative decode; returns (tokens [1, plen + new],
    accepted_fraction scalar — the mean share of draft proposals kept).

    ``target_model``/``draft_model``: GPT instances sharing the
    tokenizer/vocab.  ``prompt_ids``: [1, plen] int32.
    """
    b, plen = prompt_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative decoding is the batch-1 latency path; got "
            f"batch {b} (run generate() for throughput batching)")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1; got {gamma}")
    total = plen + max_new_tokens
    if max_len is not None and total > max_len:
        # same refusal contract as GPT.generate's _check_gen_lengths
        raise ValueError(f"prompt ({plen}) + max_new_tokens "
                         f"({max_new_tokens}) = {total} exceeds "
                         f"max_len {max_len}")
    # the last round starts at i <= total-2, so windows write token/cache
    # columns up to total+gamma-1 and embed positions up to total+gamma-2;
    # the scratch tail is sliced off before returning
    scratch = total + gamma
    for model, which in ((target_model, "target"), (draft_model, "draft")):
        c = model.config
        if (c.position_embedding == "learned"
                and c.max_position < scratch - 1):
            raise ValueError(
                f"{which} model's learned position table ({c.max_position}"
                f") is smaller than plen + max_new_tokens + gamma - 1 = "
                f"{scratch - 1} — speculative windows need that headroom")

    t_cache = target_model.init_cache(1, scratch)
    d_cache = draft_model.init_cache(1, scratch)
    tokens = jnp.zeros((1, scratch), jnp.int32)
    tokens = lax.dynamic_update_slice_in_dim(tokens, prompt_ids, 0, axis=1)

    # prompt prefill on BOTH models; the target's last-position logits
    # emit the first new token
    logits, t_cache = target_model.decode_block(target_params, t_cache,
                                                prompt_ids)
    first = jnp.argmax(logits, -1).astype(jnp.int32)         # [1]
    tokens = lax.dynamic_update_slice_in_dim(tokens, first[:, None],
                                             plen, axis=1)
    _, d_cache = draft_model.decode_block(draft_params, d_cache,
                                          prompt_ids)

    def round_step(state):
        tokens, t_cache, d_cache, i, n_acc, n_prop = state
        tok_i = lax.dynamic_slice_in_dim(tokens, i, 1, axis=1)[:, 0]

        # -- draft: gamma+1 autoregressive steps from tokens[i] ----------
        # (the +1 consumes its own last proposal so the draft cache holds
        # K/V for every window column even after a clean sweep; its final
        # prediction is discarded)
        def draft_one(carry, _):
            d_cache, tok = carry
            lg, d_cache = draft_model.decode_step(draft_params, d_cache,
                                                  tok)
            nxt = jnp.argmax(lg, -1).astype(jnp.int32)       # [1]
            return (d_cache, nxt), nxt

        (d_cache, _), proposals = lax.scan(draft_one, (d_cache, tok_i),
                                           None, length=gamma + 1)
        drafts = proposals[:gamma, 0]                        # [gamma]

        # -- target: verify all gamma proposals (+ bonus) in ONE window --
        window = jnp.concatenate([tok_i, drafts])[None, :]   # [1, gamma+1]
        logits, t_cache = target_model.decode_window(target_params,
                                                     t_cache, window)
        greedy = jnp.argmax(logits[0], -1).astype(jnp.int32)  # [gamma+1]
        # greedy[k] is the target's choice for token index i+k+1; the
        # draft's claim for that index is drafts[k] (k < gamma);
        # greedy[gamma] is the bonus token after a clean sweep

        match = drafts == greedy[:gamma]
        n = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))    # leading Trues
        # emit accepted drafts then the target's correction/bonus
        emit = jnp.where(jnp.arange(gamma + 1) < n,
                         jnp.concatenate([drafts, drafts[-1:]]), greedy)
        n_emit = jnp.minimum(n + 1, total - 1 - i)           # never overrun
        tokens = lax.dynamic_update_slice_in_dim(
            tokens, emit[None, :], i + 1, axis=1)

        # rollback = move pos; stale columns are masked, then overwritten
        t_cache = dict(t_cache, pos=i + n_emit)
        d_cache = dict(d_cache, pos=i + n_emit)
        return (tokens, t_cache, d_cache, i + n_emit,
                n_acc + jnp.minimum(n, n_emit), n_prop + gamma)

    def cond(state):
        _, _, _, i, _, _ = state
        return i < total - 1

    state = (tokens, t_cache, d_cache, jnp.int32(plen),
             jnp.int32(0), jnp.int32(0))
    tokens, _, _, _, n_acc, n_prop = lax.while_loop(cond, round_step,
                                                    state)
    accepted_fraction = n_acc / jnp.maximum(n_prop, 1)
    return tokens[:, :total], accepted_fraction
