"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy speculative decoding (Leviathan et al. 2023; Stern et al. 2018's
blockwise verification): per round the DRAFT model autoregressively
proposes ``gamma`` tokens with its own KV cache, then the TARGET model
scores the window ``[current, d_1..d_gamma]`` in ONE ``decode_window``
dispatch.  The longest prefix of draft tokens matching the target's
greedy choices is accepted, followed by one target-chosen token (the
correction at the first divergence, or the BONUS token after a clean
sweep) — so every round emits 1..gamma+1 tokens for ONE target forward.

Output guarantee (greedy mode): the emitted sequence is the target
model's greedy decode — the acceptance rule only ever keeps tokens the
target itself chose, so the speedup comes from the draft's proposals
amortizing target dispatches, never from changing the answer.  One
numerical caveat: corrections/bonus tokens argmax ``decode_window``
logits while ``generate`` argmaxes ``decode_step`` logits — two XLA
reductions that agree to ~1e-4, so a vocab pair tied closer than that
at an emitted position can in principle flip a token between the two
paths (same class of tie-noise as the int8 row's greedy-agreement
metric).  tests/test_speculative.py asserts bit-equality against
``GPT.generate`` at fixed seeds on the CPU backend, where this is
deterministic.  In sampled mode the guarantee is distributional: the
output law equals token-by-token sampling from the target.

Cache rollback costs nothing: rejected positions stay in the KV cache
but are masked (attention reads columns ``<= pos + row``) and are
overwritten by the next round's window write.

Scope: batch size 1 (speculative decoding is the LATENCY play — at large
batch the accelerator is throughput-bound and verification wastes the
rejected columns' FLOPs).  ``temperature <= 0`` uses the greedy
longest-matching-prefix rule above; ``temperature > 0`` uses
``speculative_accept``'s rejection sampling, whose emitted tokens are
distributed exactly as sampling from the target (Monte-Carlo-verified in
tests/test_speculative.py).  ``top_k``/``top_p`` apply the SAME
``ops.decoding.filtered_logits`` filter to both sides, so filtered
sampled speculative decoding reproduces ``generate``'s filtered
sampling law.  The reference has no serving tier at all (SURVEY.md §2 —
framework-native scope, like the KV cache itself).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate_speculative", "speculative_accept"]


def speculative_accept(rng, p, q, drafts):
    """The rejection-sampling acceptance rule (Leviathan et al. 2023,
    Thm 1): accept draft ``d_k ~ q_k`` with prob ``min(1, p_k(d_k) /
    q_k(d_k))``; at the first rejection emit a token from the residual
    ``norm(max(0, p_n - q_n))``; after a clean sweep emit the bonus from
    ``p_gamma``.  The emitted prefix is then distributed EXACTLY as
    sampling from ``p`` token by token — the distribution-preserving
    counterpart of the greedy longest-prefix rule (verified empirically
    by tests/test_speculative.py's Monte-Carlo check).

    ``p``: [gamma+1, V] target probabilities (row k for token index
    i+k+1); ``q``: [gamma, V] draft proposal probabilities;
    ``drafts``: [gamma] int32 proposed tokens.
    Returns (n accepted [scalar int32], emit [gamma+1] int32 — rows
    ``< n`` are accepted drafts, row ``n`` is the residual/bonus draw).
    """
    gamma = drafts.shape[0]
    k_rng, r_rng = jax.random.split(rng)
    u = jax.random.uniform(k_rng, (gamma,))
    p_d = jnp.take_along_axis(p[:gamma], drafts[:, None], axis=1)[:, 0]
    q_d = jnp.take_along_axis(q, drafts[:, None], axis=1)[:, 0]
    # u < p/q without dividing by zero; STRICT so p_d == 0 (a token the
    # filtered target excludes) can never be accepted even when u == 0.0
    # (uniform samples [0, 1)); p_d >= q_d still accepts w.p. 1 since
    # u*q_d < q_d <= p_d
    accept = u * q_d < p_d
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))

    # residual distribution at the first rejected position (row n); the
    # bonus row gamma IS p there (q has no row gamma: pad with zeros)
    q_pad = jnp.concatenate([q, jnp.zeros_like(p[:1])], axis=0)
    res = jnp.maximum(p[n] - q_pad[n], 0.0)
    tot = jnp.sum(res)
    # tot == 0 can only happen when p == q rowwise (acceptance prob 1,
    # so the rejection branch is unreachable); guard the normalization
    res = jnp.where(tot > 0, res / jnp.maximum(tot, 1e-20), p[n])
    corr = jax.random.choice(r_rng, res.shape[-1], p=res)
    emit = jnp.where(jnp.arange(gamma + 1) < n,
                     jnp.concatenate([drafts, drafts[-1:]]),
                     corr.astype(jnp.int32))
    return n, emit


def generate_speculative(target_model, target_params, draft_model,
                         draft_params, prompt_ids, max_new_tokens: int,
                         gamma: int = 4,
                         temperature: float = 0.0, rng=None,
                         max_len: Optional[int] = None,
                         prefill_chunk: Optional[int] = None,
                         eos_id: Optional[int] = None,
                         pad_id: Optional[int] = None,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None):
    """Speculative decode; returns (tokens [1, plen + new],
    accepted_fraction scalar — the mean share of draft proposals kept).

    ``temperature <= 0``: greedy longest-matching-prefix acceptance
    (output = the target's greedy decode).  ``temperature > 0``:
    ``speculative_accept``'s rejection sampling — drafts sample from
    ``softmax(q/T)``, the target accepts/corrects so the OUTPUT
    distribution equals sampling from ``softmax(p/T)`` directly (the
    Leviathan guarantee).  ``top_k``/``top_p`` apply the SAME filter to
    both sides (``ops.decoding.filtered_logits``), so the output law
    equals ``generate``'s filtered sampling — the guarantee holds for
    the filtered target distribution.  ``eos_id``: generation stops at
    the first emitted EOS (the
    round truncates there; later slots hold ``pad_id``, default
    ``eos_id`` — ``generate``'s stop-token contract).
    ``target_model``/``draft_model``: GPT instances sharing the
    tokenizer/vocab.  ``prompt_ids``: [1, plen] int32.
    """
    b, plen = prompt_ids.shape
    if b != 1:
        raise ValueError(
            f"speculative decoding is the batch-1 latency path; got "
            f"batch {b} (run generate() for throughput batching)")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1; got {gamma}")
    total = plen + max_new_tokens
    if max_len is not None and total > max_len:
        # same refusal contract as GPT.generate's _check_gen_lengths
        raise ValueError(f"prompt ({plen}) + max_new_tokens "
                         f"({max_new_tokens}) = {total} exceeds "
                         f"max_len {max_len}")
    # the last round starts at i <= total-2, so windows write token/cache
    # columns up to total+gamma-1 and embed positions up to total+gamma-2;
    # the scratch tail is sliced off before returning
    scratch = total + gamma
    for model, which in ((target_model, "target"), (draft_model, "draft")):
        c = model.config
        if (c.position_embedding == "learned"
                and c.max_position < scratch - 1):
            raise ValueError(
                f"{which} model's learned position table ({c.max_position}"
                f") is smaller than plen + max_new_tokens + gamma - 1 = "
                f"{scratch - 1} — speculative windows need that headroom")

    sampled = temperature > 0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    from ..ops import decoding as dec
    # unconditional: resolve_pad raises on pad_id-without-eos_id, the
    # same argument contract as generate
    resolved = dec.resolve_pad(eos_id, pad_id)
    pad = 0 if resolved is None else resolved

    t_cache = target_model.init_cache(1, scratch)
    d_cache = draft_model.init_cache(1, scratch)
    tokens = jnp.full((1, scratch), pad, jnp.int32)
    tokens = lax.dynamic_update_slice_in_dim(tokens, prompt_ids, 0, axis=1)

    # prompt prefill on BOTH models (optionally chunked — the bounded-
    # memory long-prompt path); the target's last-position logits emit
    # the first new token
    logits, t_cache = target_model.prefill_cache(target_params, t_cache,
                                                 prompt_ids,
                                                 chunk=prefill_chunk)
    rng, sub = jax.random.split(rng)
    # shared next-token selection rule (temperature <= 0 is greedy there)
    first = dec.sample_logits(sub, logits, temperature,
                              top_k=top_k, top_p=top_p)      # [1]
    tokens = lax.dynamic_update_slice_in_dim(tokens, first[:, None],
                                             plen, axis=1)
    finished0 = (jnp.any(first == eos_id) if eos_id is not None
                 else jnp.asarray(False))
    _, d_cache = draft_model.prefill_cache(draft_params, d_cache,
                                           prompt_ids,
                                           chunk=prefill_chunk)

    def round_step(state):
        tokens, t_cache, d_cache, rng, i, n_acc, n_prop, _ = state
        tok_i = lax.dynamic_slice_in_dim(tokens, i, 1, axis=1)[:, 0]

        # -- draft: gamma+1 autoregressive steps from tokens[i] ----------
        # (the +1 consumes its own last proposal so the draft cache holds
        # K/V for every window column even after a clean sweep; its final
        # prediction is discarded)
        def draft_one(carry, step_rng):
            d_cache, tok = carry
            lg, d_cache = draft_model.decode_step(draft_params, d_cache,
                                                  tok)
            if sampled:
                # ONE filter pass: the sample and its recorded q row
                # come from the same filtered tensor
                fl = dec.filtered_logits(lg, temperature, top_k, top_p)
                nxt = jax.random.categorical(step_rng, fl
                                             ).astype(jnp.int32)  # [1]
                probs = jax.nn.softmax(fl[0])
            else:
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)       # [1]
                probs = lg[0]   # unused on the greedy path
            return (d_cache, nxt), (nxt, probs)

        rng, d_rng, a_rng = jax.random.split(rng, 3)
        (d_cache, _), (proposals, q_rows) = lax.scan(
            draft_one, (d_cache, tok_i),
            jax.random.split(d_rng, gamma + 1))
        drafts = proposals[:gamma, 0]                        # [gamma]

        # -- target: verify all gamma proposals (+ bonus) in ONE window --
        window = jnp.concatenate([tok_i, drafts])[None, :]   # [1, gamma+1]
        logits, t_cache = target_model.decode_window(target_params,
                                                     t_cache, window)
        # row k scores token index i+k+1; the draft's claim for that
        # index is drafts[k] (k < gamma); row gamma is the bonus position

        if sampled:
            # the same filter on the target side: acceptance then
            # reproduces the FILTERED target law, matching generate's
            # filtered sampling semantics
            p = jax.nn.softmax(dec.filtered_logits(
                logits[0], temperature, top_k, top_p))       # [gamma+1, V]
            n, emit = speculative_accept(a_rng, p, q_rows[:gamma], drafts)
        else:
            greedy = jnp.argmax(logits[0], -1).astype(jnp.int32)
            match = drafts == greedy[:gamma]
            n = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
            # emit accepted drafts then the target's correction/bonus
            emit = jnp.where(jnp.arange(gamma + 1) < n,
                             jnp.concatenate([drafts, drafts[-1:]]),
                             greedy)
        n_emit = jnp.minimum(n + 1, total - 1 - i)           # never overrun
        finished = jnp.asarray(False)
        if eos_id is not None:
            # stop at the FIRST emitted EOS: truncate the round there and
            # pad the rest of this round's write (nothing overwrites it)
            idx = jnp.arange(gamma + 1)
            is_eos = (emit == eos_id) & (idx < n_emit)
            first_eos = jnp.min(jnp.where(is_eos, idx, gamma + 1))
            finished = jnp.any(is_eos)
            n_emit = jnp.minimum(n_emit, first_eos + 1)
            emit = jnp.where(idx < n_emit, emit, pad)
        tokens = lax.dynamic_update_slice_in_dim(
            tokens, emit[None, :], i + 1, axis=1)

        # rollback = move pos; stale columns are masked, then overwritten
        t_cache = dict(t_cache, pos=i + n_emit)
        d_cache = dict(d_cache, pos=i + n_emit)
        return (tokens, t_cache, d_cache, rng, i + n_emit,
                n_acc + jnp.minimum(n, n_emit), n_prop + gamma,
                finished)

    def cond(state):
        _, _, _, _, i, _, _, finished = state
        return (i < total - 1) & ~finished

    state = (tokens, t_cache, d_cache, rng, jnp.int32(plen),
             jnp.int32(0), jnp.int32(0), finished0)
    tokens, _, _, _, _, n_acc, n_prop, _ = lax.while_loop(cond, round_step,
                                                          state)
    accepted_fraction = n_acc / jnp.maximum(n_prop, 1)
    return tokens[:, :total], accepted_fraction
