"""ResNet family (v1.5 bottleneck) — BASELINE config #4 (pjit on a 2D mesh).

Implemented against the framework's own Layer protocol (params + BatchNorm
running-stat state), NHWC throughout so convs tile onto the MXU.  The
``partition_rules`` shard conv output channels over ``tensor`` and
optionally fsdp the input-channel dim; BatchNorm can be made cross-replica
by passing ``axis_name`` when training under shard_map (under plain pjit the
global-batch stats come out of the partitioner automatically).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.layers import BatchNorm, Conv2D, Dense, GlobalAvgPool, Layer
from ..parallel.sharding import PartitionRules

__all__ = ["ResNet", "resnet18", "resnet50", "resnet_cifar"]


class _Bottleneck(Layer):
    """1x1 -> 3x3 -> 1x1 (x4) with projection shortcut when shapes change."""
    expansion = 4

    def __init__(self, filters: int, in_channels: int, stride: int = 1,
                 name: Optional[str] = None):
        super().__init__(name or "bottleneck")
        self.filters = filters
        self.stride = stride
        self.conv1 = Conv2D(filters, 1, use_bias=False)
        self.bn1 = BatchNorm()
        self.conv2 = Conv2D(filters, 3, strides=stride, use_bias=False)
        self.bn2 = BatchNorm()
        self.conv3 = Conv2D(filters * self.expansion, 1, use_bias=False)
        self.bn3 = BatchNorm()
        # Shortcut structure is fixed at construction (not in init()), so a
        # fresh model instance can apply() restored params directly.
        out_ch = filters * self.expansion
        if stride != 1 or in_channels != out_ch:
            self.proj: Optional[Conv2D] = Conv2D(out_ch, 1, strides=stride,
                                                 use_bias=False)
            self.bn_proj: Optional[BatchNorm] = BatchNorm()
        else:
            self.proj = None
            self.bn_proj = None

    def _parts(self):
        parts = [("conv1", self.conv1), ("bn1", self.bn1),
                 ("conv2", self.conv2), ("bn2", self.bn2),
                 ("conv3", self.conv3), ("bn3", self.bn3)]
        if self.proj is not None:
            parts += [("proj", self.proj), ("bn_proj", self.bn_proj)]
        return parts

    def init(self, key, in_shape):
        params, state = {}, {}
        shape = tuple(in_shape)
        keys = jax.random.split(key, 8)
        shapes = {"conv1": shape}
        shapes["bn1"] = self.conv1.out_shape(shape)
        shapes["conv2"] = shapes["bn1"]
        shapes["bn2"] = self.conv2.out_shape(shapes["conv2"])
        shapes["conv3"] = shapes["bn2"]
        shapes["bn3"] = self.conv3.out_shape(shapes["conv3"])
        shapes["proj"] = shape
        shapes["bn_proj"] = shapes["bn3"]
        for k_, (name, layer) in zip(keys, self._parts()):
            p, s = layer.init(k_, shapes[name])
            if p:
                params[name] = p
            if s:
                state[name] = s
        return params, state

    def out_shape(self, in_shape):
        return self.conv3.out_shape(
            self.conv2.out_shape(self.conv1.out_shape(in_shape)))

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)

        def run(name, layer, h):
            out, s = layer.apply(params.get(name, {}), state.get(name, {}), h,
                                 train=train, rng=None)
            if s:
                new_state[name] = s
            return out

        h = jax.nn.relu(run("bn1", self.bn1, run("conv1", self.conv1, x)))
        h = jax.nn.relu(run("bn2", self.bn2, run("conv2", self.conv2, h)))
        h = run("bn3", self.bn3, run("conv3", self.conv3, h))
        shortcut = x
        if self.proj is not None:
            shortcut = run("bn_proj", self.bn_proj,
                           run("proj", self.proj, x))
        return jax.nn.relu(h + shortcut), new_state


class ResNet(Layer):
    """Stage-structured ResNet; ``stages`` = blocks per stage."""

    def __init__(self, stages: Sequence[int], num_classes: int = 1000,
                 stem_stride: int = 2, stem_pool: bool = True,
                 width: int = 64, name: Optional[str] = None):
        super().__init__(name or "resnet")
        self.stem = Conv2D(width, 7 if stem_pool else 3,
                           strides=stem_stride, use_bias=False)
        self.stem_bn = BatchNorm()
        self.stem_pool = stem_pool
        self.blocks = []
        filters = width
        in_channels = width   # channels coming out of the stem
        for stage_idx, num_blocks in enumerate(stages):
            for block_idx in range(num_blocks):
                stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
                block = _Bottleneck(filters, in_channels, stride)
                self.blocks.append(
                    (f"stage{stage_idx}_block{block_idx}", block))
                in_channels = filters * _Bottleneck.expansion
            filters *= 2
        self.head = Dense(num_classes)
        self.pool = GlobalAvgPool()

    def init(self, key, in_shape):
        params, state = {}, {}
        keys = jax.random.split(key, len(self.blocks) + 3)
        shape = tuple(in_shape)
        p, s = self.stem.init(keys[0], shape)
        params["stem"] = p
        shape = self.stem.out_shape(shape)
        p, s = self.stem_bn.init(keys[1], shape)
        if p:
            params["stem_bn"] = p
        state["stem_bn"] = s
        if self.stem_pool:
            shape = (-(-shape[0] // 2), -(-shape[1] // 2), shape[2])
        for k_, (name, block) in zip(keys[2:-1], self.blocks):
            p, s = block.init(k_, shape)
            params[name] = p
            if s:
                state[name] = s
            shape = block.out_shape(shape)
        p, _ = self.head.init(keys[-1], (shape[-1],))
        params["head"] = p
        return params, state

    def out_shape(self, in_shape):
        return (self.head.units,)

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        h, _ = self.stem.apply(params["stem"], {}, x)
        h, s = self.stem_bn.apply(params.get("stem_bn", {}),
                                  state["stem_bn"], h, train=train)
        new_state["stem_bn"] = s
        h = jax.nn.relu(h)
        if self.stem_pool:
            h = jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        for name, block in self.blocks:
            h, s = block.apply(params[name], state.get(name, {}), h,
                               train=train, rng=None)
            if s:
                new_state[name] = s
        h, _ = self.pool.apply({}, {}, h)
        logits, _ = self.head.apply(params["head"], {}, h)
        return logits, new_state

    @staticmethod
    def partition_rules(fsdp: bool = False) -> PartitionRules:
        f = "fsdp" if fsdp else None
        return PartitionRules([
            # conv kernels [kh, kw, cin, cout]: output channels on tensor
            (r"(conv|proj|stem).*kernel", P(None, None, f, "tensor")),
            (r"head/kernel", P(f, "tensor")),
        ])


def resnet50(num_classes: int = 1000) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes)


def resnet18(num_classes: int = 1000) -> ResNet:
    # (kept bottleneck-based for uniformity; depth-equivalent small net)
    return ResNet([2, 2, 2, 2], num_classes)


def resnet_cifar(num_classes: int = 10) -> ResNet:
    """3x3 stem, no maxpool — the standard CIFAR variant."""
    return ResNet([2, 2, 2], num_classes, stem_stride=1, stem_pool=False,
                  width=32)
