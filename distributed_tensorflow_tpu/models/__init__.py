"""Model zoo + high-level Sequential/compile/fit API."""

from . import bert, callbacks, gpt, llama, resnet, saving, seq2seq, vit, zoo
from .saving import load_model, save_model
from .vit import ViT, ViTConfig, vit_base, vit_tiny
from .bert import Bert, BertConfig, bert_base, bert_tiny
from .gpt import GPT, GPTConfig, gpt_small, gpt_tiny
from .speculative import generate_speculative
from .llama import llama_config, llama_tiny, llama2_7b, llama3_8b
from .seq2seq import Seq2Seq, Seq2SeqConfig, seq2seq_tiny
from .callbacks import (Callback, CSVLogger, EarlyStopping, History,
                        LambdaCallback, LearningRateScheduler,
                        ModelCheckpoint, ReduceLROnPlateau, TensorBoard,
                        TerminateOnNaN)
from .resnet import ResNet, resnet18, resnet50, resnet_cifar
from .sequential import Sequential
from .zoo import cifar_cnn, mnist_mlp, xor_mlp

__all__ = ["bert", "callbacks", "gpt", "llama", "resnet", "saving",
           "seq2seq", "vit",
           "zoo", "load_model", "save_model",
           "ViT", "ViTConfig", "vit_base", "vit_tiny",
           "Bert", "BertConfig",
           "GPT", "GPTConfig", "gpt_small", "gpt_tiny",
           "generate_speculative",
           "llama_config", "llama_tiny", "llama2_7b", "llama3_8b",
           "bert_base", "bert_tiny", "Seq2Seq", "Seq2SeqConfig", "seq2seq_tiny",
           "Callback", "CSVLogger", "EarlyStopping", "History",
           "LambdaCallback", "LearningRateScheduler", "ModelCheckpoint",
           "ReduceLROnPlateau", "TerminateOnNaN",
           "TensorBoard", "ResNet", "resnet18", "resnet50", "resnet_cifar",
           "Sequential", "cifar_cnn", "mnist_mlp", "xor_mlp"]
