"""Model zoo + high-level Sequential/compile/fit API."""

from . import callbacks
from .callbacks import Callback, EarlyStopping, History, TensorBoard
from .sequential import Sequential

__all__ = ["callbacks", "Callback", "EarlyStopping", "History",
           "TensorBoard", "Sequential"]
