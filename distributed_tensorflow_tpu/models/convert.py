"""Checkpoint interop: Hugging Face transformers -> this framework.

The reference has no pretrained-weight story (its model is a from-scratch
MLP, reference example.py:149-155); this module is the usability bridge the
TPU model zoo needs — load a GPT-2 checkpoint trained elsewhere and run it
under this framework's pjit/pipeline/KV-cache machinery.

Design: converters take an ALREADY-CONSTRUCTED ``transformers`` model (or
its ``state_dict``), not a hub name — no network access is assumed or
performed here; fetch/cache is the caller's concern.  The mapping is exact:
GPT-2 is pre-LN with tanh-approximate GELU ("gelu_new") and a tied LM head,
which is precisely this repo's ``GPT`` architecture
(``models/gpt.py``), so converted logits match the torch forward to float
tolerance (tests/test_convert.py).

HF GPT-2 layout facts the mapping relies on:
  * ``Conv1D`` stores weights **[in, out]** (unlike ``nn.Linear``), so
    kernels land in our [in, ...out] layout with NO transpose;
  * ``attn.c_attn`` fuses q|k|v on the output dim ([d, 3d]);
  * per-head reshape is ``[d] -> [heads, head_dim]`` in both frameworks;
  * ``lm_head.weight`` is the wte matrix (tied) — our ``GPT.logits``
    reuses ``embeddings/word`` the same way.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .bert import Bert, BertConfig
from .gpt import GPT, GPTConfig
from .vit import ViT, ViTConfig

__all__ = ["gpt2_config_from_hf", "gpt2_params_from_hf", "gpt2_from_hf",
           "bert_config_from_hf", "bert_params_from_hf", "bert_from_hf",
           "vit_config_from_hf", "vit_params_from_hf", "vit_from_hf",
           "llama_config_from_hf", "llama_params_from_hf", "llama_from_hf"]


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        # .float() first: torch's .numpy() rejects BFloat16 (common for
        # torch_dtype=bfloat16 checkpoints), and every weight becomes
        # f32 on our side anyway
        return t.detach().cpu().float().numpy()
    return np.asarray(t)


def _ln_of(sd, prefix):
    """HF LayerNorm {weight, bias} -> this repo's {gamma, beta}."""
    return {"gamma": jnp.asarray(_np(sd[f"{prefix}.weight"]), jnp.float32),
            "beta": jnp.asarray(_np(sd[f"{prefix}.bias"]), jnp.float32)}


def _stack_layers(layers):
    """Per-layer trees -> one tree with the scanned leading layer axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def gpt2_config_from_hf(hf_config) -> GPTConfig:
    """Map a ``transformers.GPT2Config`` onto ``GPTConfig``."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"GPT-2 activation {act!r} unsupported: this zoo's FFN is "
            "tanh-approximate GELU (gelu_new), the GPT-2 default")
    if not getattr(hf_config, "scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False is unsupported")
    if getattr(hf_config, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx is unsupported: "
                         "this attention never divides logits by the "
                         "layer index")
    if getattr(hf_config, "reorder_and_upcast_attn", False):
        raise ValueError("reorder_and_upcast_attn is unsupported")
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        intermediate_size=(hf_config.n_inner or 4 * hf_config.n_embd),
        max_position=hf_config.n_positions,
        dropout_rate=float(hf_config.resid_pdrop),
        layer_norm_eps=float(hf_config.layer_norm_epsilon),
        position_embedding="learned",
    )


def gpt2_params_from_hf(state_dict: Dict[str, Any],
                        config: GPTConfig) -> Dict[str, Any]:
    """Convert a GPT-2 ``state_dict`` (GPT2Model or GPT2LMHeadModel) into
    this framework's stacked-decoder param tree."""
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    d, h = config.hidden_size, config.num_heads
    hd = config.head_dim
    L = config.num_layers

    def ln(prefix):
        return _ln_of(sd, prefix)

    def layer(i):
        cattn_w = _np(sd[f"h.{i}.attn.c_attn.weight"])   # [d, 3d], in-out
        cattn_b = _np(sd[f"h.{i}.attn.c_attn.bias"])     # [3d]
        qw, kw, vw = np.split(cattn_w, 3, axis=1)
        qb, kb, vb = np.split(cattn_b, 3, axis=0)

        def qkv(w, b):
            return {"kernel": jnp.asarray(w.reshape(d, h, hd), jnp.float32),
                    "bias": jnp.asarray(b.reshape(h, hd), jnp.float32)}

        return {
            "ln_1": ln(f"h.{i}.ln_1"),
            "attention": {
                "query": qkv(qw, qb),
                "key": qkv(kw, kb),
                "value": qkv(vw, vb),
                "out": {"kernel": jnp.asarray(
                            _np(sd[f"h.{i}.attn.c_proj.weight"]
                                ).reshape(h, hd, d), jnp.float32),
                        "bias": jnp.asarray(
                            _np(sd[f"h.{i}.attn.c_proj.bias"]),
                            jnp.float32)},
            },
            "ln_2": ln(f"h.{i}.ln_2"),
            "ffn": {
                "w_in": {"kernel": jnp.asarray(
                             _np(sd[f"h.{i}.mlp.c_fc.weight"]), jnp.float32),
                         "bias": jnp.asarray(
                             _np(sd[f"h.{i}.mlp.c_fc.bias"]), jnp.float32)},
                "w_out": {"kernel": jnp.asarray(
                              _np(sd[f"h.{i}.mlp.c_proj.weight"]),
                              jnp.float32),
                          "bias": jnp.asarray(
                              _np(sd[f"h.{i}.mlp.c_proj.bias"]),
                              jnp.float32)},
            },
        }

    decoder = _stack_layers([layer(i) for i in range(L)])
    return {
        "embeddings": {
            "word": jnp.asarray(_np(sd["wte.weight"]), jnp.float32),
            "position": jnp.asarray(_np(sd["wpe.weight"]), jnp.float32),
        },
        "decoder": decoder,
        "ln_f": ln("ln_f"),
    }


def bert_config_from_hf(hf_config) -> BertConfig:
    """Map a ``transformers.BertConfig`` onto ``BertConfig``.  HF BERT
    checkpoints use the EXACT (erf) GELU — ``hidden_act="gelu"`` threads
    that through the FFN and MLM transform."""
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "relu"):
        raise ValueError(f"BERT hidden_act {act!r} unsupported")
    pos = getattr(hf_config, "position_embedding_type", "absolute")
    if pos != "absolute":
        raise ValueError(
            f"position_embedding_type {pos!r} unsupported: this Bert "
            "implements absolute positions only — a relative-position "
            "checkpoint would convert silently wrong")
    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        dropout_rate=float(hf_config.hidden_dropout_prob),
        layer_norm_eps=float(hf_config.layer_norm_eps),
        hidden_act=act,
    )


def bert_params_from_hf(state_dict: Dict[str, Any],
                        config: BertConfig) -> Dict[str, Any]:
    """Convert a BertModel / BertForMaskedLM ``state_dict``.

    HF BERT uses ``nn.Linear`` ([out, in] weights — transposed into this
    repo's [in, out] kernels, unlike GPT-2's Conv1D).  The pooler and the
    MLM head (transform + LayerNorm + tied decoder + output bias) convert
    when present; missing heads fall back to fresh zeros-free init shapes
    being ABSENT from the tree (Bert.init adds them — slice what you need).
    """
    sd = {k.removeprefix("bert."): v for k, v in state_dict.items()}
    d, h = config.hidden_size, config.num_heads
    hd = config.head_dim
    L = config.num_layers

    def ln(prefix):
        return _ln_of(sd, prefix)

    def linear_t(prefix):
        """nn.Linear [out, in] -> kernel [in, out]."""
        return (_np(sd[f"{prefix}.weight"]).T,
                _np(sd[f"{prefix}.bias"]))

    def layer(i):
        base = f"encoder.layer.{i}"

        def qkv(name):
            w, b = linear_t(f"{base}.attention.self.{name}")
            return {"kernel": jnp.asarray(w.reshape(d, h, hd), jnp.float32),
                    "bias": jnp.asarray(b.reshape(h, hd), jnp.float32)}

        ow, ob = linear_t(f"{base}.attention.output.dense")
        iw, ib = linear_t(f"{base}.intermediate.dense")
        fw, fb = linear_t(f"{base}.output.dense")
        return {
            "attention": {
                "query": qkv("query"),
                "key": qkv("key"),
                "value": qkv("value"),
                "out": {"kernel": jnp.asarray(ow.reshape(h, hd, d),
                                              jnp.float32),
                        "bias": jnp.asarray(ob, jnp.float32)},
                "ln": ln(f"{base}.attention.output.LayerNorm"),
            },
            "ffn": {
                "w_in": {"kernel": jnp.asarray(iw, jnp.float32),
                         "bias": jnp.asarray(ib, jnp.float32)},
                "w_out": {"kernel": jnp.asarray(fw, jnp.float32),
                          "bias": jnp.asarray(fb, jnp.float32)},
                "ln": ln(f"{base}.output.LayerNorm"),
            },
        }

    params: Dict[str, Any] = {
        "embeddings": {
            "word": jnp.asarray(
                _np(sd["embeddings.word_embeddings.weight"]), jnp.float32),
            "position": jnp.asarray(
                _np(sd["embeddings.position_embeddings.weight"]),
                jnp.float32),
            "type": jnp.asarray(
                _np(sd["embeddings.token_type_embeddings.weight"]),
                jnp.float32),
            "ln": ln("embeddings.LayerNorm"),
        },
        "encoder": _stack_layers([layer(i) for i in range(L)]),
    }
    if "pooler.dense.weight" in sd:
        pw, pb = linear_t("pooler.dense")
        params["pooler"] = {"kernel": jnp.asarray(pw, jnp.float32),
                            "bias": jnp.asarray(pb, jnp.float32)}
    if "cls.predictions.transform.dense.weight" in state_dict:
        tw, tb = (_np(state_dict["cls.predictions.transform.dense.weight"]).T,
                  _np(state_dict["cls.predictions.transform.dense.bias"]))
        params["mlm"] = {
            "transform": {"kernel": jnp.asarray(tw, jnp.float32),
                          "bias": jnp.asarray(tb, jnp.float32)},
            "ln": {"gamma": jnp.asarray(_np(state_dict[
                       "cls.predictions.transform.LayerNorm.weight"]),
                       jnp.float32),
                   "beta": jnp.asarray(_np(state_dict[
                       "cls.predictions.transform.LayerNorm.bias"]),
                       jnp.float32)},
            "output_bias": jnp.asarray(
                _np(state_dict["cls.predictions.bias"]), jnp.float32),
        }
    return params


def bert_from_hf(hf_model, mesh=None) -> Tuple[Bert, Dict[str, Any]]:
    """(Bert, params) from a ``transformers`` BertModel / BertForMaskedLM
    instance — sequence outputs, pooled head, and MLM logits match the
    torch forward (tests/test_convert.py)."""
    config = bert_config_from_hf(hf_model.config)
    model = Bert(config, mesh=mesh)
    params = bert_params_from_hf(hf_model.state_dict(), config)
    return model, params


def vit_config_from_hf(hf_config, num_classes: int) -> ViTConfig:
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_approx"):
        raise ValueError(f"ViT hidden_act {act!r} unsupported")
    if not getattr(hf_config, "qkv_bias", True):
        raise ValueError("qkv_bias=False is unsupported: this zoo's "
                         "attention projections always carry biases")
    return ViTConfig(
        image_size=hf_config.image_size,
        patch_size=hf_config.patch_size,
        channels=hf_config.num_channels,
        num_classes=num_classes,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        dropout_rate=float(hf_config.hidden_dropout_prob),
        layer_norm_eps=float(hf_config.layer_norm_eps),
        hidden_act=act,
    )


def vit_params_from_hf(state_dict: Dict[str, Any],
                       config: ViTConfig) -> Dict[str, Any]:
    """Convert a ViTModel / ViTForImageClassification ``state_dict``.

    The patch projection is a torch conv ([out, in, kh, kw]) transposed to
    the HWIO layout of ``lax.conv_general_dilated``; encoder layers are
    BERT-style ``nn.Linear`` transposes with pre-LN naming
    (``layernorm_before``/``after``).  A ``classifier`` head maps onto the
    classification head when present; otherwise the head is zero-init and
    ``apply(return_features=True)`` is the parity surface.
    """
    sd = {k.removeprefix("vit."): v for k, v in state_dict.items()}
    d, h = config.hidden_size, config.num_heads
    hd = config.head_dim
    L = config.num_layers

    def ln(prefix):
        return _ln_of(sd, prefix)

    def linear_t(prefix):
        return (_np(sd[f"{prefix}.weight"]).T, _np(sd[f"{prefix}.bias"]))

    def layer(i):
        base = f"encoder.layer.{i}"

        def qkv(name):
            w, b = linear_t(f"{base}.attention.attention.{name}")
            return {"kernel": jnp.asarray(w.reshape(d, h, hd), jnp.float32),
                    "bias": jnp.asarray(b.reshape(h, hd), jnp.float32)}

        ow, ob = linear_t(f"{base}.attention.output.dense")
        iw, ib = linear_t(f"{base}.intermediate.dense")
        fw, fb = linear_t(f"{base}.output.dense")
        return {
            "attention": {
                "query": qkv("query"), "key": qkv("key"),
                "value": qkv("value"),
                "out": {"kernel": jnp.asarray(ow.reshape(h, hd, d),
                                              jnp.float32),
                        "bias": jnp.asarray(ob, jnp.float32)},
                "ln": ln(f"{base}.layernorm_before"),
            },
            "ffn": {
                "w_in": {"kernel": jnp.asarray(iw, jnp.float32),
                         "bias": jnp.asarray(ib, jnp.float32)},
                "w_out": {"kernel": jnp.asarray(fw, jnp.float32),
                          "bias": jnp.asarray(fb, jnp.float32)},
                "ln": ln(f"{base}.layernorm_after"),
            },
        }

    proj = _np(sd["embeddings.patch_embeddings.projection.weight"])
    params: Dict[str, Any] = {
        "patch_embed": {
            # torch conv [out, in, kh, kw] -> HWIO [kh, kw, in, out]
            "kernel": jnp.asarray(proj.transpose(2, 3, 1, 0), jnp.float32),
            "bias": jnp.asarray(
                _np(sd["embeddings.patch_embeddings.projection.bias"]),
                jnp.float32),
        },
        "cls_token": jnp.asarray(_np(sd["embeddings.cls_token"]),
                                 jnp.float32),
        "pos_embed": jnp.asarray(_np(sd["embeddings.position_embeddings"]),
                                 jnp.float32),
        "encoder": _stack_layers([layer(i) for i in range(L)]),
        "final_ln": ln("layernorm"),
    }
    if "classifier.weight" in state_dict:
        cw, cb = (_np(state_dict["classifier.weight"]).T,
                  _np(state_dict["classifier.bias"]))
        params["head"] = {"kernel": jnp.asarray(cw, jnp.float32),
                          "bias": jnp.asarray(cb, jnp.float32)}
    else:
        params["head"] = {
            "kernel": jnp.zeros((d, config.num_classes), jnp.float32),
            "bias": jnp.zeros((config.num_classes,), jnp.float32)}
    return params


def vit_from_hf(hf_model, mesh=None) -> Tuple[ViT, Dict[str, Any]]:
    """(ViT, params) from a ``transformers`` ViTModel /
    ViTForImageClassification instance.  Features (and, with a classifier,
    logits) match the torch forward; images are NHWC here vs torch NCHW."""
    del mesh  # ViT carries no mesh state; kept for signature symmetry
    n_classes = getattr(getattr(hf_model, "config", None), "num_labels", 0)
    if not hasattr(hf_model, "classifier"):
        n_classes = max(int(n_classes or 0), 1)
    config = vit_config_from_hf(hf_model.config, num_classes=n_classes)
    model = ViT(config)
    params = vit_params_from_hf(hf_model.state_dict(), config)
    return model, params


def gpt2_from_hf(hf_model, mesh=None) -> Tuple[GPT, Dict[str, Any]]:
    """(GPT, params) from a ``transformers`` GPT2Model / GPT2LMHeadModel
    instance.  The returned model runs everything the zoo offers —
    jit/pjit forward, ``lm_loss_fn`` fine-tuning, KV-cache ``generate`` /
    ``beam_search`` — with logits matching the torch forward."""
    config = gpt2_config_from_hf(hf_model.config)
    model = GPT(config, mesh=mesh)
    params = gpt2_params_from_hf(hf_model.state_dict(), config)
    return model, params


def llama_config_from_hf(hf_config) -> GPTConfig:
    """Map a ``transformers.LlamaConfig`` onto the Llama recipe of
    ``GPTConfig`` (models/llama.py)."""
    from .llama import llama_config
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(f"Llama hidden_act {act!r} unsupported: the "
                         "swiglu FFN gate is silu")
    if getattr(hf_config, "attention_bias", False) or \
            getattr(hf_config, "mlp_bias", False):
        raise ValueError("attention_bias/mlp_bias checkpoints are "
                         "unsupported: the Llama recipe is bias-free")
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        raise ValueError(f"rope_scaling {scaling!r} unsupported: plain "
                         "rotate-half RoPE only")
    head_dim = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    if head_dim is not None and head_dim != derived:
        # reject here with the field named, not later with a bare reshape
        # error inside llama_params_from_hf
        raise ValueError(
            f"explicit head_dim {head_dim} != hidden_size//num_heads "
            f"{derived} unsupported: GPTConfig derives head_dim")
    return llama_config(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        intermediate_size=hf_config.intermediate_size,
        max_position=hf_config.max_position_embeddings,
        layer_norm_eps=hf_config.rms_norm_eps,
        rope_base=getattr(hf_config, "rope_theta", 10000.0),
        tied_head=bool(getattr(hf_config, "tie_word_embeddings", False)),
    )


def llama_params_from_hf(state_dict: Dict[str, Any],
                         config: GPTConfig) -> Dict[str, Any]:
    """Convert a Llama ``state_dict`` (LlamaModel or LlamaForCausalLM,
    HF-format weights) into the stacked-decoder param tree.

    Layout facts: ``nn.Linear`` weights are [out, in] (transpose to land
    in our [in, ...out] kernels); q/k/v out dims are head-major, matching
    our [d, heads, head_dim] reshape; HF-format checkpoints already use
    the rotate-half RoPE convention of ``ops.attention.apply_rope``."""
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    d, h = config.hidden_size, config.num_heads
    hd, kv = config.head_dim, config.kv_heads
    L = config.num_layers

    def rms(prefix):
        return {"gamma": jnp.asarray(_np(sd[f"{prefix}.weight"]),
                                     jnp.float32)}

    def lin_t(prefix, shape):
        return {"kernel": jnp.asarray(
            _np(sd[f"{prefix}.weight"]).T.reshape(shape), jnp.float32)}

    def layer(i):
        p = f"layers.{i}"
        return {
            "ln_1": rms(f"{p}.input_layernorm"),
            "attention": {
                "query": lin_t(f"{p}.self_attn.q_proj", (d, h, hd)),
                "key": lin_t(f"{p}.self_attn.k_proj", (d, kv, hd)),
                "value": lin_t(f"{p}.self_attn.v_proj", (d, kv, hd)),
                # out kernel is [h, hd, d]: o_proj.weight [d, h*hd] -> .T
                # is [h*hd, d], reshaped head-major
                "out": {"kernel": jnp.asarray(
                    _np(sd[f"{p}.self_attn.o_proj.weight"]).T.reshape(
                        h, hd, d), jnp.float32)},
            },
            "ln_2": rms(f"{p}.post_attention_layernorm"),
            "ffn": {
                "w_in": lin_t(f"{p}.mlp.up_proj", (d, -1)),
                "w_gate": lin_t(f"{p}.mlp.gate_proj", (d, -1)),
                "w_out": lin_t(f"{p}.mlp.down_proj", (-1, d)),
            },
        }

    params = {
        "embeddings": {
            "word": jnp.asarray(_np(sd["embed_tokens.weight"]),
                                jnp.float32),
        },
        "decoder": _stack_layers([layer(i) for i in range(L)]),
        "ln_f": rms("norm"),
    }
    if not config.tied_head:
        # LlamaModel state_dicts lack the head; LlamaForCausalLM has it
        # (tie_word_embeddings checkpoints alias it to embed_tokens)
        head = state_dict.get("lm_head.weight")
        if head is None:
            raise ValueError(
                "state_dict has no lm_head.weight (a bare LlamaModel?) — "
                "convert from LlamaForCausalLM, or set tied_head=True")
        params["lm_head"] = jnp.asarray(_np(head), jnp.float32)
    return params


def llama_from_hf(hf_model, mesh=None) -> Tuple[GPT, Dict[str, Any]]:
    """(GPT, params) from a ``transformers`` LlamaModel / LlamaForCausalLM
    instance — the zoo's full decoder surface (pjit/TP, KV-cache
    generate/beam_search, GQA cache) with logits matching torch."""
    config = llama_config_from_hf(hf_model.config)
    model = GPT(config, mesh=mesh)
    params = llama_params_from_hf(hf_model.state_dict(), config)
    return model, params
