"""Sequential — the high-level ``compile``/``fit`` tier.

Capability parity with the reference's Keras path (reference
example2.py:148-200): ``Sequential`` container, ``add``, ``compile(loss,
optimizer, metrics)``, ``fit(x, y, epochs, batch_size, validation_data,
callbacks)``, ``evaluate``, ``predict`` — re-built on the framework's own
compiled steps (no session binding: where the reference must smuggle the
monitored session into Keras via ``K.set_session`` at example2.py:194-195,
here ``fit`` simply drives the same jitted step the low-level API uses).

Distribution: pass ``mesh=`` at compile time and the whole fit loop runs
data-parallel over the mesh's ``data`` axis with batches prefetched to
device already sharded — the high-level user never sees a collective.
"""
from __future__ import annotations

import collections
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.pipeline import Dataset, prefetch_to_device
from ..ops import layers as layer_lib
from ..ops import losses as loss_lib
from ..ops import metrics as metric_lib
from ..optim import optimizers as opt_lib
from ..train import step as step_lib
from ..train.session import TrainState
from .callbacks import Callback, History

log = logging.getLogger(__name__)

__all__ = ["Sequential"]


def _group_batches(it, spe: int, active: bool):
    """K-stack consecutive same-shaped batches for the multi-step path;
    a count-tail shorter than ``spe`` falls through as single batches.
    Runs on the prefetch producer thread."""
    if not active or spe <= 1:
        yield from it
        return
    buf = []
    for b in it:
        # A ragged batch (e.g. a drop_remainder=False tail) can't be
        # stacked with its neighbours; flush the buffer as single batches
        # instead of letting np.stack raise an opaque ValueError from
        # inside the producer thread.
        if buf and any(x.shape != y.shape for x, y in zip(b, buf[0])):
            yield from buf
            buf = []
        buf.append(b)
        if len(buf) == spe:
            yield tuple(np.stack(z) for z in zip(*buf))
            buf = []
    yield from buf


def _stream_shardings(mesh, base_ndim, want_multi: bool):
    """(per-batch sharding, sharding_fn) for prefetch_to_device — the fn
    routes [K, batch, ...] groups to P(None, 'data') and plain batches to
    P('data')."""
    if mesh is None:
        return None, None
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    if not want_multi:
        return sharding, None
    multi = NamedSharding(mesh, PartitionSpec(None, "data"))

    def fn(item):
        return multi if item[0].ndim > base_ndim else sharding

    return sharding, fn


def _sync_every(mesh) -> int:
    """Metric-pull cadence: XLA:CPU's collective rendezvous dies under a
    deep async queue, so the CPU mesh syncs every dispatch; TPU pulls
    rarely and keeps the queue async."""
    return (1 if jax.devices()[0].platform == "cpu" and mesh is not None
            else 50)


class _MeanAccumulator:
    """Exact epoch mean of step metrics with no per-batch host pulls:
    every dispatch's scalars (or the [K] vector of a multi-step group)
    are summed into a DEVICE-resident running total — a couple of tiny
    async dispatches per step — and the host pulls once, at epoch end.
    Replaces the round-3 sampled mean (every ~50th dispatch on TPU),
    which made History a sample rather than the Keras mean over all
    batches.  ``block()`` is the queue-depth bound: called at the sync
    cadence it waits for the running total (and therefore every chained
    step before it) without transferring anything."""

    def __init__(self):
        self.sums: Dict[str, Any] = {}
        self.counts: Dict[str, int] = {}

    def add(self, metrics: Dict[str, Any]) -> None:
        for k, v in metrics.items():
            s = jnp.sum(jnp.asarray(v), dtype=jnp.float32)
            prev = self.sums.get(k)
            self.sums[k] = s if prev is None else prev + s
            self.counts[k] = (self.counts.get(k, 0)
                              + int(np.prod(np.shape(v)) or 1))

    def block(self) -> None:
        for v in self.sums.values():
            jax.block_until_ready(v)
            break

    def means(self) -> Dict[str, float]:
        return {k: float(self.sums[k]) / self.counts[k] for k in self.sums}


class Sequential:
    def __init__(self, layers: Sequence[layer_lib.Layer] = (),
                 name: str = "sequential"):
        self.name = name
        self._layers: List[layer_lib.Layer] = list(layers)
        self._stack: Optional[layer_lib.Stack] = None
        self.state: Optional[TrainState] = None
        self.stop_training = False
        self._compiled = None
        self._compile_config = None   # JSON-able compile args (for save)
        self._in_shape = None         # recorded at build (for load)

    # -- construction ----------------------------------------------------
    def add(self, layer: layer_lib.Layer) -> None:
        """reference example2.py:151-156 ``model.add`` parity."""
        self._layers.append(layer)
        self._stack = None
        self._compiled = None

    @property
    def stack(self) -> layer_lib.Stack:
        if self._stack is None:
            self._stack = layer_lib.Stack(self._layers, name=self.name)
        return self._stack

    @property
    def layers(self) -> List[layer_lib.Layer]:
        """Ordered layer list (Keras ``model.layers`` parity); consumed by
        ``summary.model_graph_nodes`` for the TB graph event."""
        return self._layers

    # -- compile ---------------------------------------------------------
    def compile(self, loss, optimizer="adam",
                metrics: Sequence = (),
                mesh=None, params_spec=None, seed: int = 0,
                grad_clip_norm: Optional[float] = None,
                policy=None, steps_per_execution: int = 1,
                grad_accum_steps: int = 1) -> None:
        """reference example2.py:165 parity: strings or callables/objects.

        ``policy``: mixed-precision spec (e.g. ``"mixed_bfloat16"``) applied
        to both the train and eval steps — see train/precision.py.

        ``steps_per_execution``: run K optimizer updates per compiled
        dispatch (``lax.scan`` inside the step — train/step.py's
        make_multi_train_step).  Each dispatch pays one host→device round
        trip, tens of ms over a TPU tunnel; for small models that latency
        dominates (bench.py measured 5.6x on the MNIST MLP at K=64).
        Update semantics are IDENTICAL to K single steps — the scan body
        is the single-step function — and epoch-boundary callbacks are
        unaffected (this fit has no per-batch callbacks).  Epoch tails
        shorter than K fall back to the single-step path.  fit() with
        ``sample_weight``/``class_weight`` ignores it (those compile
        dedicated single-step programs) — a one-line log says so.

        ``grad_accum_steps``: split each batch into that many microbatches
        inside the step (train/step.py gradient accumulation): ONE
        optimizer update from the averaged gradients, peak activation
        memory down ~accum-fold — the HBM lever when the target batch
        doesn't fit.  Requires ``fit(batch_size=...)`` divisible by it;
        composes with ``steps_per_execution``.
        """
        loss_fn = loss_lib.get(loss)
        # with_lr_scale: LearningRateScheduler / ReduceLROnPlateau mutate a
        # device scalar in opt_state between steps — no recompilation.
        opt = opt_lib.with_lr_scale(opt_lib.get(optimizer))
        metric_fns = {}
        for m in metrics:
            fn = metric_lib.get(m)
            metric_fns[getattr(fn, "__name__", str(m))] = fn
        # ONE kwargs dict builds the default step AND any class-weighted
        # sibling fit() compiles later — they can never drift apart.
        if grad_accum_steps < 1:
            raise ValueError(
                f"grad_accum_steps must be >= 1; got {grad_accum_steps}")
        step_kwargs = dict(metric_fns=metric_fns, seed=seed, mesh=mesh,
                           params_spec=params_spec,
                           grad_clip_norm=grad_clip_norm, policy=policy,
                           accum_steps=int(grad_accum_steps))
        if steps_per_execution < 1:
            raise ValueError(
                f"steps_per_execution must be >= 1; got {steps_per_execution}")
        self._compiled = dict(
            loss=loss_fn, optimizer=opt, metric_fns=metric_fns, mesh=mesh,
            loss_name=loss if isinstance(loss, str) else None,
            step_kwargs=step_kwargs,
            weighted_steps={},
            steps_per_execution=int(steps_per_execution),
            multi_train_step=(step_lib.make_multi_train_step(
                self.stack, loss_fn, opt,
                steps_per_call=int(steps_per_execution), **step_kwargs)
                if steps_per_execution > 1 else None),
            train_step=step_lib.make_train_step(
                self.stack, loss_fn, opt, **step_kwargs),
            eval_step=step_lib.make_eval_step(
                self.stack, loss_fn, metric_fns=metric_fns, mesh=mesh,
                policy=policy),
        )
        # Record the compile call for model.save when every piece is a
        # JSON-able registry name (a mesh or callable can't round-trip).
        serializable = (isinstance(loss, str) and isinstance(optimizer, str)
                        and all(isinstance(m, str) for m in metrics)
                        and (policy is None or isinstance(policy, str))
                        and mesh is None and params_spec is None)
        self._compile_config = dict(
            loss=loss, optimizer=optimizer, metrics=list(metrics),
            seed=seed, grad_clip_norm=grad_clip_norm, policy=policy,
            steps_per_execution=int(steps_per_execution),
            grad_accum_steps=int(grad_accum_steps)
        ) if serializable else None
        # Recompile keeps the weights but resets the optimizer state for
        # the new optimizer (Keras recompile semantics) — also what lets
        # load_model restore weights before the user's own compile().
        if self.state is not None:
            self.state = self.state._replace(
                opt_state=opt.init(self.state.params))

    def _require_compiled(self) -> dict:
        if self._compiled is None:
            raise RuntimeError("call model.compile(...) before fit/evaluate")
        return self._compiled

    def build(self, in_shape: Tuple[int, ...], seed: int = 0) -> TrainState:
        """Initialize parameters for per-example feature shape ``in_shape``."""
        c = self._require_compiled()
        key = jax.random.PRNGKey(seed)
        self._in_shape = tuple(int(d) for d in in_shape)
        self.state = step_lib.init_train_state(self.stack, c["optimizer"],
                                               key, in_shape)
        if c["mesh"] is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(c["mesh"], PartitionSpec())
            self.state = jax.device_put(self.state, replicated)
        return self.state

    # -- training --------------------------------------------------------
    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            validation_data: Optional[Tuple] = None,
            validation_split: float = 0.0,
            callbacks: Sequence[Callback] = (),
            shuffle: bool = True, seed: int = 0,
            verbose: int = 1, augment=None,
            class_weight=None, sample_weight=None) -> History:
        """reference example2.py:197-200 parity (sync-DP underneath).

        ``augment``: per-batch transform from ``data.augment`` (host-side,
        overlapped with device compute via the prefetch queue); applied to
        training batches only, never to validation.

        ``validation_split``: fraction (0, 1) held out from the END of
        ``(x, y)`` before shuffling (Keras semantics) when no explicit
        ``validation_data`` is given.

        Epoch ``logs``/History values are the SAMPLED running mean of
        compiled-step metrics — every dispatch pulled at a sync point
        contributes (all K of a multi-step group).  Pulling every batch
        would stall the async dispatch queue, so on TPU the mean samples
        every ~50th dispatch; on the CPU mesh (sync_every=1) it is exactly
        Keras's epoch mean of batch metrics.  Exact full-data means are
        available via ``evaluate()``.

        ``class_weight``: {class_id: weight} applied to the TRAINING loss
        (Keras semantics; validation stays unweighted).  Requires a
        string classification loss (see ``ops.losses.class_weighted``);
        each distinct weighting compiles its own step once and is cached.

        ``sample_weight``: per-sample float array [n] weighting the
        TRAINING loss with Keras 2.0.8's exact normalization
        (``sum(loss_i * w_i) / count_nonzero(w)`` — the
        ``weighted_masked_objective`` rule the reference's ``model.fit``
        applies, reference example2.py:200).  The weights ride the batch
        tuple through ONE compiled weighted step (no recompile per call);
        shuffling/sharding stay aligned with (x, y).  Assumes a loss whose
        batch value is the mean of independent per-sample terms (true of
        every registry loss).  Divergences from Keras 2.0.8, by design:
        metrics stay unweighted, and combining with ``class_weight``
        raises instead of silently preferring ``sample_weight``.
        """
        c = self._require_compiled()
        train_step = c["train_step"]
        accum = c["step_kwargs"].get("accum_steps", 1)
        if accum > 1 and (sample_weight is not None
                          or class_weight is not None):
            # per-microbatch weighted means averaged equally are NOT the
            # full-batch weighted mean when the weight mass differs per
            # microbatch — refuse rather than silently bias gradients
            raise ValueError(
                "grad_accum_steps > 1 composes only with the unweighted "
                "loss path; drop sample_weight/class_weight or recompile "
                "with grad_accum_steps=1")
        if sample_weight is not None:
            if class_weight is not None:
                raise ValueError(
                    "pass either sample_weight or class_weight, not both "
                    "(Keras 2.0.8 silently ignored class_weight here; "
                    "refusing is safer)")
            sample_weight = np.asarray(sample_weight, np.float32)
            if sample_weight.shape != (int(np.shape(x)[0]),):
                raise ValueError(
                    f"sample_weight shape {sample_weight.shape} != "
                    f"({int(np.shape(x)[0])},) — one float per sample")
            train_step = self._sample_weighted_step(c)
        if class_weight is not None:
            if c["loss_name"] is None:
                raise ValueError("class_weight needs the model compiled "
                                 "with a loss NAME (string), not a callable")
            key_cw = tuple(sorted((int(k), float(v))
                                  for k, v in class_weight.items()))
            if key_cw not in c["weighted_steps"]:
                wfn = loss_lib.class_weighted(c["loss_name"], class_weight)
                c["weighted_steps"][key_cw] = step_lib.make_train_step(
                    self.stack, wfn, c["optimizer"], **c["step_kwargs"])
            train_step = c["weighted_steps"][key_cw]
        if validation_split and validation_data is None:
            if not 0.0 < validation_split < 1.0:
                raise ValueError(
                    f"validation_split must be in (0, 1); got "
                    f"{validation_split}")
            n = int(np.shape(x)[0])
            split = n - max(1, int(n * validation_split))
            x, y = np.asarray(x), np.asarray(y)
            validation_data = (x[split:], y[split:])
            x, y = x[:split], y[:split]
            if sample_weight is not None:   # held-out rows eval unweighted
                sample_weight = sample_weight[:split]
        if self.state is None:
            self.build(tuple(np.shape(x)[1:]), seed=seed)

        history = History()
        callbacks = list(callbacks) + [history]
        self.stop_training = False

        if c["mesh"] is not None:
            from ..parallel.mesh import round_batch_to_mesh
            rounded = round_batch_to_mesh(batch_size, c["mesh"])
            if rounded != batch_size:
                log.info("batch_size %d -> %d (divisible by mesh data shards)",
                         batch_size, rounded)
                batch_size = rounded
        if accum > 1 and batch_size % accum:
            # validated AFTER mesh rounding — the rounded size is what the
            # step actually splits into microbatches
            raise ValueError(
                f"batch_size {batch_size} is not divisible by "
                f"grad_accum_steps {accum}")
        arrays = [np.asarray(x), np.asarray(y)]
        if sample_weight is not None:
            arrays.append(sample_weight)   # shuffles/shards with (x, y)
        dataset = Dataset(arrays, batch_size,
                          shuffle=shuffle, seed=seed, transform=augment)
        sharding = None
        if c["mesh"] is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sharding = NamedSharding(c["mesh"], PartitionSpec("data"))

        # steps_per_execution: scan K updates into one dispatch.  Only the
        # default step has a multi sibling — the weighted paths compile
        # dedicated single-step programs.
        spe = c["steps_per_execution"]
        multi_step = (c["multi_train_step"]
                      if train_step is c["train_step"] else None)
        if spe > 1 and multi_step is None:
            log.info("steps_per_execution=%d ignored for this fit "
                     "(sample_weight/class_weight use their own compiled "
                     "step)", spe)
        base_ndim = arrays[0].ndim   # group leaves carry one extra dim
        _, batch_sharding = _stream_shardings(
            c["mesh"], base_ndim, multi_step is not None)

        for cb in callbacks:
            cb.on_train_begin(self)
        for epoch in range(epochs):
            if self.stop_training:
                break
            for cb in callbacks:
                cb.on_epoch_begin(self, epoch)
            # Exact epoch mean, accumulated on device: every dispatch
            # contributes (a float() per batch would stall the async
            # dispatch queue, so the host pulls once at epoch end); the
            # sync cadence only BLOCKS — bounding queue depth, and on the
            # CPU mesh guarding the collective rendezvous.
            sync_every = _sync_every(c["mesh"])
            acc = _MeanAccumulator()
            last_metrics: Dict[str, Any] = {}
            dispatches = 0
            groups = _group_batches(iter(dataset), spe,
                                    multi_step is not None)
            for batch in prefetch_to_device(groups, sharding=sharding,
                                            sharding_fn=batch_sharding):
                if batch[0].ndim > base_ndim:       # [K, batch, ...] group
                    self.state, last_metrics = multi_step(self.state, batch)
                else:
                    self.state, last_metrics = train_step(self.state, batch)
                dispatches += 1
                acc.add(last_metrics)
                if dispatches % sync_every == 0:
                    acc.block()
            logs = acc.means()
            if validation_data is not None:
                val = self.evaluate(validation_data[0], validation_data[1],
                                    batch_size=batch_size, verbose=0)
                logs.update({f"val_{k}": v for k, v in val.items()})
            if verbose:
                parts = ", ".join(f"{k}={v:.4f}" for k, v in logs.items())
                print(f"Epoch {epoch + 1}/{epochs}: {parts}", flush=True)
            for cb in callbacks:
                cb.on_epoch_end(self, epoch, logs)
        for cb in callbacks:
            cb.on_train_end(self)
        return history

    def fit_stream(self, batches, steps_per_epoch: int, epochs: int = 1,
                   callbacks: Sequence[Callback] = (),
                   validation_data: Optional[Tuple] = None,
                   verbose: int = 1) -> History:
        """Train from streamed batches — the ``fit_generator``-shaped
        entry for sources that don't fit in memory.

        ``batches``: an iterator of ``(x, y)`` numpy batch tuples, or a
        callable ``epoch -> iterator`` (pass ``data.tfrecord_batches``
        with its ``epoch=`` argument for the per-epoch reshuffle
        contract).  All batches must share one shape, divisible by the
        mesh's data shards and by ``grad_accum_steps`` (validated on the
        first batch — the stream fixes the size, so nothing is rounded).
        Each epoch draws ``steps_per_epoch`` batches; a source that ends
        sooner ends the epoch — and training — early, with no ghost
        epoch.  ``compile(steps_per_execution=K)`` groups dispatches
        exactly as in ``fit``; sample/class weights are not supported on
        this path.
        """
        c = self._require_compiled()
        train_step = c["train_step"]
        spe = c["steps_per_execution"]
        multi_step = c["multi_train_step"]

        def epoch_iter(epoch):
            it = batches(epoch) if callable(batches) else batches
            for _ in range(steps_per_epoch):
                try:
                    yield next(it)
                except StopIteration:
                    return

        # Build + validate from the first batch: the stream fixes the
        # batch size, so incompatibilities must fail HERE with the
        # parameter's name, not at trace time inside the step.
        first_it = epoch_iter(0)
        try:
            first = next(first_it)
        except StopIteration:
            raise ValueError("batch stream is empty")
        bs = int(np.shape(first[0])[0])
        accum = c["step_kwargs"].get("accum_steps", 1)
        if accum > 1 and bs % accum:
            raise ValueError(f"streamed batch size {bs} is not divisible "
                             f"by grad_accum_steps {accum}")
        if c["mesh"] is not None:
            shards = c["mesh"].shape.get("data", 1)
            if bs % shards:
                raise ValueError(f"streamed batch size {bs} is not "
                                 f"divisible by the mesh's {shards} data "
                                 f"shards")
        if self.state is None:
            self.build(tuple(np.shape(first[0])[1:]))
        base_ndim = np.asarray(first[0]).ndim
        sharding, batch_sharding = _stream_shardings(
            c["mesh"], base_ndim, multi_step is not None)

        import itertools
        history = History()
        callbacks = list(callbacks) + [history]
        self.stop_training = False
        exhausted = False
        for cb in callbacks:
            cb.on_train_begin(self)
        for epoch in range(epochs):
            if self.stop_training or exhausted:
                break
            it = (itertools.chain([first], first_it) if epoch == 0
                  else epoch_iter(epoch))
            sync_every = _sync_every(c["mesh"])
            acc = _MeanAccumulator()
            last_metrics: Dict[str, Any] = {}
            drawn = 0
            dispatches = 0
            epoch_began = False
            groups = _group_batches(it, spe, multi_step is not None)
            for batch in prefetch_to_device(groups, sharding=sharding,
                                            sharding_fn=batch_sharding):
                if not epoch_began:
                    # after the first batch exists: an exactly-exhausted
                    # stream must not produce a ghost zero-step epoch
                    epoch_began = True
                    for cb in callbacks:
                        cb.on_epoch_begin(self, epoch)
                if batch[0].ndim > base_ndim:
                    self.state, last_metrics = multi_step(self.state, batch)
                    drawn += batch[0].shape[0]
                else:
                    self.state, last_metrics = train_step(self.state, batch)
                    drawn += 1
                dispatches += 1
                acc.add(last_metrics)
                if dispatches % sync_every == 0:
                    acc.block()
            if not epoch_began:
                break                              # stream already dry
            exhausted = drawn < steps_per_epoch
            logs = acc.means()
            if validation_data is not None:
                val = self.evaluate(validation_data[0], validation_data[1],
                                    verbose=0)
                logs.update({f"val_{k}": v for k, v in val.items()})
            if verbose:
                parts = ", ".join(f"{k}={v:.4f}" for k, v in logs.items())
                print(f"Epoch {epoch + 1}/{epochs}: {parts}", flush=True)
            for cb in callbacks:
                cb.on_epoch_end(self, epoch, logs)
        for cb in callbacks:
            cb.on_train_end(self)
        return history

    def _sample_weighted_step(self, c) -> Any:
        """Compiled ``step(state, (x, y, w))`` applying Keras 2.0.8's
        sample-weight rule; built once per compile and cached (the weights
        are batch data, so every fit(sample_weight=...) reuses it)."""
        if "sample_step" in c:
            return c["sample_step"]
        loss_value_fn = c["loss"]
        metric_fns = c["metric_fns"]
        stack = self.stack

        def loss_fn(params, model_state, batch, rng, train):
            xb, yb, wb = batch
            preds, new_ms = stack.apply(params, model_state, xb,
                                        train=train, rng=rng)
            # per-sample losses: the scalar loss of each sample's own
            # [1, ...] slice (exact for any mean-of-per-sample-terms loss)
            per = jax.vmap(
                lambda pi, yi: loss_value_fn(pi[None], yi[None]))(preds, yb)
            w = wb.astype(per.dtype)
            nonzero = jnp.sum((w != 0).astype(per.dtype))
            loss = jnp.sum(per * w) / jnp.maximum(nonzero, 1.0)
            metrics = {name: metric_lib.get(fn)(preds, yb)
                       for name, fn in metric_fns.items()}
            return loss, (metrics, new_ms)

        kw = c["step_kwargs"]
        mesh, state_sh, batch_sh = kw["mesh"], None, None
        if mesh is not None:
            from jax.sharding import PartitionSpec
            state_sh, (bx, by) = step_lib._state_batch_shardings(
                mesh, kw["params_spec"], PartitionSpec("data"))
            batch_sh = (bx, by, by)
        c["sample_step"] = step_lib.make_custom_train_step(
            loss_fn, c["optimizer"], seed=kw["seed"], mesh=mesh,
            state_shardings=state_sh, batch_shardings=batch_sh,
            grad_clip_norm=kw["grad_clip_norm"], policy=kw["policy"])
        return c["sample_step"]

    def _masked_eval_step(self, c) -> Any:
        """Compiled ``eval_step(state, (x, y, w))`` excluding mask-0
        examples from the means (multi-process ragged-tail path); built
        lazily and cached per compile like the sample-weight step."""
        if "masked_eval_step" not in c:
            c["masked_eval_step"] = step_lib.make_masked_eval_step(
                self.stack, c["loss"], metric_fns=c["metric_fns"],
                policy=c["step_kwargs"]["policy"])
        return c["masked_eval_step"]

    # -- single-batch steps (Keras train/test/predict_on_batch parity) ---
    def _mesh_batch(self, x, y, train: bool):
        """Shard an on-batch pair for a mesh-compiled model.  The train
        step pins ``P('data')`` in_shardings, so its batch MUST divide the
        data shards; the eval step propagates shardings and accepts either."""
        c = self._require_compiled()
        batch = (np.asarray(x), np.asarray(y))
        mesh = c["mesh"]
        if mesh is None:
            return batch
        shards = mesh.shape["data"]
        if batch[0].shape[0] % shards == 0:
            from jax.sharding import NamedSharding, PartitionSpec
            return jax.device_put(
                batch, NamedSharding(mesh, PartitionSpec("data")))
        if train:
            raise ValueError(
                f"train_on_batch with a mesh-compiled model needs the batch "
                f"({batch[0].shape[0]}) divisible by the mesh's data shards "
                f"({shards})")
        return batch

    def train_on_batch(self, x, y) -> Dict[str, float]:
        """One optimizer step on one batch -> metric dict."""
        c = self._require_compiled()
        if self.state is None:
            self.build(tuple(np.shape(x)[1:]))
        self.state, metrics = c["train_step"](
            self.state, self._mesh_batch(x, y, train=True))
        return {k: float(v) for k, v in metrics.items()}

    def test_on_batch(self, x, y) -> Dict[str, float]:
        """Loss/metrics on one batch, no state change."""
        c = self._require_compiled()
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        metrics = c["eval_step"](self.state,
                                 self._mesh_batch(x, y, train=False))
        return {k: float(v) for k, v in metrics.items()}

    def predict_on_batch(self, x) -> np.ndarray:
        return self.predict(np.asarray(x), batch_size=int(np.shape(x)[0]))

    def evaluate(self, x, y, batch_size: int = 32,
                 verbose: int = 1) -> Dict[str, float]:
        self._require_compiled()
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        dataset = Dataset([np.asarray(x), np.asarray(y)], batch_size,
                          shuffle=False, drop_remainder=False)
        return self._evaluate_batches(iter(dataset), verbose)

    def evaluate_stream(self, batches, steps: Optional[int] = None,
                        verbose: int = 1) -> Dict[str, float]:
        """``evaluate`` over streamed ``(x, y)`` batches (an iterator, e.g.
        ``data.tfrecord_batches``): batch-size-weighted metric means over
        up to ``steps`` batches (all of them when ``steps`` is None; the
        limit is an ``islice``, so no extra batch is drawn from a shared
        iterator).  Same pull discipline and multi-host upload path as
        ``evaluate``/``fit_stream``."""
        import itertools
        it = batches if steps is None else itertools.islice(batches, steps)
        return self._evaluate_batches(it, verbose)

    def _evaluate_batches(self, it, verbose: int) -> Dict[str, float]:
        """ONE eval core: batch-size-weighted metric means over an
        iterator of (x, y) batches.  Pulls are deferred (a float() per
        batch would sync the async dispatch queue once per dispatch —
        over a TPU tunnel that costs more than the eval compute) but
        BOUNDED by the same ``_sync_every`` cadence the fit paths use, so
        neither the dispatch queue nor the pending list grows with the
        stream; on the CPU mesh the cadence is 1, which is also the
        collective-rendezvous guard.  Uploads route through
        ``prefetch_to_device`` — overlap plus the multi-host per-process
        assembly.  A batch not divisible by the mesh's data shards (the
        ragged eval tail) is uploaded unsharded on one host in a
        single-process run (exact); in a MULTI-process run it is PADDED
        up to the next shardable size with a per-example validity mask
        and fed through a masked eval step that excludes the padding from
        the means — so N-process ``evaluate`` equals the 1-process means
        instead of silently applying drop_remainder semantics."""
        c = self._require_compiled()
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        sharding, _ = _stream_shardings(c["mesh"], 0, want_multi=False)
        shards = (sharding.mesh.shape["data"] if sharding is not None
                  else 1)
        multi_process = jax.process_count() > 1
        # Each process uploads its LOCAL batch; the assembled global array
        # needs the local leading dim divisible by the process's share of
        # the data axis (equal local tails across processes, same contract
        # as the divisible-batch path).
        local_shards = max(1, shards // jax.process_count())
        # Host-side real-count carry for padded tails: prefetch preserves
        # FIFO order, so the consumer pops the global real count matching
        # each 3-tuple batch (device-summing the mask would sync the
        # async dispatch queue).  Equal local tails across processes is
        # the same contract the divisible-batch path already assumes.
        tail_real = collections.deque()

        def keep(it):
            for b in it:
                if (sharding is not None and multi_process
                        and b[0].shape[0] % shards):
                    bs = b[0].shape[0]
                    padded = -(-bs // local_shards) * local_shards
                    pad = padded - bs
                    w = np.concatenate([np.ones(bs, np.float32),
                                        np.zeros(pad, np.float32)])
                    tail_real.append(bs * jax.process_count())
                    # pad value is arbitrary (masked out); repeating the
                    # last example keeps dtypes/shapes without branches
                    yield tuple(np.concatenate(
                        [a, np.repeat(a[-1:], pad, axis=0)]) for a in b
                    ) + (w,)
                    continue
                yield b

        it = keep(it)

        def batch_sharding(item):
            if sharding is None:
                return None
            if len(item) == 3 or item[0].shape[0] % shards == 0:
                return sharding
            return None

        sync_every = _sync_every(c["mesh"])
        pending = []
        totals: Dict[str, float] = {}
        n = 0

        def pull_all():
            nonlocal n
            for bs, metrics in pending:
                for k, v in metrics.items():
                    totals[k] = totals.get(k, 0.0) + float(v) * bs
                n += bs
            pending.clear()

        masked_step = None
        for batch in prefetch_to_device(it, sharding=None,
                                        sharding_fn=batch_sharding):
            if len(batch) == 3:
                if masked_step is None:
                    masked_step = self._masked_eval_step(c)
                pending.append((tail_real.popleft(),
                                masked_step(self.state, batch)))
            else:
                pending.append((batch[0].shape[0],
                                c["eval_step"](self.state, batch)))
            if len(pending) >= sync_every:
                pull_all()
        pull_all()
        out = {k: v / max(n, 1) for k, v in totals.items()}
        if verbose:
            parts = ", ".join(f"{k}={v:.4f}" for k, v in out.items())
            print(f"evaluate: {parts}", flush=True)
        return out

    # -- weights IO (Keras save_weights/load_weights parity) -------------
    def save_weights(self, ckpt_dir: str) -> str:
        """Write {params, model_state} (not optimizer state) as a
        step-stamped checkpoint under ``ckpt_dir``."""
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        from ..train import checkpoint as ck
        return ck.save(ckpt_dir, int(self.state.step),
                       {"params": self.state.params,
                        "model_state": self.state.model_state})

    def load_weights(self, ckpt_dir: str) -> None:
        """Restore the latest weights checkpoint from ``ckpt_dir`` into the
        (built) model — optimizer state is untouched."""
        if self.state is None:
            raise RuntimeError("build the model (compile + build/fit) "
                               "before load_weights")
        from ..train import checkpoint as ck
        latest = ck.latest_checkpoint(ckpt_dir)
        if latest is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
        restored = ck.restore({"params": self.state.params,
                               "model_state": self.state.model_state},
                              latest)
        self.state = self.state._replace(params=restored["params"],
                                         model_state=restored["model_state"])

    def predict(self, x, batch_size: int = 256) -> np.ndarray:
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        apply_fn = jax.jit(
            lambda params, model_state, xb: self.stack.apply(
                params, model_state, xb, train=False, rng=None)[0])
        outs = []
        x = np.asarray(x)
        for lo in range(0, x.shape[0], batch_size):
            # device arrays, un-pulled: dispatch the whole stream async,
            # convert once at the end (one sync, not one per batch)
            outs.append(apply_fn(self.state.params, self.state.model_state,
                                 x[lo:lo + batch_size]))
        return np.concatenate([np.asarray(o) for o in outs], axis=0)

    # -- flat weights access (Keras get_weights/set_weights analogue) ----
    def _layer_leaves(self):
        """(layer_key, leaves, treedef) per param-owning layer, in LAYER
        order (dict-key sorting would put 'dense_10' before 'dense_2')."""
        out = []
        for key in self.stack.keys:
            sub = self.state.params.get(key)
            if sub is not None:
                leaves, treedef = jax.tree_util.tree_flatten(sub)
                out.append((key, leaves, treedef))
        return out

    def get_weights(self) -> List[np.ndarray]:
        """Parameters as a flat list of host arrays: layers in model
        order, leaves in this framework's (sorted-key) order within each
        layer — ``set_weights`` is the exact inverse."""
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        return [np.asarray(w) for _, leaves, _ in self._layer_leaves()
                for w in leaves]

    def set_weights(self, weights: Sequence[np.ndarray]) -> None:
        """Inverse of ``get_weights``: same order, shapes must match."""
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        per_layer = self._layer_leaves()
        total = sum(len(leaves) for _, leaves, _ in per_layer)
        if len(weights) != total:
            raise ValueError(f"expected {total} arrays, got {len(weights)}")
        params = dict(self.state.params)
        i = 0
        for key, leaves, treedef in per_layer:
            new = []
            for cur in leaves:
                w = np.asarray(weights[i])
                i += 1
                if w.shape != cur.shape:
                    raise ValueError(f"shape mismatch at {key!r}: expected "
                                     f"{cur.shape}, got {w.shape}")
                new.append(jnp.asarray(w, cur.dtype))
            params[key] = jax.tree_util.tree_unflatten(treedef, new)
        self.state = self.state._replace(params=params)

    # -- full-model IO (Keras model.save / load_model / to_json parity) --
    def save(self, path: str) -> str:
        """Architecture + weights under ``path`` (see models.saving)."""
        from . import saving
        return saving.save_model(self, path)

    def to_json(self, **dump_kwargs) -> str:
        from . import saving
        import json
        return json.dumps(saving.model_to_config(self), **dump_kwargs)

    @classmethod
    def from_json(cls, text: str) -> "Sequential":
        from . import saving
        import json
        return saving.model_from_config(json.loads(text))

    # -- learning-rate control (Keras optimizer.lr mutation analogue) ----
    @property
    def lr_scale(self) -> float:
        """Multiplier on the compiled optimizer's learning rate."""
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        return opt_lib.get_lr_scale(self.state.opt_state)

    @lr_scale.setter
    def lr_scale(self, value: float) -> None:
        if self.state is None:
            raise RuntimeError("model has no state; call fit or build first")
        self.state = self.state._replace(
            opt_state=opt_lib.set_lr_scale(self.state.opt_state, value))

    # -- introspection ---------------------------------------------------
    def summary(self) -> str:
        lines = [f"Model: {self.name}"]
        total = 0
        if self.state is not None:
            for name, p in self.state.params.items():
                n = sum(int(np.prod(leaf.shape))
                        for leaf in jax.tree_util.tree_leaves(p))
                total += n
                lines.append(f"  {name}: {n:,} params")
            lines.append(f"Total params: {total:,}")
        else:
            lines += [f"  {layer!r}" for layer in self._layers]
        text = "\n".join(lines)
        print(text, flush=True)
        return text
