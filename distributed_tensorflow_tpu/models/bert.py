"""BERT encoder family (MLM pre-train / fine-tune) — the flagship model.

The reference has no transformer (its model is a 3-layer MLP, reference
example.py:149-155); BERT-base MLM is the driver's largest baseline config
(BASELINE.md #5: pjit data+model parallel on v5p-128).  TPU-first design:

  * **Scanned layer stack**: the L encoder layers are ONE set of parameter
    arrays with a leading ``[L, ...]`` stacking dim, applied with
    ``lax.scan`` — compile time is O(1) in depth and XLA pipelines the
    layers.  Optional ``remat`` wraps the scan body in ``jax.checkpoint``
    to trade recompute for HBM (long-context requirement).
  * **4D mesh-ready sharding**: ``partition_rules()`` ships megatron-style
    specs — attention heads and FFN hidden on ``tensor`` (column-parallel
    in, row-parallel out), optional ``fsdp`` on the complementary dim,
    embeddings sharded on vocab — one rule table from 1 chip to a pod.
  * **Sequence parallelism**: ``apply`` takes the activations in
    ``[batch, seq, hidden]``; with ``seq_axis`` set, attention runs as ring
    attention over the ``seq`` mesh axis (parallel.ring) so sequences can
    exceed one chip's HBM.
  * bf16 activations / f32 master params via the shared layer conventions.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import attention as attn_lib
from ..ops import initializers as init_lib
from ..ops import losses as loss_lib
from ..parallel.sharding import PartitionRules

__all__ = ["BertConfig", "Bert", "bert_base", "bert_tiny"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32          # activation/compute dtype
    remat: bool = False               # checkpoint each encoder layer
    # with remat=True: "full" (save nothing), "dots" (save matmul
    # outputs, recompute elementwise only), "dots_no_batch" — see
    # GPTConfig.remat_policy
    remat_policy: str = "full"
    seq_axis: Optional[str] = None    # mesh axis for ring attention (SP)
    # True / False / "auto": auto dispatches the fused Pallas kernel on TPU
    # at seq >= the measured crossover (ops.attention.resolve_use_flash).
    # Hardware-validated + measured 2026-07-31 (docs/PERF.md): ties XLA at
    # seq <= 1024, wins 1.3-1.7x at 2048, ~3x at 4096 — "auto" is safe.
    use_flash: Any = "auto"
    # True / False / "auto": LayerNorms via the fused Pallas kernel
    # (ops.pallas.fused_layernorm, one HBM pass); auto = TPU only.
    # Default False until the end-to-end win is measured on hardware.
    fused_layernorm: Any = False
    # >0: the original BERT ``max_predictions_per_seq`` design — gather at
    # most N masked positions per sequence BEFORE the MLM head, so the
    # transform/LN/vocab projection (2*d*V FLOPs/token, V=30522) runs on
    # ~15% of tokens instead of all of them and the [b, s, V] logits are
    # never built.  Exact vs the full path while every row has <= N masked
    # positions; overflow drops extra positions from the loss (reported in
    # the ``mlm_overflow`` metric).  0 = project every position.
    mlm_predictions_per_seq: int = 0
    # FFN / MLM-transform activation: "gelu_approx" (tanh, the GPT-2/zoo
    # default) or "gelu" (exact erf — what HF BERT checkpoints were
    # trained with; models/convert.py sets this)
    hidden_act: str = "gelu_approx"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def act_fn(self):
        from ..ops.attention import resolve_activation
        return resolve_activation(self.hidden_act)


def bert_base(**kw) -> "Bert":
    return Bert(BertConfig(**kw))


def bert_tiny(**kw) -> "Bert":
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate_size", 512)
    kw.setdefault("vocab_size", 1000)
    kw.setdefault("max_position", 128)
    return Bert(BertConfig(**kw))


def _layer_norm(params, x, eps, fused=False):
    if fused:
        from ..ops.pallas import fused_layernorm
        return fused_layernorm(x, params["gamma"], params["beta"], eps=eps)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * params["gamma"] + params["beta"]).astype(x.dtype)


def _resolve_fused_ln(flag) -> bool:
    from ..ops.pallas import resolve_fused_ln
    return resolve_fused_ln(flag)


def mlm_gather_flops_correction(config, seq: int) -> float:
    """Training FLOPs/token the gathered MLM head SKIPS vs projecting
    every position: transform d^2 + vocab projection d*V, 6x each (fwd
    2x + bwd 4x), on the non-gathered fraction.  One accounting shared
    by bench.py and scripts/mfu_ablation.py so their MFU columns stay
    comparable.  0 when gathering is off."""
    n = config.mlm_predictions_per_seq
    if not n:
        return 0.0
    d, v = config.hidden_size, config.vocab_size
    return (1.0 - n / seq) * 6.0 * (d * d + d * v)


def _dropout(x, rate, rng, train):
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


class Bert:
    """Functional BERT: ``init(key) -> params``, ``apply(params, batch, ...)``."""

    def __init__(self, config: BertConfig, mesh=None):
        self.config = config
        # Mesh is only needed for sequence parallelism: with ``seq_axis``
        # set and a mesh attached, attention runs as a partial-manual ring
        # over that axis inside the otherwise-auto pjit program.
        self.mesh = mesh

    # -- init -------------------------------------------------------------
    def init(self, key) -> Dict[str, Any]:
        c = self.config
        trunc = init_lib.truncated_normal(0.02)
        k_emb, k_layers, k_head = jax.random.split(key, 3)
        ke = jax.random.split(k_emb, 3)

        def ln():
            return {"gamma": jnp.ones((c.hidden_size,), jnp.float32),
                    "beta": jnp.zeros((c.hidden_size,), jnp.float32)}

        params: Dict[str, Any] = {
            "embeddings": {
                "word": trunc(ke[0], (c.vocab_size, c.hidden_size)),
                "position": trunc(ke[1], (c.max_position, c.hidden_size)),
                "type": trunc(ke[2], (c.type_vocab_size, c.hidden_size)),
                "ln": ln(),
            },
        }

        h, hd, d, i = c.num_heads, c.head_dim, c.hidden_size, c.intermediate_size

        def one_layer(k):
            ks = jax.random.split(k, 6)
            return {
                "attention": {
                    "query": {"kernel": trunc(ks[0], (d, h, hd)),
                              "bias": jnp.zeros((h, hd), jnp.float32)},
                    "key": {"kernel": trunc(ks[1], (d, h, hd)),
                            "bias": jnp.zeros((h, hd), jnp.float32)},
                    "value": {"kernel": trunc(ks[2], (d, h, hd)),
                              "bias": jnp.zeros((h, hd), jnp.float32)},
                    "out": {"kernel": trunc(ks[3], (h, hd, d)),
                            "bias": jnp.zeros((d,), jnp.float32)},
                    "ln": ln(),
                },
                "ffn": {
                    "w_in": {"kernel": trunc(ks[4], (d, i)),
                             "bias": jnp.zeros((i,), jnp.float32)},
                    "w_out": {"kernel": trunc(ks[5], (i, d)),
                              "bias": jnp.zeros((d,), jnp.float32)},
                    "ln": ln(),
                },
            }

        # Stacked layers: vmap init over per-layer keys -> leading [L, ...].
        params["encoder"] = jax.vmap(one_layer)(
            jax.random.split(k_layers, c.num_layers))

        kh = jax.random.split(k_head, 2)
        params["mlm"] = {
            "transform": {"kernel": trunc(kh[0], (d, d)),
                          "bias": jnp.zeros((d,), jnp.float32)},
            "ln": ln(),
            "output_bias": jnp.zeros((c.vocab_size,), jnp.float32),
        }
        params["pooler"] = {"kernel": trunc(kh[1], (d, d)),
                            "bias": jnp.zeros((d,), jnp.float32)}
        return params

    # -- encoder ----------------------------------------------------------
    def _attention(self, p, x, mask, valid, rng, train):
        c = self.config

        if c.seq_axis is not None and self.mesh is not None:
            # the flash crossover applies to the kernel's PER-CALL seq:
            # inside the ring each call sees one shard, so gate on the
            # local shard length, not the global sequence
            local = x.shape[1] // self.mesh.shape[c.seq_axis]
            if attn_lib.resolve_use_flash(c.use_flash, local):
                # SP x flash: the ring schedule with the fused kernel per
                # block pair (parallel.ring_flash) — both long-context
                # levers stacked
                from ..parallel.ring_flash import ring_flash_attention_sharded
                attention_fn = lambda q, k, v, mask=None: \
                    ring_flash_attention_sharded(
                        q, k, v, self.mesh, seq_axis=c.seq_axis,
                        kv_valid=valid)
            else:
                from ..parallel.ring import ring_attention_sharded
                attention_fn = lambda q, k, v, mask=None: \
                    ring_attention_sharded(
                        q, k, v, self.mesh, seq_axis=c.seq_axis,
                        kv_valid=valid)
        elif c.seq_axis is not None:
            # traced inside a caller's shard_map: x is the local shard
            if attn_lib.resolve_use_flash(c.use_flash, x.shape[1]):
                from ..parallel.ring_flash import ring_flash_attention
                attention_fn = lambda q, k, v, mask=None: \
                    ring_flash_attention(q, k, v, axis_name=c.seq_axis,
                                         kv_valid=valid)
            else:
                from ..parallel.ring import ring_attention
                attention_fn = lambda q, k, v, mask=None: ring_attention(
                    q, k, v, axis_name=c.seq_axis, kv_valid=valid)
        elif attn_lib.resolve_use_flash(c.use_flash, x.shape[1]):
            from ..ops.pallas import flash_attention
            attention_fn = lambda q, k, v, mask=None: flash_attention(
                q, k, v, kv_valid=valid)
        else:
            attention_fn = attn_lib.dot_product_attention
        return attn_lib.attention_core(
            p, x, mask=mask, dropout_rate=c.dropout_rate, rng=rng,
            train=train, attention_fn=attention_fn)

    def _encoder_layer(self, p, x, mask, valid, rng, train):
        c = self.config
        fused = _resolve_fused_ln(c.fused_layernorm)
        r1, r2, r3 = jax.random.split(rng, 3)
        attn_out = self._attention(p["attention"], x, mask, valid, r1, train)
        x = _layer_norm(p["attention"]["ln"],
                        x + _dropout(attn_out, c.dropout_rate, r2, train),
                        c.layer_norm_eps, fused=fused)
        ffn_out = attn_lib.ffn_core(p["ffn"], x, activation=c.act_fn)
        return _layer_norm(p["ffn"]["ln"],
                           x + _dropout(ffn_out, c.dropout_rate, r3, train),
                           c.layer_norm_eps, fused=fused)

    def apply(self, params, input_ids, *, token_type_ids=None,
              attention_mask=None, train: bool = False, rng=None):
        """-> sequence output [batch, seq, hidden] in config.dtype."""
        c = self.config
        if rng is None:
            if train:
                raise ValueError(
                    "Bert.apply(train=True) requires an rng key (dropout); "
                    "use make_custom_train_step or pass rng explicitly")
            rng = jax.random.PRNGKey(0)   # eval: dropout is a no-op
        b, s = input_ids.shape
        emb = params["embeddings"]
        x = jnp.take(emb["word"], input_ids, axis=0)
        x = x + emb["position"][None, :s, :]
        if token_type_ids is not None:
            x = x + jnp.take(emb["type"], token_type_ids, axis=0)
        else:
            x = x + emb["type"][0][None, None, :]
        x = _layer_norm(emb["ln"], x, c.layer_norm_eps,
                        fused=_resolve_fused_ln(c.fused_layernorm))
        r_emb, r_layers = jax.random.split(rng)
        x = _dropout(x, c.dropout_rate, r_emb, train).astype(c.dtype)

        mask = (attn_lib.padding_mask(attention_mask)
                if attention_mask is not None else None)
        valid = attention_mask  # raw [b, s] form for the ring path

        layer_fn = self._encoder_layer
        if c.remat:
            from .gpt import _remat_policy
            layer_fn = jax.checkpoint(layer_fn, static_argnums=(5,),
                                      policy=_remat_policy(c.remat_policy))

        def body(carry, inputs):
            layer_params, layer_key = inputs
            return layer_fn(layer_params, carry, mask, valid, layer_key,
                            train), None

        layer_keys = jax.random.split(r_layers, c.num_layers)
        x, _ = jax.lax.scan(body, x, (params["encoder"], layer_keys))
        return x

    # -- heads ------------------------------------------------------------
    def mlm_logits(self, params, sequence_output):
        """Tied-embedding MLM head -> [batch, seq, vocab] (f32 logits)."""
        c = self.config
        p = params["mlm"]
        dtype = sequence_output.dtype
        h = c.act_fn(sequence_output @ p["transform"]["kernel"].astype(dtype)
                     + p["transform"]["bias"].astype(dtype))
        h = _layer_norm(p["ln"], h, c.layer_norm_eps,
                        fused=_resolve_fused_ln(c.fused_layernorm))
        logits = h @ params["embeddings"]["word"].T.astype(dtype)
        return logits.astype(jnp.float32) + p["output_bias"]

    def pooled(self, params, sequence_output):
        """[CLS] pooler -> [batch, hidden] (classification fine-tune)."""
        p = params["pooler"]
        first = sequence_output[:, 0, :]
        return jnp.tanh(first @ p["kernel"].astype(first.dtype)
                        + p["bias"].astype(first.dtype))

    # -- losses -----------------------------------------------------------
    def mlm_loss_fn(self):
        """Contract for ``train.make_custom_train_step``: batch dict with
        input_ids / labels / mlm mask (-100 or mask array) / attention_mask."""

        def loss_fn(params, model_state, batch, rng, train):
            seq = self.apply(params, batch["input_ids"],
                             token_type_ids=batch.get("token_type_ids"),
                             attention_mask=batch.get("attention_mask"),
                             train=train, rng=rng)
            mask = batch["mlm_mask"]
            labels = batch["labels"]
            n_pred = self.config.mlm_predictions_per_seq
            extra = {}
            if n_pred:
                # top_k on the 0/1 mask sorts the masked positions first;
                # the gathered mask values double as the loss weights, so
                # rows with fewer than n_pred masked positions pad with
                # weight 0 and rows with more drop the overflow.
                w, idx = jax.lax.top_k(mask.astype(jnp.float32), n_pred)
                seq = jnp.take_along_axis(seq, idx[..., None], axis=1)
                labels = jnp.take_along_axis(labels, idx, axis=1)
                full = jnp.sum(mask.astype(jnp.float32))
                mask = w
                extra["mlm_overflow"] = full - jnp.sum(w)
            logits = self.mlm_logits(params, seq)
            loss = loss_lib.softmax_cross_entropy_with_integer_labels(
                logits, labels, where=mask)
            acc_hits = (jnp.argmax(logits, -1) == labels).astype(
                jnp.float32) * mask
            accuracy = jnp.sum(acc_hits) / jnp.maximum(jnp.sum(mask), 1.0)
            # loss_weight: the masked-mean normalizer, consumed by
            # train.step gradient accumulation for exact full-batch grads.
            return loss, ({"mlm_accuracy": accuracy,
                           "loss_weight": jnp.sum(mask).astype(jnp.float32),
                           **extra},
                          model_state)

        return loss_fn

    # -- sharding ---------------------------------------------------------
    def partition_rules(self, fsdp: bool = False) -> PartitionRules:
        """Megatron-style TP specs (+ optional fsdp on the complementary
        dim).  Paths include the scanned leading layer dim, which is never
        sharded (each chip holds all L slices of its shard)."""
        f = "fsdp" if fsdp else None
        return PartitionRules([
            # embeddings: vocab on tensor (row-parallel gather + tied head)
            (r"embeddings/word$", P("tensor", f)),
            (r"embeddings/(position|type)$", P(None, None)),
            # attention projections [L, d, h, hd]: heads on tensor
            (r"encoder/attention/(query|key|value)/kernel", P(None, f, "tensor", None)),
            (r"encoder/attention/(query|key|value)/bias", P(None, "tensor", None)),
            # out projection [L, h, hd, d]: heads on tensor (row-parallel)
            (r"encoder/attention/out/kernel", P(None, "tensor", None, f)),
            # FFN [L, d, i] / [L, i, d]: hidden i on tensor
            (r"encoder/ffn/w_in/kernel", P(None, f, "tensor")),
            (r"encoder/ffn/w_in/bias", P(None, "tensor")),
            (r"encoder/ffn/w_out/kernel", P(None, "tensor", f)),
            (r"mlm/transform/kernel", P(f, "tensor")),
            (r"pooler/kernel", P(f, "tensor")),
            (r"mlm/output_bias", P("tensor")),
        ])
