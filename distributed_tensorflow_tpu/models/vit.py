"""Vision Transformer (ViT) — image classification on the transformer core.

The reference has no vision models (its model is a 3-layer MLP on bit
vectors, reference example.py:149-155); ViT extends the framework's model
zoo to the modern image-classification architecture while reusing the same
building blocks as BERT/GPT/seq2seq: ``attention_core``/``ffn_core``
(ops/attention.py), scanned encoder layers (compile time O(1) in depth),
megatron-style partition rules, optional flash attention and remat.

TPU-first choices:
  * Patchify is ONE strided conv (maps to the MXU) instead of
    reshape+gather shuffles.
  * Pre-LN blocks (ViT convention, unlike BERT's post-LN) — residuals
    stay in the compute dtype, norms in f32.
  * Learned position embeddings over ``(1 + n_patches)`` tokens; CLS token
    carries the classification signal (standard ViT head).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import attention as attn_lib
from ..ops import initializers as init_lib
from ..ops import losses as loss_lib
from ..parallel.sharding import PartitionRules
from .bert import _dropout, _layer_norm

__all__ = ["ViTConfig", "ViT", "vit_base", "vit_tiny"]


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    dropout_rate: float = 0.0
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.float32
    # FFN activation: "gelu_approx" (tanh, zoo default) or "gelu" (exact
    # erf — HF ViT checkpoints; models/convert.py sets this)
    hidden_act: str = "gelu_approx"
    remat: bool = False
    # True / False / "auto" (ops.attention.resolve_use_flash); ViT seq is
    # (image/patch)^2+1 — 197 for 224/16 — so "auto" stays on XLA until
    # high-resolution inputs push past the measured seq-2048 crossover.
    use_flash: Any = "auto"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def act_fn(self):
        from ..ops.attention import resolve_activation
        return resolve_activation(self.hidden_act)

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side


def vit_base(**kw) -> "ViT":
    return ViT(ViTConfig(**kw))


def vit_tiny(**kw) -> "ViT":
    kw.setdefault("image_size", 32)
    kw.setdefault("patch_size", 8)
    kw.setdefault("num_classes", 10)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate_size", 128)
    return ViT(ViTConfig(**kw))


class ViT:
    """Functional ViT: ``init(key) -> params``, ``apply(params, images)``."""

    def __init__(self, config: ViTConfig):
        self.config = config

    # -- init -------------------------------------------------------------
    def init(self, key) -> Dict[str, Any]:
        c = self.config
        if c.image_size % c.patch_size:
            raise ValueError(f"image_size {c.image_size} not divisible by "
                             f"patch_size {c.patch_size}")
        trunc = init_lib.truncated_normal(0.02)
        lecun = init_lib.lecun_normal()
        k_patch, k_pos, k_cls, k_layers, k_head = jax.random.split(key, 5)

        def ln():
            return {"gamma": jnp.ones((c.hidden_size,), jnp.float32),
                    "beta": jnp.zeros((c.hidden_size,), jnp.float32)}

        d, h, hd, i = (c.hidden_size, c.num_heads, c.head_dim,
                       c.intermediate_size)
        params: Dict[str, Any] = {
            "patch_embed": {
                "kernel": lecun(k_patch, (c.patch_size, c.patch_size,
                                          c.channels, d)),
                "bias": jnp.zeros((d,), jnp.float32),
            },
            "cls_token": jnp.zeros((1, 1, d), jnp.float32),
            "pos_embed": trunc(k_pos, (1, 1 + c.n_patches, d)),
        }
        del k_cls  # cls token is zero-init (BERT/ViT convention)

        def one_layer(k):
            ks = jax.random.split(k, 6)
            return {
                "attention": {
                    "query": {"kernel": trunc(ks[0], (d, h, hd)),
                              "bias": jnp.zeros((h, hd), jnp.float32)},
                    "key": {"kernel": trunc(ks[1], (d, h, hd)),
                            "bias": jnp.zeros((h, hd), jnp.float32)},
                    "value": {"kernel": trunc(ks[2], (d, h, hd)),
                              "bias": jnp.zeros((h, hd), jnp.float32)},
                    "out": {"kernel": trunc(ks[3], (h, hd, d)),
                            "bias": jnp.zeros((d,), jnp.float32)},
                    "ln": ln(),
                },
                "ffn": {
                    "w_in": {"kernel": trunc(ks[4], (d, i)),
                             "bias": jnp.zeros((i,), jnp.float32)},
                    "w_out": {"kernel": trunc(ks[5], (i, d)),
                              "bias": jnp.zeros((d,), jnp.float32)},
                    "ln": ln(),
                },
            }

        params["encoder"] = jax.vmap(one_layer)(
            jax.random.split(k_layers, c.num_layers))
        params["final_ln"] = ln()
        params["head"] = {"kernel": jnp.zeros((d, c.num_classes),
                                              jnp.float32),
                          "bias": jnp.zeros((c.num_classes,), jnp.float32)}
        return params

    # -- encoder ----------------------------------------------------------
    def _encoder_layer(self, p, x, rng, train):
        """Pre-LN block: x + attn(LN(x)); x + ffn(LN(x))."""
        c = self.config
        r1, r2, r3 = jax.random.split(rng, 3)
        if attn_lib.resolve_use_flash(c.use_flash, x.shape[1]):
            from ..ops.pallas import flash_attention
            attention_fn = lambda q, k, v, mask=None: flash_attention(q, k, v)
        else:
            attention_fn = attn_lib.dot_product_attention
        y = _layer_norm(p["attention"]["ln"], x, c.layer_norm_eps)
        y = attn_lib.attention_core(p["attention"], y, mask=None,
                                    dropout_rate=c.dropout_rate, rng=r1,
                                    train=train, attention_fn=attention_fn)
        x = x + _dropout(y, c.dropout_rate, r2, train)
        y = _layer_norm(p["ffn"]["ln"], x, c.layer_norm_eps)
        y = attn_lib.ffn_core(p["ffn"], y, activation=c.act_fn)
        return x + _dropout(y, c.dropout_rate, r3, train)

    def apply(self, params, images, *, train: bool = False, rng=None,
              return_features: bool = False):
        """NHWC images -> [batch, num_classes] f32 logits; with
        ``return_features`` the post-final-LN token sequence
        [batch, 1 + n_patches, hidden] instead (feature extraction /
        HF-parity surface)."""
        c = self.config
        if rng is None:
            if train and c.dropout_rate > 0.0:
                raise ValueError("ViT.apply(train=True) requires an rng key")
            rng = jax.random.PRNGKey(0)
        x = jax.lax.conv_general_dilated(
            images.astype(c.dtype),
            params["patch_embed"]["kernel"].astype(c.dtype),
            window_strides=(c.patch_size, c.patch_size), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        b = x.shape[0]
        x = x.reshape(b, -1, c.hidden_size)
        x = x + params["patch_embed"]["bias"].astype(c.dtype)
        cls = jnp.broadcast_to(params["cls_token"].astype(c.dtype),
                               (b, 1, c.hidden_size))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos_embed"].astype(c.dtype)
        r_emb, r_layers = jax.random.split(rng)
        x = _dropout(x, c.dropout_rate, r_emb, train)

        layer_fn = self._encoder_layer
        if c.remat:
            layer_fn = jax.checkpoint(layer_fn, static_argnums=(3,))

        def body(carry, inputs):
            layer_params, layer_key = inputs
            return layer_fn(layer_params, carry, layer_key, train), None

        layer_keys = jax.random.split(r_layers, c.num_layers)
        x, _ = jax.lax.scan(body, x, (params["encoder"], layer_keys))
        x = _layer_norm(params["final_ln"], x, c.layer_norm_eps)
        if return_features:
            return x
        cls_out = x[:, 0, :]
        logits = (cls_out @ params["head"]["kernel"].astype(cls_out.dtype)
                  + params["head"]["bias"].astype(cls_out.dtype))
        return logits.astype(jnp.float32)

    # -- loss -------------------------------------------------------------
    def loss_fn(self):
        """Contract for ``train.make_custom_train_step``: batch is
        ``(images, integer_labels)``."""

        def loss_fn(params, model_state, batch, rng, train):
            images, labels = batch
            logits = self.apply(params, images, train=train, rng=rng)
            loss = loss_lib.softmax_cross_entropy_with_integer_labels(
                logits, labels)
            accuracy = jnp.mean(
                (jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            return loss, ({"accuracy": accuracy}, model_state)

        return loss_fn

    # -- sharding ---------------------------------------------------------
    def partition_rules(self, fsdp: bool = False) -> PartitionRules:
        """Same megatron TP layout as the BERT table (heads and FFN hidden
        on ``tensor``); patch conv and head shard their output dim."""
        f = "fsdp" if fsdp else None
        return PartitionRules([
            (r"patch_embed/kernel", P(None, None, None, "tensor")),
            (r"encoder/attention/(query|key|value)/kernel",
             P(None, f, "tensor", None)),
            (r"encoder/attention/(query|key|value)/bias",
             P(None, "tensor", None)),
            (r"encoder/attention/out/kernel", P(None, "tensor", None, f)),
            (r"encoder/ffn/w_in/kernel", P(None, f, "tensor")),
            (r"encoder/ffn/w_in/bias", P(None, "tensor")),
            (r"encoder/ffn/w_out/kernel", P(None, "tensor", f)),
            (r"head/kernel", P(f, "tensor")),
        ])
