"""GPT decoder family (causal LM + KV-cache generation).

The reference has no transformer at all (3-layer MLP, reference
example.py:149-155); the decoder family completes the model zoo beside the
BERT encoder (models/bert.py) with the same TPU-first machinery:

  * **Scanned layer stack**: L pre-LN decoder blocks as ONE stacked
    parameter set applied with ``lax.scan`` — O(1) compile time in depth;
    optional ``remat`` for long-context HBM headroom.
  * **Causal attention** through the shared kernel swap: full softmax by
    default, Pallas flash attention (``use_flash``) on TPU, ring attention
    over a ``seq`` mesh axis (``seq_axis``) for context parallelism.
  * **KV-cache decode**: ``init_cache`` + ``decode_step`` run one token
    through the stack against a static-shape cache (``dynamic_update_slice``
    writes, position-masked reads) so ``generate`` is a ``lax.scan`` with no
    recompilation per token.
  * **Tied embeddings**: the LM head is the word-embedding transpose —
    megatron-style ``tensor`` sharding applies to both at once
    (``partition_rules``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import attention as attn_lib
from ..ops import initializers as init_lib
from ..ops import losses as loss_lib
from ..ops.moe import apply_moe, init_moe, moe_partition_rules
from ..parallel.sharding import PartitionRules
from .bert import _dropout, _layer_norm

__all__ = ["GPTConfig", "GPT", "gpt_small", "gpt_tiny"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    remat: bool = False
    # With remat=True, what the per-layer checkpoint SAVES: "full"
    # (nothing — recompute the whole block, max memory savings),
    # "dots" (all matmul outputs — recompute only elementwise chains,
    # much cheaper backward at higher memory), "dots_no_batch"
    # (weight-only dots).  Measured on hardware via
    # scripts/mfu_ablation.py before changing any default.
    remat_policy: str = "full"
    seq_axis: Optional[str] = None    # mesh axis for ring attention (SP)
    # True / False / "auto": auto dispatches the fused Pallas kernel on TPU
    # at seq >= the measured crossover (ops.attention.resolve_use_flash).
    # Hardware-validated + measured 2026-07-31 (docs/PERF.md): ties XLA at
    # seq <= 1024, wins 1.3-1.7x at 2048, ~3x at 4096 — "auto" is safe.
    use_flash: Any = "auto"
    # True / False / "auto": block norms via the fused Pallas kernel —
    # ops.pallas.fused_layernorm for norm="layernorm",
    # ops.pallas.fused_rmsnorm for norm="rmsnorm"; auto = TPU only.
    # Default False until the end-to-end win is measured on hardware.
    fused_layernorm: Any = False
    # >0: compute the LM loss ``loss_seq_chunk`` tokens at a time (head
    # projection + log-softmax reduced per chunk under jax.checkpoint) so
    # the [tokens, vocab] logits tensor is never fully materialised —
    # GPT-2-small at bench shapes pays ~2.5 GB of f32 logits otherwise.
    # 0 = off (single full-width projection).
    loss_seq_chunk: int = 0
    # "learned" absolute positions (GPT-2) or "rope" rotary embeddings
    # (relative; extrapolates past trained length, no position table)
    position_embedding: str = "learned"
    # RoPE frequency base (10000 = Su et al. / Llama-2; Llama-3 ships
    # 500000 for its 8k context)
    rope_base: float = 10000.0
    # Block normalization: "layernorm" (GPT-2) or "rmsnorm" (Llama — gamma
    # only, no centering/beta)
    norm: str = "layernorm"
    # FFN body: "gelu" (w_in -> gelu -> w_out) or "swiglu" (Llama:
    # w_out(silu(w_gate(x)) * w_in(x)) — w_in is HF's up_proj)
    ffn_activation: str = "gelu"
    # False (Llama): no bias params anywhere in attention/FFN projections
    use_bias: bool = True
    # False (Llama): separate lm_head matrix instead of the tied
    # word-embedding transpose
    tied_head: bool = True
    # Grouped-query attention: number of key/value heads (None = num_heads
    # i.e. plain MHA; 1 = MQA).  Shrinks the KV cache num_heads/num_kv_heads
    # fold — the serving-memory lever for long-context decode.
    num_kv_heads: Optional[int] = None
    # Sparse (MoE) FFN: 0 = dense.  With experts > 0 every block's FFN is a
    # grouped top-k MoE bank (ops.moe) shardable over the ``expert`` axis;
    # the router aux losses are folded into lm_loss_fn automatically.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    moe_z_weight: float = 1e-3
    # Pipeline parallelism (parallel.pipeline): split the L decoder blocks
    # into ``pipeline_stages`` same-shape stages of L/S blocks over the
    # ``pipe_axis`` mesh axis.  Embedding and LM head run pipe-REPLICATED
    # (they are O(vocab*d) beside L blocks; dedicating stages to them would
    # stretch the bubble instead).  0/1 = off.  Requires a mesh at
    # construction (``GPT(config, mesh=...)``).
    pipeline_stages: int = 0
    pipe_axis: str = "pipe"
    # microbatches per step; 0 -> pipeline_stages (the GPipe minimum for
    # full utilization)
    pipeline_microbatches: int = 0
    # KV-cache storage dtype at decode: None = the model dtype; "int8"
    # stores symmetric per-(token, head) int8 with f32 scales — cache
    # reads rival the weight reads at serving batch sizes, so this is
    # the decode HBM-bandwidth lever (2x smaller cache traffic AND 2x
    # the cache capacity per chip at bf16 models).  Dequantize happens
    # at the attention operand, where XLA fuses the widen+scale (same
    # scheme as ops.quant's weight-only path).
    kv_cache_dtype: Optional[str] = None

    def __post_init__(self):
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype must be None or 'int8'; "
                             f"got {self.kv_cache_dtype!r}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"norm must be 'layernorm' or 'rmsnorm'; "
                             f"got {self.norm!r}")
        if self.loss_seq_chunk < 0:
            raise ValueError(f"loss_seq_chunk must be >= 0; "
                             f"got {self.loss_seq_chunk}")
        if self.ffn_activation not in ("gelu", "swiglu"):
            raise ValueError(f"ffn_activation must be 'gelu' or 'swiglu'; "
                             f"got {self.ffn_activation!r}")
        if self.ffn_activation == "swiglu" and self.moe_experts > 0:
            raise ValueError("moe_experts with ffn_activation='swiglu' is "
                             "unsupported: ops.moe's expert bank is the "
                             "two-matrix gelu FFN")
        if self.pipeline_stages > 1:
            if self.num_layers % self.pipeline_stages:
                raise ValueError(
                    f"num_layers {self.num_layers} not divisible by "
                    f"pipeline_stages {self.pipeline_stages}")
            if self.moe_experts > 0:
                raise ValueError(
                    "pipeline_stages with MoE is unsupported: the router "
                    "aux-loss scalar cannot cross the same-shape pipeline "
                    "stage contract (parallel/pipeline.py)")
            if self.seq_axis is not None:
                raise ValueError(
                    "pipeline_stages with seq_axis (ring attention) is "
                    "unsupported: ring's shard_map cannot nest inside the "
                    "pipe-manual region")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        # explicit None check: 0 must be rejected (at init), not silently
        # fall back to full MHA
        return (self.num_heads if self.num_kv_heads is None
                else self.num_kv_heads)


def gpt_small(**kw) -> "GPT":
    return GPT(GPTConfig(**kw))


def _remat_policy(name: str):
    """Map the config string to a jax.checkpoint save policy (None =
    save nothing, the classic full-block remat)."""
    if name == "full":
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    if name == "dots_no_batch":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"remat_policy must be 'full', 'dots', or "
                     f"'dots_no_batch'; got {name!r}")


def gpt_tiny(**kw) -> "GPT":
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("intermediate_size", 512)
    kw.setdefault("vocab_size", 512)
    kw.setdefault("max_position", 128)
    return GPT(GPTConfig(**kw))


class GPT:
    """Functional decoder: ``init(key) -> params``,
    ``apply(params, input_ids, ...) -> [b, s, hidden]``."""

    def __init__(self, config: GPTConfig, mesh=None):
        self.config = config
        self.mesh = mesh  # only needed for the ring-attention (SP) path

    # -- init -------------------------------------------------------------
    def init(self, key) -> Dict[str, Any]:
        c = self.config
        trunc = init_lib.truncated_normal(0.02)
        k_emb, k_layers = jax.random.split(key)
        ke = jax.random.split(k_emb, 2)

        def ln():
            p = {"gamma": jnp.ones((c.hidden_size,), jnp.float32)}
            if c.norm == "layernorm":
                p["beta"] = jnp.zeros((c.hidden_size,), jnp.float32)
            return p

        def maybe_bias(shape):
            return {"bias": jnp.zeros(shape, jnp.float32)} if c.use_bias \
                else {}

        h, hd, d, i = c.num_heads, c.head_dim, c.hidden_size, \
            c.intermediate_size
        kv = c.kv_heads
        if kv < 1 or h % kv:
            raise ValueError(f"num_kv_heads must be a positive divisor of "
                             f"num_heads {h}; got {kv}")

        def one_layer(k):
            ks = jax.random.split(k, 7)
            layer = {
                "ln_1": ln(),
                "attention": {
                    "query": {"kernel": trunc(ks[0], (d, h, hd)),
                              **maybe_bias((h, hd))},
                    "key": {"kernel": trunc(ks[1], (d, kv, hd)),
                            **maybe_bias((kv, hd))},
                    "value": {"kernel": trunc(ks[2], (d, kv, hd)),
                              **maybe_bias((kv, hd))},
                    "out": {"kernel": trunc(ks[3], (h, hd, d)),
                            **maybe_bias((d,))},
                },
                "ln_2": ln(),
            }
            if c.moe_experts > 0:
                layer["moe"] = init_moe(ks[4], d, i, c.moe_experts)
            else:
                layer["ffn"] = {
                    "w_in": {"kernel": trunc(ks[4], (d, i)),
                             **maybe_bias((i,))},
                    "w_out": {"kernel": trunc(ks[5], (i, d)),
                              **maybe_bias((d,))},
                }
                if c.ffn_activation == "swiglu":
                    layer["ffn"]["w_gate"] = {
                        "kernel": trunc(ks[6], (d, i)),
                        **maybe_bias((i,))}
            return layer

        embeddings = {"word": trunc(ke[0], (c.vocab_size, c.hidden_size))}
        if c.position_embedding == "learned":
            embeddings["position"] = trunc(
                ke[1], (c.max_position, c.hidden_size))
        elif c.position_embedding != "rope":
            raise ValueError("position_embedding must be 'learned' or "
                             f"'rope'; got {c.position_embedding!r}")
        params = {
            "embeddings": embeddings,
            "decoder": jax.vmap(one_layer)(
                jax.random.split(k_layers, c.num_layers)),
            "ln_f": ln(),
        }
        if not c.tied_head:
            # HF lm_head layout [vocab, d] so logits() shares the tied
            # `hidden @ W.T` projection
            params["lm_head"] = trunc(jax.random.split(ke[1])[0],
                                      (c.vocab_size, c.hidden_size))
        return params

    # -- blocks -----------------------------------------------------------
    def _norm(self, p, x):
        """Config-dispatched block norm: LayerNorm (GPT-2) or RMSNorm
        (Llama: f32 rms, gamma scale, no centering — matches HF
        LlamaRMSNorm numerics)."""
        c = self.config
        from ..ops.pallas import resolve_fused_ln
        if c.norm == "rmsnorm":
            if resolve_fused_ln(c.fused_layernorm):
                from ..ops.pallas import fused_rmsnorm
                return fused_rmsnorm(x, p["gamma"], c.layer_norm_eps)
            xf = x.astype(jnp.float32)
            y = xf * jax.lax.rsqrt(
                jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
                + c.layer_norm_eps)
            return (y * p["gamma"]).astype(x.dtype)
        return _layer_norm(p, x, c.layer_norm_eps,
                           fused=resolve_fused_ln(c.fused_layernorm))

    def _rope_transform(self, local_seq_len: int):
        """qk_transform for this forward, or None.  Built ONCE per forward
        (apply hoists it out of the layer scan — cos/sin tables are
        identical across layers).  Under the in-shard_map ring path the
        local shard restarts at 0, so positions get the shard's global
        offset from its axis index."""
        c = self.config
        if c.position_embedding != "rope":
            return None
        positions = jnp.arange(local_seq_len)
        if c.seq_axis is not None and self.mesh is None:
            # traced inside an existing shard_map over seq_axis
            positions = (jax.lax.axis_index(c.seq_axis) * local_seq_len
                         + positions)
        cos, sin = attn_lib.rope_tables(positions, c.head_dim,
                                        base=c.rope_base)
        return lambda q, k: (attn_lib.apply_rope(q, cos, sin),
                             attn_lib.apply_rope(k, cos, sin))

    def _attention(self, p, x, mask, rng, train, qk_transform=None):
        c = self.config
        if c.seq_axis is not None and self.mesh is not None:
            # flash-vs-XLA crossover applies to the kernel's PER-CALL
            # sequence: inside the ring each call sees one shard, so the
            # gate uses the local shard length, not the global seq
            local = x.shape[1] // self.mesh.shape[c.seq_axis]
            if attn_lib.resolve_use_flash(c.use_flash, local):
                # SP x flash: ring schedule with the fused kernel per
                # block pair (parallel.ring_flash)
                from ..parallel.ring_flash import ring_flash_attention_sharded
                attention_fn = lambda q, k, v, mask=None: \
                    ring_flash_attention_sharded(
                        q, k, v, self.mesh, seq_axis=c.seq_axis,
                        causal=True)
                attention_fn.supports_gqa = True
            else:
                from ..parallel.ring import ring_attention_sharded
                attention_fn = lambda q, k, v, mask=None: \
                    ring_attention_sharded(
                        q, k, v, self.mesh, seq_axis=c.seq_axis,
                        causal=True)
        elif c.seq_axis is not None:
            # traced inside a caller's shard_map: x is already the local
            # shard, so x.shape[1] IS the per-call sequence
            if attn_lib.resolve_use_flash(c.use_flash, x.shape[1]):
                from ..parallel.ring_flash import ring_flash_attention
                attention_fn = lambda q, k, v, mask=None: \
                    ring_flash_attention(q, k, v, axis_name=c.seq_axis,
                                         causal=True)
                attention_fn.supports_gqa = True
            else:
                from ..parallel.ring import ring_attention
                attention_fn = lambda q, k, v, mask=None: ring_attention(
                    q, k, v, axis_name=c.seq_axis, causal=True)
        elif attn_lib.resolve_use_flash(c.use_flash, x.shape[1]):
            # GQA configs run natively: the kernel maps kv blocks by
            # q_head // group, so no broadcast materialises
            from ..ops.pallas.flash_attention import make_flash_attention_fn
            attention_fn = make_flash_attention_fn(causal=True)
        else:
            attention_fn = attn_lib.dot_product_attention
        return attn_lib.attention_core(
            p, x, mask=mask, dropout_rate=c.dropout_rate, rng=rng,
            train=train, attention_fn=attention_fn,
            qk_transform=qk_transform)

    def _ffn(self, p, x, rng=None, train=False):
        """Pre-LN FFN (dense or MoE): shared by the full-sequence and
        KV-cache paths so the math can never diverge between them.

        Returns ``(out, aux)`` — ``aux`` is the weighted router loss scalar
        (0 for the dense path).  Note: at KV-cache decode the MoE routes one
        token per group, so capacity never drops; full-sequence outputs
        match decode exactly only when the configured capacity drops no
        tokens (use a generous ``moe_capacity_factor`` at eval).
        """
        c = self.config
        h = self._norm(p["ln_2"], x)
        if "moe" in p:
            y, m = apply_moe(p["moe"], h, k=c.moe_top_k,
                             capacity_factor=c.moe_capacity_factor,
                             train=train, rng=rng)
            aux = (c.moe_aux_weight * m["aux_loss"]
                   + c.moe_z_weight * m["router_z_loss"])
            return y, aux
        if c.ffn_activation == "swiglu":
            return (attn_lib.ffn_swiglu_core(p["ffn"], h),
                    jnp.zeros((), jnp.float32))
        return attn_lib.ffn_core(p["ffn"], h), jnp.zeros((), jnp.float32)

    def _block(self, p, x, mask, rng, train, qk_transform=None):
        c = self.config
        r_attn, r_res, r_moe, r_drop = jax.random.split(rng, 4)
        attn_out = self._attention(
            p["attention"], self._norm(p["ln_1"], x),
            mask, r_attn, train, qk_transform=qk_transform)
        x = x + _dropout(attn_out, c.dropout_rate, r_res, train)
        ffn_out, aux = self._ffn(p, x, rng=r_moe, train=train)
        return x + _dropout(ffn_out, c.dropout_rate, r_drop, train), aux

    def _embed(self, emb, input_ids, r_emb, train):
        """Word (+ learned position) embedding, dropout, compute-dtype
        cast — ONE implementation for the plain forward and the 1F1B path
        (the gradient parity between them depends on bit-identity here)."""
        c = self.config
        s = input_ids.shape[1]
        x = jnp.take(emb["word"], input_ids, axis=0)
        if c.position_embedding == "learned":
            x = x + emb["position"][None, :s, :]
        return _dropout(x, c.dropout_rate, r_emb, train).astype(c.dtype)

    def _make_layer_fn(self, seq_len: int):
        """Decoder block fn with the RoPE transform bound and optional
        remat — shared by apply() and the 1F1B path.  The transform is
        bound via partial (not a call argument): it's a callable, which
        jax.checkpoint can't accept as a traced arg."""
        from functools import partial
        layer_fn = partial(self._block,
                           qk_transform=self._rope_transform(seq_len))
        if self.config.remat:
            layer_fn = jax.checkpoint(
                layer_fn, static_argnums=(4,),
                policy=_remat_policy(self.config.remat_policy))
        return layer_fn

    # -- full-sequence forward -------------------------------------------
    def apply(self, params, input_ids, *, train: bool = False, rng=None,
              return_aux: bool = False):
        """-> hidden [b, s, d]; with ``return_aux`` also the summed router
        aux-loss scalar (nonzero only for MoE configs)."""
        c = self.config
        if rng is None:
            if train:
                raise ValueError("GPT.apply(train=True) requires rng")
            rng = jax.random.PRNGKey(0)
        s = input_ids.shape[1]
        r_emb, r_layers = jax.random.split(rng)
        x = self._embed(params["embeddings"], input_ids, r_emb, train)
        layer_fn = self._make_layer_fn(s)
        layer_keys = jax.random.split(r_layers, c.num_layers)
        if c.pipeline_stages > 1:
            # the stage_fn builds its own mask (shard_map bodies cannot
            # capture traced values) — don't materialize one here
            x = self._pipeline_blocks(params, x, layer_keys, train, layer_fn)
            aux_total = jnp.zeros((), jnp.float32)   # MoE rejected at config
        else:
            # Ring / flash paths mask internally (causal=True); the dense
            # path gets an explicit causal mask.
            mask = (None if (c.seq_axis is not None
                             or attn_lib.resolve_use_flash(c.use_flash, s))
                    else attn_lib.causal_mask(s))

            def body(carry, inputs):
                layer_params, layer_key = inputs
                new_x, aux = layer_fn(layer_params, carry, mask, layer_key,
                                      train)
                return new_x, aux

            x, aux_per_layer = lax.scan(body, x,
                                        (params["decoder"], layer_keys))
            aux_total = jnp.sum(aux_per_layer)
        hidden = self._norm(params["ln_f"], x)
        if return_aux:
            return hidden, aux_total
        return hidden

    def _pipeline_stage_bits(self, params, layer_keys, train, layer_fn):
        """(stage_params, stage_fn) for the pipelined decoder stack.

        The scanned [L, ...] decoder stack reshapes to [S, L/S, ...] stage
        params (a local view when the store shards the leading layer dim
        ``P(pipe_axis)`` — ``partition_rules``); per-layer dropout keys ride
        along inside the stage params so every block keeps its own key.
        Note: under pp each layer key is reused for every microbatch of the
        step, so dropout masks repeat across microbatches (still random
        per layer/step); the non-pp path draws one mask over the full batch.
        The causal mask is rebuilt from the microbatch shape inside the
        stage (a closure-free constant — shard_map bodies cannot capture
        traced values).
        """
        c = self.config
        if self.mesh is None:
            raise ValueError("pipeline_stages requires GPT(config, mesh=...)")
        s_count = c.pipeline_stages
        per = c.num_layers // s_count
        stage_params = {
            "layers": jax.tree.map(
                lambda p: p.reshape(s_count, per, *p.shape[1:]),
                params["decoder"]),
            "keys": layer_keys.reshape(s_count, per, *layer_keys.shape[1:]),
        }

        def stage_fn(sp, acts):
            mask = (None if attn_lib.resolve_use_flash(c.use_flash,
                                                       acts.shape[1])
                    else attn_lib.causal_mask(acts.shape[1]))

            def body(carry, inputs):
                lp, lk = inputs
                new_x, _ = layer_fn(lp, carry, mask, lk, train)
                return new_x, None

            acts, _ = lax.scan(body, acts, (sp["layers"], sp["keys"]))
            return acts

        return stage_params, stage_fn

    def _pipeline_blocks(self, params, x, layer_keys, train, layer_fn):
        """Decoder blocks as a GPipe pipeline over ``config.pipe_axis``
        (see ``_pipeline_stage_bits`` for the stage construction)."""
        from ..parallel.pipeline import pipeline_apply
        c = self.config
        stage_params, stage_fn = self._pipeline_stage_bits(
            params, layer_keys, train, layer_fn)
        return pipeline_apply(
            stage_fn, stage_params, x, self.mesh,
            c.pipeline_microbatches or c.pipeline_stages, axis=c.pipe_axis)

    def _logits_from_word(self, word, hidden):
        """Tied-head projection against an explicit word matrix — ONE
        implementation for logits() and the 1F1B head loss (their
        gradient parity depends on bit-identity)."""
        return (hidden @ word.T.astype(hidden.dtype)).astype(jnp.float32)

    def _head_word(self, params):
        """The LM head's [vocab, d] matrix: the tied word embedding, or
        the separate ``lm_head`` for ``tied_head=False`` configs.  One
        resolver for logits(), the chunked loss, and the 1F1B head."""
        return (params["embeddings"]["word"] if self.config.tied_head
                else params["lm_head"])

    def logits(self, params, hidden):
        """LM head -> [b, s, vocab] f32 logits."""
        return self._logits_from_word(self._head_word(params), hidden)

    # -- training ---------------------------------------------------------
    def _chunked_lm_stats(self, word, hidden, targets, mask, chunk):
        """(nll_sum, hit_sum) over all tokens, computed ``chunk`` tokens at
        a time so the full ``[tokens, vocab]`` logits tensor is never live:
        each scan step projects one chunk against the head and reduces it,
        with ``jax.checkpoint`` recomputing the chunk's logits in backward.
        At GPT-2 bench shapes the unchunked f32 logits are ~2.5 GB of the
        step's peak (batch 48 x seq 256 x vocab 50257) — this caps the
        live slice at ``chunk x vocab`` and unlocks bigger batches."""
        d = hidden.shape[-1]
        h2 = hidden.reshape(-1, d)
        y2 = targets.reshape(-1)
        m2 = (jnp.ones(y2.shape, jnp.float32) if mask is None
              else mask.reshape(-1).astype(jnp.float32))
        t = h2.shape[0]
        # an over-large chunk would PAD tokens up to it and allocate a
        # bigger logits block than the unchunked path — clamp, don't cliff
        chunk = min(chunk, t)
        pad = (-t) % chunk
        if pad:
            h2 = jnp.concatenate(
                [h2, jnp.zeros((pad, d), h2.dtype)])
            y2 = jnp.concatenate([y2, jnp.zeros((pad,), y2.dtype)])
            m2 = jnp.concatenate([m2, jnp.zeros((pad,), m2.dtype)])
        n = h2.shape[0] // chunk

        @jax.checkpoint
        def stats(h_c, y_c, m_c):
            logits = self._logits_from_word(word, h_c)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, y_c[:, None], axis=-1)[:, 0]
            hits = (jnp.argmax(logits, -1) == y_c).astype(jnp.float32)
            return jnp.sum(nll * m_c), jnp.sum(hits * m_c)

        def body(carry, xs):
            nll_c, hit_c = stats(*xs)
            return (carry[0] + nll_c, carry[1] + hit_c), None

        (nll_sum, hit_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (h2.reshape(n, chunk, d), y2.reshape(n, chunk),
             m2.reshape(n, chunk)))
        return nll_sum, hit_sum

    def lm_loss_fn(self):
        """Contract for ``train.make_custom_train_step``: batch dict with
        ``input_ids`` [b, s] and optional ``loss_mask`` [b, s-1]; next-token
        targets are the shifted inputs."""

        def loss_fn(params, model_state, batch, rng, train):
            c = self.config
            ids = batch["input_ids"]
            hidden, aux = self.apply(params, ids[:, :-1], train=train,
                                     rng=rng, return_aux=True)
            targets = ids[:, 1:]
            mask = batch.get("loss_mask")
            if c.loss_seq_chunk:
                nll_sum, hit_sum = self._chunked_lm_stats(
                    self._head_word(params), hidden, targets, mask,
                    c.loss_seq_chunk)
                if mask is None:
                    count = jnp.asarray(targets.size, jnp.float32)
                    loss = nll_sum / count
                    acc = hit_sum / count
                else:
                    w = jnp.sum(mask.astype(jnp.float32))
                    loss = nll_sum / jnp.maximum(w, 1e-9)
                    acc = hit_sum / jnp.maximum(w, 1.0)
            else:
                logits = self.logits(params, hidden)
                loss = loss_lib.softmax_cross_entropy_with_integer_labels(
                    logits, targets, where=mask)
                hits = (jnp.argmax(logits, -1) == targets
                        ).astype(jnp.float32)
                if mask is not None:
                    acc = (jnp.sum(hits * mask)
                           / jnp.maximum(jnp.sum(mask), 1.0))
                else:
                    acc = jnp.mean(hits)
            metrics = {"token_accuracy": acc}
            if mask is not None:
                # normalizer for exact gradient accumulation (train.step)
                metrics["loss_weight"] = jnp.sum(mask).astype(jnp.float32)
            if self.config.moe_experts > 0:
                metrics["moe_aux"] = aux
            return loss + aux, (metrics, model_state)

        return loss_fn

    def lm_1f1b_value_and_grad(self, params, batch, rng=None,
                               train: bool = True):
        """Full-model causal-LM training pass under the hand-scheduled
        **1F1B** pipeline -> ``(loss, grads)`` with ``grads`` matching the
        ``params`` tree (what ``jax.value_and_grad(lm_loss_fn)`` returns on
        the GPipe path, at O(stages) activation memory instead of
        O(microbatches)).

        Composition: embeddings run pipe-replicated under an explicit
        ``jax.vjp`` whose cotangent is the pipeline's ``dx``; the decoder
        stages run ``parallel.pipeline.pipeline_value_and_grad``; final-LN
        + tied LM head + softmax-CE are the pipeline's ``loss_fn`` with
        ``aux_params`` (their grads come back pipe-replicated).  The tied
        word embedding accumulates BOTH paths: embed-side lookup grads +
        head-side logit grads.
        """
        c = self.config
        if c.pipeline_stages <= 1:
            raise ValueError("lm_1f1b_value_and_grad requires "
                             "pipeline_stages > 1")
        if c.loss_seq_chunk:
            import warnings
            warnings.warn(
                "loss_seq_chunk is not applied on the 1F1B path: head_loss "
                "builds full-width logits per microbatch (already 1/N of "
                "the batch).  Use the GPipe path (the normal train step) "
                "for chunked-loss memory savings.", stacklevel=2)
        from ..parallel.pipeline import pipeline_value_and_grad
        if rng is None:
            if train:
                raise ValueError("train=True requires rng")
            rng = jax.random.PRNGKey(0)
        ids = batch["input_ids"]
        inputs, targets = ids[:, :-1], ids[:, 1:]
        mask = batch.get("loss_mask")
        r_emb, r_layers = jax.random.split(rng)

        x_emb, vjp_embed = jax.vjp(
            lambda emb: self._embed(emb, inputs, r_emb, train),
            params["embeddings"])

        layer_fn = self._make_layer_fn(inputs.shape[1])
        layer_keys = jax.random.split(r_layers, c.num_layers)
        stage_params, stage_fn = self._pipeline_stage_bits(
            params, layer_keys, train, layer_fn)

        aux = {"ln_f": params["ln_f"], "word": self._head_word(params)}

        def head_loss(a, out_mb, y_mb):
            h = self._norm(a["ln_f"], out_mb)
            logits = self._logits_from_word(a["word"], h)
            return loss_lib.softmax_cross_entropy_with_integer_labels(
                logits, y_mb["t"], where=y_mb.get("m"))

        n_micro = c.pipeline_microbatches or c.pipeline_stages
        y = {"t": targets}
        weights = None
        if mask is not None:
            # masked-mean loss: each microbatch's masked mean weighs in by
            # its share of the global mask count (uniform weights would be
            # wrong whenever microbatch mask counts differ)
            y["m"] = mask
            per_mb = mask.reshape(n_micro, -1).sum(axis=1).astype(
                jnp.float32)
            # 1e-9 floor, same as ops.losses: a 1.0 floor would silently
            # shrink fractional-weight batches relative to the GPipe path
            weights = per_mb / jnp.maximum(per_mb.sum(), 1e-9)

        loss, stage_grads, aux_grads, dx = pipeline_value_and_grad(
            stage_fn, head_loss, stage_params, x_emb, y, self.mesh,
            n_micro, axis=c.pipe_axis, aux_params=aux, with_dx=True,
            microbatch_weights=weights)

        (emb_grads,) = vjp_embed(dx)
        emb_grads = dict(emb_grads)
        grads = {
            "embeddings": emb_grads,
            "decoder": jax.tree.map(
                lambda g, p: g.reshape(p.shape),
                stage_grads["layers"], params["decoder"]),
            "ln_f": aux_grads["ln_f"],
        }
        if c.tied_head:
            # tied embedding: head-side grads add to the lookup-side grads
            emb_grads["word"] = (emb_grads["word"]
                                 + aux_grads["word"].astype(
                                     emb_grads["word"].dtype))
        else:
            grads["lm_head"] = aux_grads["word"]
        return loss, grads

    # -- LoRA adapters ----------------------------------------------------
    # Low-rank per-request adapters for the serving tier (serve/ +
    # fleet/): many fine-tuned variants of one base serve from ONE set of
    # base weights.  An adapter adds rank-r deltas to the four attention
    # projections — q/k/v get x @ a @ b added to the projection output
    # BEFORE RoPE (both are linear, so this equals projecting with the
    # merged kernel W + a@b, pinned by ``merge_lora`` parity tests); the
    # out projection gets attn @ a @ b.  Adapters live in a fixed-
    # capacity STACKED table ([T, L, ...] leaves) indexed by a traced
    # per-row slot -> table-row vector, so loading, evicting, and
    # swapping adapters never changes any compiled executable
    # (serve.adapters.AdapterTable is the host-side manager).  Row 0 is
    # reserved all-zero: ``adapter_id=None`` requests resolve to it and
    # their delta is an exact zero — output tokens identical to an
    # adapter-free engine.

    _LORA_TARGETS = ("query", "key", "value", "out")

    def lora_shapes(self, rank: int) -> Dict[str, Any]:
        """{target: (a_shape, b_shape)} for ONE layer of a rank-``rank``
        adapter (the per-adapter leaves prepend [num_layers], the table
        leaves [capacity, num_layers])."""
        c = self.config
        h, hd, d = c.num_heads, c.head_dim, c.hidden_size
        kv = c.kv_heads
        return {
            "query": ((d, rank), (rank, h, hd)),
            "key": ((d, rank), (rank, kv, hd)),
            "value": ((d, rank), (rank, kv, hd)),
            "out": ((h, hd, rank), (rank, d)),
        }

    def init_lora(self, key, rank: int, scale: float = 1.0):
        """One adapter: {target: {a, b}} with [L, ...] leaves.  Standard
        LoRA init — ``a`` ~ N(0, 0.02) truncated, ``b`` zeros, so a fresh
        adapter is a no-op until trained/loaded; bake any alpha/r scaling
        into ``b`` (``scale`` multiplies ``a`` for synthetic tests)."""
        if rank < 1:
            raise ValueError(f"rank must be >= 1; got {rank}")
        c = self.config
        trunc = init_lib.truncated_normal(0.02)
        keys = jax.random.split(key, len(self._LORA_TARGETS))
        adapter = {}
        for k_t, (name, (a_shape, b_shape)) in zip(
                keys, self.lora_shapes(rank).items()):
            adapter[name] = {
                "a": trunc(k_t, (c.num_layers,) + a_shape) * scale,
                "b": jnp.zeros((c.num_layers,) + b_shape, jnp.float32),
            }
        return adapter

    def init_lora_table(self, capacity: int, rank: int):
        """All-zero stacked adapter table: {target: {a, b}} with
        [capacity, L, ...] leaves.  Row 0 is the reserved zero adapter
        (``adapter_id=None``) — never write it."""
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2 (row 0 is the "
                             f"reserved zero adapter); got {capacity}")
        c = self.config
        return {name: {"a": jnp.zeros((capacity, c.num_layers) + a_shape,
                                      jnp.float32),
                       "b": jnp.zeros((capacity, c.num_layers) + b_shape,
                                      jnp.float32)}
                for name, (a_shape, b_shape)
                in self.lora_shapes(rank).items()}

    @staticmethod
    def lora_insert_row(table, row, adapter):
        """Splice one adapter into table row ``row`` (traced index —
        ONE executable loads every row; jit with the table donated)."""
        def splice(buf, leaf):
            starts = (jnp.asarray(row, jnp.int32),) \
                + (jnp.int32(0),) * leaf.ndim
            return lax.dynamic_update_slice(
                buf, leaf[None].astype(buf.dtype), starts)
        return jax.tree.map(splice, table, adapter)

    def merge_lora(self, params, adapter):
        """Base params with the adapter's deltas MERGED into the four
        attention projection kernels — the exactness oracle: running the
        merged params adapter-free must match running the base params
        with the adapter applied per-request."""
        merged = jax.tree.map(lambda x: x, params)   # shallow-ish copy
        dec_p = dict(merged["decoder"])
        for name in self._LORA_TARGETS:
            a, b = adapter[name]["a"], adapter[name]["b"]
            if name == "out":
                delta = jnp.einsum("lhkr,lrd->lhkd", a, b)
            else:
                delta = jnp.einsum("ldr,lrhk->ldhk", a, b)
            attn = dict(dec_p["attention"])
            attn[name] = dict(attn[name],
                              kernel=attn[name]["kernel"] + delta)
            dec_p["attention"] = attn
        merged["decoder"] = dec_p
        return merged

    def _lora_deltas(self, adapters, adapter_rows, i, dtype):
        """Per-row rank-r projection deltas for layer ``i``:
        {target: fn(x) -> delta}.  ``adapters``: stacked [T, L, ...]
        table leaves; ``adapter_rows`` [b]: each batch row's table row.
        The gathers are [b, ...] slices of a tiny table — the einsum
        chain is O(b·s·d·r), negligible beside the dense projection."""
        def gathered(name):
            a = lax.dynamic_index_in_dim(adapters[name]["a"], i, 1,
                                         keepdims=False)     # [T, ...]
            b = lax.dynamic_index_in_dim(adapters[name]["b"], i, 1,
                                         keepdims=False)
            return (jnp.take(a, adapter_rows, axis=0).astype(dtype),
                    jnp.take(b, adapter_rows, axis=0).astype(dtype))

        def qkv_delta(name):
            a, b = gathered(name)                 # [b,d,r], [b,r,h,hd]
            def fn(x):                            # x: [b, s, d]
                t = jnp.einsum("bsd,bdr->bsr", x, a)
                return jnp.einsum("bsr,brhk->bshk", t, b)
            return fn

        def out_delta():
            a, b = gathered("out")                # [b,h,hd,r], [b,r,d]
            def fn(attn):                         # attn: [b, s, h, hd]
                t = jnp.einsum("bshk,bhkr->bsr", attn, a)
                return jnp.einsum("bsr,brd->bsd", t, b)
            return fn

        return {"query": qkv_delta("query"), "key": qkv_delta("key"),
                "value": qkv_delta("value"), "out": out_delta()}

    # -- KV-cache decode --------------------------------------------------
    def init_cache(self, batch_size: int, max_len: Optional[int] = None):
        c = self.config
        max_len = max_len or c.max_position
        # kv_heads, not num_heads: GQA's cache is the whole point
        shape = (c.num_layers, batch_size, max_len, c.kv_heads, c.head_dim)
        if c.kv_cache_dtype == "int8":
            sshape = shape[:-1] + (1,)   # per-(token, head) scale
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32),
                    "pos": jnp.zeros((), jnp.int32)}
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "pos": jnp.zeros((), jnp.int32)}

    @staticmethod
    def _cache_kv(cache):
        """The scan-carried K/V subtree of a cache dict (everything but
        the position pointer)."""
        return {k: v for k, v in cache.items() if k != "pos"}

    def _dequant_layer_kv(self, kv, i):
        """Layer ``i``'s (k, v) read from the carried cache subtree, in
        the compute dtype — dequantizing int8 entries at the operand
        (XLA fuses the widen+scale into the attention einsum)."""
        k_all = lax.dynamic_index_in_dim(kv["k"], i, keepdims=False)
        v_all = lax.dynamic_index_in_dim(kv["v"], i, keepdims=False)
        if "k_scale" not in kv:
            return k_all, v_all
        from ..ops import quant
        dtype = self.config.dtype
        ks = lax.dynamic_index_in_dim(kv["k_scale"], i, keepdims=False)
        vs = lax.dynamic_index_in_dim(kv["v_scale"], i, keepdims=False)
        return (quant.dequantize_tensor(quant.QTensor(k_all, ks), dtype),
                quant.dequantize_tensor(quant.QTensor(v_all, vs), dtype))

    def _paged_layer_kv(self, kv, i, page_tab):
        """Layer ``i``'s (k, v) read from a PAGE POOL through per-row
        page tables, in the compute dtype.

        ``kv``: pool subtree with ``[L, num_pages, page_size, kv_heads,
        ...]`` leaves (serve/pages.py); ``page_tab`` [b, pages_per_row]
        int32: row r's logical page j lives at pool page
        ``page_tab[r, j]``.  The traced gather materializes the same
        ``[b, view_len, kv_heads, head_dim]`` operand the contiguous
        slot cache hands attention (``view_len = pages_per_row *
        page_size``), so downstream attention math — int8 dequant at
        the operand included — is IDENTICAL to the stripe layout's; the
        indirection swaps per-slot worst-case stripes for pay-as-you-go
        pages without touching the compiled attention."""
        def view(name):
            layer = lax.dynamic_index_in_dim(kv[name], i, keepdims=False)
            g = jnp.take(layer, page_tab, axis=0)   # [b, mp, pg, kvh, x]
            return g.reshape(g.shape[0], g.shape[1] * g.shape[2],
                             *g.shape[3:])
        k_all, v_all = view("k"), view("v")
        if "k_scale" not in kv:
            return k_all, v_all
        from ..ops import quant
        dtype = self.config.dtype
        return (quant.dequantize_tensor(
                    quant.QTensor(k_all, view("k_scale")), dtype),
                quant.dequantize_tensor(
                    quant.QTensor(v_all, view("v_scale")), dtype))

    def decode_step(self, params, cache, token_ids, kv_valid=None,
                    positions=None):
        """One token through the stack against the cache.

        token_ids: [b] int32 — the token at position ``cache['pos']``.
        Returns (logits [b, vocab] f32, new cache).  Static shapes: cache
        reads are masked by position, writes are ``dynamic_update_slice``.

        Ragged-prompt serving (``generate(prompt_valid=...)``): ``kv_valid``
        [b, max_len] additionally masks per-row cache positions (left-pad
        slots), and ``positions`` [b] supplies per-row position indices
        (cache position minus the row's pad length) so learned/RoPE
        embeddings see each row's REAL token positions.
        """
        c = self.config
        b = token_ids.shape[0]
        pos = cache["pos"]
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)[:, None, :]   # [b,1,d]
        if c.position_embedding == "learned":
            if positions is not None:
                x = x + jnp.take(emb["position"], positions,
                                 axis=0)[:, None, :]
            else:
                x = x + lax.dynamic_slice_in_dim(emb["position"], pos,
                                                 1)[None]
        x = x.astype(c.dtype)

        max_len = cache["k"].shape[2]
        # keys at positions > pos are zeros/garbage — mask them out
        # (additive 0/-inf convention of ops.attention)
        kv_mask = jnp.where(jnp.arange(max_len) <= pos, 0.0,
                            attn_lib.NEG_INF)[None, None, None, :]
        if kv_valid is not None:
            kv_mask = kv_mask + jnp.where(kv_valid, 0.0, attn_lib.NEG_INF
                                          )[:, None, None, :]

        # rope tables built ONCE per call, not once per layer (cos/sin are
        # identical across the layer scan — same hoist as _rope_transform)
        rope_cs = None
        if c.position_embedding == "rope":
            # rotate q and THIS k at its own position; cached keys were
            # rotated when written, matching the full-sequence path
            pos1 = (positions[:, None] if positions is not None
                    else jnp.full((1,), pos))
            rope_cs = attn_lib.rope_tables(pos1, c.head_dim,
                                           base=c.rope_base)

        def attention(q, k_blk, v_blk, kv, i):
            del k_blk, v_blk   # single token: read back through the cache
            k_cache, v_cache = self._dequant_layer_kv(kv, i)
            # GQA handled natively by the dense kernel (grouped einsum
            # against the unrepeated cache — no full-head materialization)
            return attn_lib.dot_product_attention(q, k_cache, v_cache,
                                                  mask=kv_mask)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=pos, rope_cs=rope_cs,
                                     attention=attention), None

        (x, new_kv), _ = lax.scan(
            body, (x, self._cache_kv(cache)),
            (params["decoder"], jnp.arange(c.num_layers)))
        x = self._norm(params["ln_f"], x)
        logits = self.logits(params, x)[:, 0, :]
        return logits, dict(new_kv, pos=pos + 1)

    def decode_step_slots(self, params, kv, token_ids, write_col,
                          kv_valid, positions, adapters=None,
                          adapter_rows=None):
        """One token per row against a SLOT cache (continuous batching).

        The serving tier's hot step (serve/): ``kv`` is a position-free
        cache subtree ({k, v[, k_scale, v_scale]} — the ``init_cache``
        layout minus ``pos``) whose batch dimension is a bank of SLOTS,
        each holding an independent request.  Per-row state replaces the
        scalar ``pos``: row r's incoming token is written at column
        ``write_col[r]`` (per-row scatter, see ``_cache_layer``),
        attention sees the columns flagged in ``kv_valid[r]`` plus the
        token's own column, and ``positions[r]`` supplies the row's
        position index — the token count, which differs from
        ``write_col`` when the slot was spliced from a LEFT-padded
        ragged prefill.  Per row the math is exactly ``decode_step`` at
        ``pos = write_col[r]``, and every op is row-independent, so
        admitting or retiring one slot cannot change another slot's
        logits (bit-identity pinned by tests/test_serve.py).

        Returns (logits [b, vocab] f32, new kv).  State advancement —
        marking the written column valid, bumping write_col/positions —
        is the caller's job (serve.slots.decode_slots_step), because
        only the scheduler knows which rows are live.

        ``adapters`` / ``adapter_rows`` [b]: per-row LoRA deltas from a
        stacked adapter table (see the LoRA section above) — row r runs
        table row ``adapter_rows[r]``'s adapter; row 0 of the table is
        the zero adapter, so mixing adapter and non-adapter requests in
        one tick costs one gather, never a recompile.
        """
        c = self.config
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)[:, None, :]  # [b,1,d]
        if c.position_embedding == "learned":
            x = x + jnp.take(emb["position"], positions,
                             axis=0)[:, None, :]
        x = x.astype(c.dtype)

        max_len = kv["k"].shape[2]
        valid = kv_valid | (jnp.arange(max_len)[None, :]
                            == write_col[:, None])
        kv_mask = jnp.where(valid, 0.0, attn_lib.NEG_INF)[:, None, None, :]

        rope_cs = None
        if c.position_embedding == "rope":
            rope_cs = attn_lib.rope_tables(positions[:, None], c.head_dim,
                                           base=c.rope_base)

        def attention(q, k_blk, v_blk, kv, i):
            del k_blk, v_blk   # single token: read back through the cache
            k_cache, v_cache = self._dequant_layer_kv(kv, i)
            return attn_lib.dot_product_attention(q, k_cache, v_cache,
                                                  mask=kv_mask)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=write_col, rope_cs=rope_cs,
                                     attention=attention,
                                     adapters=adapters,
                                     adapter_rows=adapter_rows), None

        (x, new_kv), _ = lax.scan(
            body, (x, dict(kv)),
            (params["decoder"], jnp.arange(c.num_layers)))
        x = self._norm(params["ln_f"], x)
        return self.logits(params, x)[:, 0, :], new_kv

    def decode_step_slots_paged(self, params, kv, token_ids, page_tab,
                                write_col, kv_valid, positions,
                                adapters=None, adapter_rows=None,
                                use_kernel: bool = False):
        """``decode_step_slots`` against a PAGED slot cache.

        Same per-row semantics as ``decode_step_slots`` — row r's token
        writes at its logical column ``write_col[r]``, attends
        ``kv_valid[r]`` plus its own column, embeds at ``positions[r]``
        — but the K/V live in a shared page pool (``kv``: ``[L,
        num_pages, page_size, ...]`` leaves) indexed by the per-row
        ``page_tab`` [b, pages_per_row]: reads gather each row's pages
        into the usual ``[b, view_len, ...]`` operand
        (``_paged_layer_kv``), the write scatters into pool cell
        ``(page_tab[r, write_col[r] // page_size], write_col[r] %
        page_size)``.  Both the table and the column state are traced,
        so page allocation, shared-prefix mapping, and slot retirement
        never change the compiled step (serve/pages.py owns the host
        bookkeeping).  Rows whose table maps the reserved trash page 0
        are retired: their writes land where no validity mask looks.

        Returns (logits [b, vocab] f32, new kv pool).  Per row the math
        is exactly ``decode_step_slots``'s on the gathered view — the
        serve tier's paged==contiguous bit-identity tests hold it
        there.

        ``use_kernel`` (STATIC, resolved by the caller through
        ``attn_lib.resolve_use_paged_kernel``): read the pool through
        the fused Pallas kernel (ops/pallas/paged_attention.py) — the
        page walk happens inside the attention loop and the gathered
        ``[b, view_len, ...]`` operand never materializes.  The write
        path is the same either way; tests pin kernel == gather token
        streams bit-for-bit.
        """
        c = self.config
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)[:, None, :]  # [b,1,d]
        if c.position_embedding == "learned":
            x = x + jnp.take(emb["position"], positions,
                             axis=0)[:, None, :]
        x = x.astype(c.dtype)

        page_size = kv["k"].shape[2]
        view_len = page_tab.shape[1] * page_size
        valid = kv_valid | (jnp.arange(view_len)[None, :]
                            == write_col[:, None])
        kv_mask = jnp.where(valid, 0.0, attn_lib.NEG_INF)[:, None, None, :]

        rope_cs = None
        if c.position_embedding == "rope":
            rope_cs = attn_lib.rope_tables(positions[:, None], c.head_dim,
                                           base=c.rope_base)

        # write cell per row, from the traced table (clamped index: a
        # full slot's frozen write head cannot run off its table row)
        page_idx = jnp.minimum(write_col // page_size,
                               page_tab.shape[1] - 1)
        w_pages = jnp.take_along_axis(page_tab, page_idx[:, None],
                                      axis=1)[:, 0]
        paged = (w_pages, write_col % page_size)

        def attention(q, k_blk, v_blk, kv, i):
            del k_blk, v_blk   # single token: read back through the pool
            if use_kernel:
                from ..ops.pallas import paged_attention as paged_lib
                return paged_lib.paged_decode_attention(q, kv, i,
                                                        page_tab, valid)
            k_cache, v_cache = self._paged_layer_kv(kv, i, page_tab)
            return attn_lib.dot_product_attention(q, k_cache, v_cache,
                                                  mask=kv_mask)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=None, rope_cs=rope_cs,
                                     attention=attention,
                                     adapters=adapters,
                                     adapter_rows=adapter_rows,
                                     paged=paged), None

        (x, new_kv), _ = lax.scan(
            body, (x, dict(kv)),
            (params["decoder"], jnp.arange(c.num_layers)))
        x = self._norm(params["ln_f"], x)
        return self.logits(params, x)[:, 0, :], new_kv

    def _cache_layer(self, p, x, kv, i, *, write_pos, rope_cs,
                     attention, adapters=None, adapter_rows=None,
                     paged=None):
        """ONE decoder layer of the KV-cache path — shared by decode_step
        (s=1 against the cache) and decode_block (whole-prompt prefill)
        so the layer math can never diverge between them.  The cache
        subtree ``kv`` ({k, v[, k_scale, v_scale]}) rides the scan
        CARRY, not the scanned ys: as ys each layer would write its FULL
        [b, max_len, h, d] cache back out every call when only
        ``write_pos`` onward changes; as carry the updates are in-place
        slice writes.  When scale entries are present the write
        quantizes to symmetric per-(token, head) int8 (the
        ``kv_cache_dtype="int8"`` decode-bandwidth lever).

        ``attention(q, k_blk, v_blk, kv, i)`` supplies the step/block-
        specific attention read; ``rope_cs``: (cos, sin) tables hoisted
        out of the layer scan.

        ``adapters``/``adapter_rows``: per-row LoRA projection deltas
        (see the LoRA section) — q/k/v deltas add BEFORE RoPE so the
        result equals projecting with the merged kernel.

        ``write_pos`` may be a scalar (one column for the whole batch —
        the generate/beam path) or a [b] vector (per-row columns — the
        slot-serving path, ``decode_step_slots``): vector positions
        write by scatter, one (row, column-run) per batch row, so slots
        at different sequence lengths share one compiled step.

        ``paged``: (page_ids [N], offs [N]) with N = b*s — the cache is
        a PAGE POOL ([L, num_pages, page_size, kv_heads, ...] leaves,
        serve/pages.py) and token t of the flattened (b, s) window
        writes at pool cell ``(page_ids[t], offs[t])`` instead of a
        column of a per-row stripe.  The traced indices come from a
        per-slot page table, so every (slot, page) assignment runs the
        SAME executable; ``write_pos`` is ignored for the write (reads
        still gather through the table in ``attention``).
        """
        h = self._norm(p["ln_1"], x)
        a = p["attention"]
        dtype = h.dtype
        lora = (self._lora_deltas(adapters, adapter_rows, i, dtype)
                if adapters is not None else None)

        def proj(name):
            pp = a[name]
            y = jnp.einsum("bsd,dhk->bshk", h,
                           pp["kernel"].astype(dtype))
            if lora is not None:
                y = y + lora[name](h)
            if "bias" in pp:
                y = y + pp["bias"].astype(dtype)
            return y

        q, k, v = proj("query"), proj("key"), proj("value")
        if rope_cs is not None:
            q = attn_lib.apply_rope(q, *rope_cs)
            k = attn_lib.apply_rope(k, *rope_cs)
        zero = jnp.zeros((), jnp.int32)
        per_row = paged is None and jnp.ndim(write_pos) == 1
        if per_row:
            b, s = x.shape[:2]
            if s == 1:
                # single-token serving step: a per-row masked overwrite
                # of the layer slice beats XLA's general scatter
                # (measured ~1.5x on CPU), and the slice is read back by
                # attention anyway.  hit: [b, max_len, 1, 1]
                max_len = kv["k"].shape[2]
                hit = (jnp.arange(max_len)[None, :]
                       == write_pos[:, None])[:, :, None, None]
            else:
                rows = jnp.arange(b)[:, None]                      # [b,1]
                cols = write_pos[:, None] + jnp.arange(s)[None, :]  # [b,s]

        def row_write(name, val):
            """Per-row positions: masked layer overwrite for s=1, a
            scatter for window writes.  Out-of-bounds columns (a slot
            past max_len) hit nothing / are dropped — never clamped
            onto live entries."""
            if s == 1:
                layer = lax.dynamic_index_in_dim(kv[name], i,
                                                 keepdims=False)
                layer = jnp.where(hit, val.astype(layer.dtype), layer)
                kv[name] = lax.dynamic_update_slice(
                    kv[name], layer[None], (i,) + (zero,) * layer.ndim)
            else:
                kv[name] = kv[name].at[i, rows, cols].set(
                    val.astype(kv[name].dtype))

        def page_write(name, val):
            """Pool-cell scatter: the flattened (b, s) tokens land at
            ``(page_ids[t], offs[t])`` of layer ``i``'s pool plane —
            scattered on the LAYER slice, then slice-written back, so
            XLA never lowers a scatter over the whole [L, ...] pool
            (same layer-slice trick as the contiguous ``row_write``).
            Live slots always map disjoint write cells (a slot's write
            page is private — serve/pages.py); retired rows map the
            reserved trash page 0, whose cells no validity mask ever
            admits, so their frozen writes are dead weight, not state."""
            flat = val.reshape((-1,) + val.shape[2:])
            layer = lax.dynamic_index_in_dim(kv[name], i, keepdims=False)
            layer = layer.at[paged].set(flat.astype(layer.dtype))
            kv[name] = lax.dynamic_update_slice(
                kv[name], layer[None],
                (i,) + (jnp.int32(0),) * layer.ndim)

        def write(name, val):
            if "k_scale" in kv:
                # ONE quantization scheme repo-wide: ops.quant's
                # symmetric int8 with a per-(token, head) scale (the
                # last axis is the reduced one)
                from ..ops import quant
                qt = quant.quantize_tensor(val, reduce_axes=(-1,))
                if paged is not None:
                    page_write(name, qt.q)
                    page_write(name + "_scale", qt.scale)
                elif per_row:
                    row_write(name, qt.q)
                    row_write(name + "_scale", qt.scale)
                else:
                    kv[name] = lax.dynamic_update_slice(
                        kv[name], qt.q[None],
                        (i, zero, write_pos, zero, zero))
                    kv[name + "_scale"] = lax.dynamic_update_slice(
                        kv[name + "_scale"], qt.scale[None],
                        (i, zero, write_pos, zero, zero))
            elif paged is not None:
                page_write(name, val)
            elif per_row:
                row_write(name, val)
            else:
                kv[name] = lax.dynamic_update_slice(
                    kv[name], val[None].astype(kv[name].dtype),
                    (i, zero, write_pos, zero, zero))

        kv = dict(kv)
        write("k", k)
        write("v", v)
        attn = attention(q, k, v, kv, i)
        attn_out = jnp.einsum("bshk,hkd->bsd", attn,
                              a["out"]["kernel"].astype(dtype))
        if lora is not None:
            attn_out = attn_out + lora["out"](attn)
        if "bias" in a["out"]:
            attn_out = attn_out + a["out"]["bias"].astype(dtype)
        x = x + attn_out
        ffn_out, _ = self._ffn(p, x)   # aux unused at decode
        return x + ffn_out, kv

    def decode_block(self, params, cache, token_ids, kv_valid=None,
                     positions=None):
        """Prefill: push a WHOLE [b, s] prompt block through the stack
        into an EMPTY cache in one forward — one batched matmul pass per
        layer instead of ``s`` sequential ``decode_step`` calls, which is
        the difference between 1 dispatch and ``s`` dependent MXU-starved
        steps for long prompts (time-to-first-token).

        Requires ``cache['pos'] == 0`` (the generate/beam_search prefill
        call sites — the in-block causal mask assumes the cache holds
        nothing before the block).  ``kv_valid`` [b, s]: per-row validity
        of the block columns (left-padded ragged prompts); ``positions``
        [b, s]: per-row position indices for learned/RoPE embeddings.
        Returns (logits [b, vocab] f32 at the LAST block position, cache
        with pos advanced by ``s``).
        """
        c = self.config
        b, s = token_ids.shape
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)            # [b,s,d]
        if c.position_embedding == "learned":
            pos_idx = (positions if positions is not None
                       else jnp.arange(s))
            x = x + jnp.take(emb["position"], pos_idx, axis=0)
        x = x.astype(c.dtype)

        # The cache beyond the block is empty, so attention reads the
        # block's own keys — s x s scores, never s x max_len.  Past the
        # measured crossover the causal no-padding case dispatches the
        # fused flash kernel exactly like the full forward; ragged
        # prompts need the per-row pad mask, which the dense path takes
        # additively.
        if kv_valid is None and attn_lib.resolve_use_flash(c.use_flash, s):
            from ..ops.pallas.flash_attention import make_flash_attention_fn
            flash_fn = make_flash_attention_fn(causal=True)

            def block_attn(q, k_blk, v_blk, kv, i):
                del kv, i
                return flash_fn(q, k_blk, v_blk)
        else:
            mask = attn_lib.causal_mask(s)
            if kv_valid is not None:
                mask = mask + attn_lib.padding_mask(kv_valid)

            def block_attn(q, k_blk, v_blk, kv, i):
                del kv, i
                return attn_lib.dot_product_attention(q, k_blk, v_blk,
                                                      mask=mask)

        rope_cs = None
        if c.position_embedding == "rope":
            rope_pos = (positions if positions is not None
                        else jnp.arange(s))
            rope_cs = attn_lib.rope_tables(rope_pos, c.head_dim,
                                           base=c.rope_base)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=jnp.zeros((), jnp.int32),
                                     rope_cs=rope_cs,
                                     attention=block_attn), None

        (x, new_kv), _ = lax.scan(
            body, (x, self._cache_kv(cache)),
            (params["decoder"], jnp.arange(c.num_layers)))
        # head on the last position only — [b, s, vocab] never materializes
        x = self._norm(params["ln_f"], x[:, -1:, :])
        logits = self.logits(params, x)[:, 0, :]
        return logits, dict(new_kv, pos=cache["pos"] + s)

    def decode_window(self, params, cache, token_ids, head: str = "all",
                      adapters=None, adapter_rows=None):
        """``s`` tokens against a NON-empty cache in one forward.

        The generalization of ``decode_block`` to ``cache['pos'] > 0``:
        row ``j`` of the window attends every cache column ``<= pos + j``
        (prefix plus in-window causal), K/V are written at columns
        ``pos..pos+s-1``.  This is the verification step of speculative
        decoding (models/speculative.py): the target model scores all
        draft tokens in ONE dispatch instead of s sequential
        decode_steps.  Rollback is the caller's job: setting ``pos`` back
        masks (and later overwrites) any rejected columns.

        ``head``: what the LM head computes — ``"all"`` ([b, s, vocab]
        f32, the verification shape), ``"last"`` ([b, vocab], prefill's
        next-token shape), ``"none"`` (logits is None — intermediate
        chunked-prefill windows only feed the cache, and the [b, s,
        vocab] tensor must not materialize for them).

        ``adapters``/``adapter_rows`` [b]: per-row LoRA deltas (see the
        LoRA section) — the serve tier prefills each request under its
        own adapter through this path.
        """
        if head not in ("all", "last", "none"):
            raise ValueError(f"head must be all|last|none; got {head!r}")
        c = self.config
        b, s = token_ids.shape
        pos = cache["pos"]
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)            # [b,s,d]
        win_pos = pos + jnp.arange(s)
        if c.position_embedding == "learned":
            x = x + jnp.take(emb["position"], win_pos, axis=0)
        x = x.astype(c.dtype)

        max_len = cache["k"].shape[2]
        # col visible to window row j iff col <= pos + j
        col = jnp.arange(max_len)[None, None, None, :]
        row = win_pos[None, None, :, None]
        kv_mask = jnp.where(col <= row, 0.0, attn_lib.NEG_INF)

        rope_cs = None
        if c.position_embedding == "rope":
            rope_cs = attn_lib.rope_tables(win_pos, c.head_dim,
                                           base=c.rope_base)

        def window_attn(q, k_blk, v_blk, kv, i):
            del k_blk, v_blk   # read back through the cache (prefix + win)
            k_cache, v_cache = self._dequant_layer_kv(kv, i)
            return attn_lib.dot_product_attention(q, k_cache, v_cache,
                                                  mask=kv_mask)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=pos, rope_cs=rope_cs,
                                     attention=window_attn,
                                     adapters=adapters,
                                     adapter_rows=adapter_rows), None

        (x, new_kv), _ = lax.scan(
            body, (x, self._cache_kv(cache)),
            (params["decoder"], jnp.arange(c.num_layers)))
        new_cache = dict(new_kv, pos=pos + s)
        if head == "none":
            return None, new_cache
        if head == "last":
            x = self._norm(params["ln_f"], x[:, -1:, :])
            return self.logits(params, x)[:, 0, :], new_cache
        x = self._norm(params["ln_f"], x)
        return self.logits(params, x), new_cache

    def decode_window_paged(self, params, kv, token_ids, page_row, pos,
                            head: str = "all", adapters=None,
                            adapter_rows=None, use_kernel: bool = False):
        """``decode_window`` against a PAGED cache: a batch-1 window of
        ``s`` tokens at positions ``pos..pos+s-1``, reading and writing
        the shared page pool through one request's ``page_row``
        [pages_per_row] int32.

        The serve tier's chunked-prefill step under paging
        (serve/pages.py): ``pos`` is a TRACED scalar, so a request that
        maps shared prefix pages simply starts its first window at
        ``pos = skip`` — the skipped windows are never dispatched, yet
        row j still attends every cache column ``<= pos + j`` (shared
        pages included).

        Structure: gather the row's pages ONCE into a contiguous
        ``[L, 1, view_len, ...]`` stripe, run the UNMODIFIED
        ``decode_window`` on it (so the window math is the contiguous
        engine's to the bit — and the layer scan carries one stripe,
        never the whole pool), then scatter the ``s`` written columns
        back to their pool cells ``(page_row[c // page_size], c %
        page_size)``.  Pad columns of the last window map whatever
        ``page_row`` holds there (the reserved trash page 0 when
        unallocated) — written but never valid, exactly the contiguous
        path's dead-weight pads.

        ``head`` as in ``decode_window``.  Returns (logits, new kv
        pool) — the pool subtree carries no ``pos``; the caller owns
        positions (serve/scheduler tracks them host-side).

        ``use_kernel`` (STATIC): skip the stripe entirely — K/V write
        straight into their pool cells (the same ``_cache_layer``
        page-write the per-token step uses) and attention walks the
        page table inside the fused Pallas kernel
        (``ops.pallas.paged_window_attention``), causal against the
        traced ``pos``.  No ``[L, 1, view_len, ...]`` stripe, no
        scatter-back.
        """
        if head not in ("all", "last", "none"):
            raise ValueError(f"head must be all|last|none; got {head!r}")
        b, s = token_ids.shape
        if b != 1:
            raise ValueError(f"decode_window_paged is batch-1 (one page "
                             f"row = one request); got batch {b}")
        page_size = kv["k"].shape[2]
        if use_kernel:
            return self._decode_window_paged_kernel(
                params, kv, token_ids, page_row, pos, head=head,
                adapters=adapters, adapter_rows=adapter_rows)

        def gather(name):
            g = jnp.take(kv[name], page_row, axis=1)  # [L, mp, pg, ...]
            return g.reshape(g.shape[0], 1, g.shape[1] * g.shape[2],
                             *g.shape[3:])
        view = {name: gather(name) for name in kv}
        logits, view = self.decode_window(
            params, dict(view, pos=pos), token_ids, head=head,
            adapters=adapters, adapter_rows=adapter_rows)

        cols = pos + jnp.arange(s)
        pids = jnp.take(page_row, cols // page_size)
        offs = cols % page_size
        new_kv = {}
        for name in kv:
            vals = jnp.take(view[name][:, 0], cols, axis=1)  # [L, s, ...]
            new_kv[name] = kv[name].at[:, pids, offs].set(vals)
        return logits, new_kv

    def _decode_window_paged_kernel(self, params, kv, token_ids,
                                    page_row, pos, *, head,
                                    adapters=None, adapter_rows=None):
        """``decode_window_paged``'s fused-kernel body: the
        ``decode_window`` structure (embed at ``pos + j``, RoPE at the
        window positions, write-then-attend per layer, same head
        modes), but the cache is the POOL — writes land on their pool
        cells via ``_cache_layer``'s page-write, reads walk ``page_row``
        inside ``ops.pallas.paged_window_attention`` with the
        ``col <= pos + j`` causal mask computed in-kernel."""
        from ..ops.pallas import paged_attention as paged_lib
        c = self.config
        b, s = token_ids.shape
        emb = params["embeddings"]
        x = jnp.take(emb["word"], token_ids, axis=0)            # [1,s,d]
        win_pos = pos + jnp.arange(s)
        if c.position_embedding == "learned":
            x = x + jnp.take(emb["position"], win_pos, axis=0)
        x = x.astype(c.dtype)

        rope_cs = None
        if c.position_embedding == "rope":
            rope_cs = attn_lib.rope_tables(win_pos, c.head_dim,
                                           base=c.rope_base)

        page_size = kv["k"].shape[2]
        cols = pos + jnp.arange(s)
        pids = jnp.take(page_row, cols // page_size)
        paged = (pids, cols % page_size)

        def window_attn(q, k_blk, v_blk, kv, i):
            del k_blk, v_blk   # read back through the pool (prefix + win)
            return paged_lib.paged_window_attention(q, kv, i, page_row,
                                                    pos)

        def body(carry, inputs):
            x, kv = carry
            p, i = inputs
            return self._cache_layer(p, x, kv, i,
                                     write_pos=None, rope_cs=rope_cs,
                                     attention=window_attn,
                                     adapters=adapters,
                                     adapter_rows=adapter_rows,
                                     paged=paged), None

        (x, new_kv), _ = lax.scan(
            body, (x, dict(kv)),
            (params["decoder"], jnp.arange(c.num_layers)))
        if head == "none":
            return None, new_kv
        if head == "last":
            x = self._norm(params["ln_f"], x[:, -1:, :])
            return self.logits(params, x)[:, 0, :], new_kv
        x = self._norm(params["ln_f"], x)
        return self.logits(params, x), new_kv

    def prefill_cache(self, params, cache, token_ids,
                      chunk: Optional[int] = None):
        """Prompt ingestion into an empty cache, optionally CHUNKED.

        ``chunk=None``: one ``decode_block`` forward (s x s attention —
        the fast path while the whole prompt's attention fits).
        ``chunk=W``: the prompt streams through ``decode_window`` W
        tokens at a time, each window attending the cached prefix plus
        itself — live attention memory is bounded by W x max_len
        instead of s x s, the long-context serving shape (a 32k prompt
        prefills at the memory of its window).  Exact parity with the
        one-block path (tests/test_gpt.py::test_chunked_prefill_*) —
        except under ``kv_cache_dtype="int8"``, where each window reads
        its own K/V back through the quantized cache (one rounding step
        the single-block path's in-block attention doesn't take), so
        chunked-prefill logits agree to quantization tolerance rather
        than exactly.

        Returns (last-position logits [b, vocab] f32, advanced cache).
        Requires an EMPTY cache (``pos == 0``, the decode_block
        precondition) — validated when ``pos`` is concrete; under jit
        the caller owns it.
        """
        b, s = token_ids.shape
        if s == 0:
            raise ValueError("prefill_cache needs a non-empty prompt")
        if chunk is not None and chunk < 1:
            raise ValueError(f"chunk must be >= 1; got {chunk}")
        if not isinstance(cache["pos"], jax.core.Tracer) \
                and int(cache["pos"]) != 0:
            raise ValueError(
                f"prefill_cache needs an empty cache (pos == 0); got pos="
                f"{int(cache['pos'])} — append to a live cache with "
                "decode_window instead")
        if chunk is None or chunk >= s:
            return self.decode_block(params, cache, token_ids)
        logits = None
        for lo in range(0, s, chunk):
            window = token_ids[:, lo:lo + chunk]
            last = lo + chunk >= s
            logits, cache = self.decode_window(
                params, cache, window, head="last" if last else "none")
        return logits, cache

    def generate(self, params, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, rng=None,
                 max_len: Optional[int] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 pad_id: Optional[int] = None,
                 prompt_valid=None,
                 prefill_chunk: Optional[int] = None) -> jnp.ndarray:
        """Autoregressive sampling with the KV cache.

        ``prefill_chunk``: stream the prompt into the cache W tokens at
        a time (``prefill_cache``) instead of one block — bounds prefill
        attention memory for very long prompts; not supported together
        with ``prompt_valid``.

        prompt_ids: [b, p] int32.  temperature 0 = greedy; ``top_k`` /
        ``top_p`` filter the sampled distribution (ops.decoding).  Returns
        [b, p + max_new_tokens].  Without ``eos_id`` the whole loop is one
        ``lax.scan`` (prompt positions are teacher-forced), so generation
        jits with no per-token recompilation.

        ``eos_id``: rows that sample EOS (after the prompt) are finished —
        they emit ``pad_id`` (default: ``eos_id``) from then on, and the
        loop becomes a ``lax.while_loop`` that EXITS EARLY once every row
        has finished: a batch whose longest answer is 10 tokens pays for
        10 decode steps, not ``max_new_tokens``.  Output shape stays
        static ([b, p + max_new_tokens], padded).

        ``prompt_valid`` [b, p]: ragged prompts, LEFT-padded so every row's
        last prompt token sits at column p-1 (1 = real token).  Pad slots
        are masked out of attention and each row's position indices are
        shifted by its pad length, so learned and RoPE models both see the
        row's true positions — batch serving for unequal prompt lengths.
        The left-padding contract is only VALIDATED on concrete masks:
        under jit the check cannot run, and a right-padded mask silently
        yields wrong positions/attention — callers tracing this must
        guarantee left-padding themselves.
        """
        from ..ops import decoding as dec
        c = self.config
        if prefill_chunk is not None and prompt_valid is not None:
            # validated up front so the combination fails the same way
            # regardless of prompt length / max_new_tokens
            raise ValueError("prefill_chunk does not compose with "
                             "prompt_valid (ragged prompts prefill as "
                             "one block)")
        pad = dec.resolve_pad(eos_id, pad_id)
        b, plen = prompt_ids.shape
        total = plen + max_new_tokens
        max_len = max_len or max(total, 1)
        self._check_gen_lengths(plen, max_new_tokens, max_len)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        cache = self.init_cache(b, max_len)
        tokens = (jnp.zeros((b, total), jnp.int32) if eos_id is None
                  else jnp.full((b, total), pad, jnp.int32))
        tokens = tokens.at[:, :plen].set(prompt_ids)

        if prompt_valid is not None:
            pad_len, kv_valid = dec.ragged_prompt_masks(
                prompt_valid, (b, plen), max_len)
        else:
            pad_len = kv_valid = None

        def advance(tokens, cache, rng, finished, i):
            tok = lax.dynamic_slice_in_dim(tokens, i, 1, axis=1)[:, 0]
            if prompt_valid is not None:
                logits, cache = self.decode_step(
                    params, cache, tok, kv_valid=kv_valid,
                    positions=jnp.maximum(i - pad_len, 0))
            else:
                logits, cache = self.decode_step(params, cache, tok)
            rng, sub = jax.random.split(rng)
            nxt = dec.sample_logits(sub, logits, temperature,
                                    top_k=top_k, top_p=top_p)
            # Teacher-force while still inside the prompt.
            inside = i + 1 < plen
            target = lax.dynamic_slice_in_dim(
                tokens, jnp.minimum(i + 1, total - 1), 1, axis=1)[:, 0]
            nxt = jnp.where(inside, target, nxt)  # sample_logits returns int32
            if eos_id is not None:
                nxt, finished = dec.finish_step(nxt, finished, eos_id, pad,
                                                eligible=~inside)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], i + 1, axis=1)
            return tokens, cache, rng, finished

        no_finish = jnp.zeros((b,), bool)
        finished = no_finish
        start = 0
        if plen > 1 and max_new_tokens > 0:
            # Batched prefill: the whole prompt in ONE forward (decode_
            # block) instead of plen sequential teacher-forced decode
            # steps, then sample the first new token from its logits.
            # Greedy output is identical to the sequential path (parity-
            # tested); sampling paths draw from the same distributions
            # but consume fewer rng splits.
            if prompt_valid is not None:
                logits, cache = self.decode_block(
                    params, cache, prompt_ids,
                    kv_valid=kv_valid[:, :plen],
                    positions=jnp.maximum(
                        jnp.arange(plen)[None, :] - pad_len[:, None], 0))
            else:
                logits, cache = self.prefill_cache(params, cache,
                                                   prompt_ids,
                                                   chunk=prefill_chunk)
            rng, sub = jax.random.split(rng)
            nxt = dec.sample_logits(sub, logits, temperature,
                                    top_k=top_k, top_p=top_p)
            if eos_id is not None:
                nxt, finished = dec.finish_step(nxt, no_finish, eos_id,
                                                pad)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, None], plen, axis=1)
            start = plen

        if eos_id is None:
            def step(carry, i):
                tokens, cache, rng = carry
                tokens, cache, rng, _ = advance(tokens, cache, rng,
                                                no_finish, i)
                return (tokens, cache, rng), None

            (tokens, _, _), _ = lax.scan(step, (tokens, cache, rng),
                                         jnp.arange(start, total - 1))
            return tokens

        (tokens, _, _, _), _ = dec.decode_loop(
            lambda carry, i: advance(*carry, i),
            (tokens, cache, rng, finished), total - 1, start=start)
        return tokens

    def _check_gen_lengths(self, plen: int, max_new_tokens: int,
                           max_len: int) -> None:
        """Shared generate/beam_search length rules."""
        c = self.config
        if max_len > c.max_position and c.position_embedding == "learned":
            # only the learned table runs out of rows; RoPE extrapolates
            raise ValueError(f"generation length {max_len} exceeds "
                             f"max_position {c.max_position}")
        if plen + max_new_tokens > max_len:
            # dynamic_update_slice would silently clamp cache writes at
            # max_len and corrupt every later token — refuse instead.
            raise ValueError(f"prompt ({plen}) + max_new_tokens "
                             f"({max_new_tokens}) = {plen + max_new_tokens} "
                             f"exceeds max_len {max_len}")

    def beam_search(self, params, prompt_ids, max_new_tokens: int,
                    beam_size: int = 4, eos_id: Optional[int] = None,
                    length_penalty: float = 0.6,
                    max_len: Optional[int] = None,
                    prompt_valid=None,
                    prefill_chunk: Optional[int] = None) -> jnp.ndarray:
        """Jittable beam search over the KV cache.

        Two phases, each one ``lax.scan``: the prompt prefills the cache at
        batch ``b`` (no beam-fold waste), then the cache rows are repeated
        ``beam_size``-fold and every expansion REORDERS them by gather (the
        standard KV-cache beam trick).  Shared bookkeeping lives in
        ``ops.decoding``.  Returns the best row per batch element,
        [b, plen + max_new_tokens].

        ``prefill_chunk``: stream the prompt prefill W tokens at a time
        (``prefill_cache``) — bounds long-prompt prefill memory; not
        supported with ``prompt_valid``, and under
        ``kv_cache_dtype="int8"`` it matches the one-block prefill to
        quantization tolerance only (see ``prefill_cache``).

        ``prompt_valid``: LEFT-padded ragged prompts, same contract as
        ``generate`` — pad slots masked from attention, per-row position
        shift through prefill and expansion.  As there, the left-padding
        check only runs on concrete masks; under jit the caller owns it.
        """
        from ..ops import decoding as dec

        c = self.config
        if prefill_chunk is not None and prompt_valid is not None:
            # same up-front refusal (and precedence) as generate: the
            # combination fails identically regardless of prompt length
            raise ValueError("prefill_chunk does not compose with "
                             "prompt_valid (ragged prompts prefill as "
                             "one block)")
        b, plen = prompt_ids.shape
        k = beam_size
        total = plen + max_new_tokens
        max_len = max_len or max(total, 1)
        self._check_gen_lengths(plen, max_new_tokens, max_len)

        if prompt_valid is not None:
            pad_len, kv_valid = dec.ragged_prompt_masks(
                prompt_valid, (b, plen), max_len)
            # loop-invariant beam folds, hoisted out of the expansion loop
            # (lax.while_loop gives no hoisting guarantee)
            kv_valid_folded = jnp.repeat(kv_valid, k, axis=0)
            pad_len_folded = jnp.repeat(pad_len, k, axis=0)
        else:
            pad_len = kv_valid = None

        def step_kwargs(i):
            """decode_step kwargs for position i with the cache rows
            beam-folded k-fold (the only decode_step caller left since
            the prefill became one decode_block forward)."""
            if prompt_valid is None:
                return {}
            return dict(kv_valid=kv_valid_folded,
                        positions=jnp.maximum(i - pad_len_folded, 0))

        # phase 1 — prefill positions 0..plen-2 at batch b, as ONE
        # decode_block forward (phase 2's first expansion reads the token
        # at plen-1, so the block stops one short); prefill_chunk streams
        # it W tokens at a time instead (long-prompt memory bound)
        cache = self.init_cache(b, max_len)
        if plen > 1:
            if prompt_valid is not None:
                _, cache = self.decode_block(
                    params, cache, prompt_ids[:, :-1],
                    kv_valid=kv_valid[:, :plen - 1],
                    positions=jnp.maximum(
                        jnp.arange(plen - 1)[None, :]
                        - pad_len[:, None], 0))
            else:
                _, cache = self.prefill_cache(params, cache,
                                              prompt_ids[:, :-1],
                                              chunk=prefill_chunk)
        # fold beams into the batch dim: row r of batch i -> i*k + r
        # (tree-mapped over every cache entry but pos, so int8 caches'
        # scale arrays fold with their values)
        cache = dict(jax.tree.map(lambda a: jnp.repeat(a, k, axis=1),
                                  self._cache_kv(cache)),
                     pos=cache["pos"])

        tokens = jnp.zeros((b, k, total), jnp.int32)
        tokens = tokens.at[:, :, :plen].set(prompt_ids[:, None, :])
        scores = dec.init_beam_scores(b, k)
        finished = jnp.zeros((b, k), bool)
        batch_base = jnp.arange(b)[:, None] * k            # [b, 1]

        def advance(carry, i):
            tokens, cache, scores, finished = carry
            tok = lax.dynamic_slice_in_dim(
                tokens.reshape(b * k, total), i, 1, axis=1)[:, 0]
            logits, cache = self.decode_step(params, cache, tok,
                                             **step_kwargs(i))
            logp = jax.nn.log_softmax(logits, -1).reshape(b, k, -1)
            logp = dec.freeze_finished(logp, finished, eos_id)
            scores, beam, nxt = dec.expand_beams(scores, logp)
            tokens = jnp.take_along_axis(tokens, beam[:, :, None], axis=1)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, nxt[:, :, None], i + 1, axis=2)
            finished = jnp.take_along_axis(finished, beam, axis=1)
            if eos_id is not None:
                finished = finished | (nxt == eos_id)
            flat = (batch_base + beam).reshape(-1)
            cache = dict(jax.tree.map(lambda a: jnp.take(a, flat, axis=1),
                                      self._cache_kv(cache)),
                         pos=cache["pos"])
            return (tokens, cache, scores, finished)

        # phase 2 — beam expansion from position plen-1 onward
        carry0 = (tokens, cache, scores, finished)
        if eos_id is None:
            (tokens, _, scores, finished), _ = lax.scan(
                lambda carry, i: (advance(carry, i), None), carry0,
                jnp.arange(plen - 1, total - 1))
        else:
            # early exit once every beam of every row finished; unwritten
            # tail positions get EOS — exactly what the full run writes
            # (frozen beams only ever extend with EOS, dec.freeze_finished)
            (tokens, _, scores, finished), steps = dec.decode_loop(
                lambda carry, j: advance(carry, plen - 1 + j),
                carry0, max_new_tokens)
            pos = jnp.arange(total)[None, None, :]
            tokens = jnp.where(pos > plen - 1 + steps, eos_id, tokens)
        best = dec.rank_beams(scores, tokens[:, :, plen:], eos_id,
                              max_new_tokens, length_penalty)
        return jnp.take_along_axis(tokens, best[:, None, None],
                                   axis=1)[:, 0, :]

    # -- sharding ---------------------------------------------------------
    def partition_rules(self, fsdp: bool = False,
                        shard_kv: Optional[bool] = None) -> PartitionRules:
        """Megatron-style TP specs; tied head sharding comes free with the
        word embedding (vocab on ``tensor``).

        GQA/MQA: the kv head axis can be smaller than the TP degree, so
        by default key/value projections follow the standard MQA recipe —
        queries shard over heads, keys/values replicate across the tensor
        axis.  Pass ``shard_kv=True`` when the tensor degree divides
        kv_heads (e.g. GQA 4 kv heads on tensor=2) to shard them too;
        the table is mesh-agnostic so it cannot decide this itself.
        """
        f = "fsdp" if fsdp else None
        # With pipeline_stages the scanned leading LAYER dim shards over the
        # pipe axis — each stage's devices hold exactly their L/S blocks;
        # apply()'s [L,...]->[S,L/S,...] reshape is then a local view.
        lead = (self.config.pipe_axis if self.config.pipeline_stages > 1
                else None)
        kv_on_tensor = (shard_kv if shard_kv is not None
                        else self.config.kv_heads == self.config.num_heads)
        kv_spec = (P(lead, f, "tensor", None) if kv_on_tensor
                   else P(lead, f, None, None))
        kv_bias = (P(lead, "tensor", None) if kv_on_tensor
                   else P(lead, None, None))
        return PartitionRules([
            (r"embeddings/word$", P("tensor", f)),
            (r"lm_head$", P("tensor", f)),      # untied head: same split
            (r"embeddings/position$", P(None, None)),
            (r"decoder/attention/query/kernel", P(lead, f, "tensor", None)),
            (r"decoder/attention/query/bias", P(lead, "tensor", None)),
            (r"decoder/attention/(key|value)/kernel", kv_spec),
            (r"decoder/attention/(key|value)/bias", kv_bias),
            (r"decoder/attention/out/kernel", P(lead, "tensor", None, f)),
            (r"decoder/ffn/w_(in|gate)/kernel", P(lead, f, "tensor")),
            (r"decoder/ffn/w_(in|gate)/bias", P(lead, "tensor")),
            (r"decoder/ffn/w_out/kernel", P(lead, "tensor", f)),
            (r"decoder/ffn/w_out/bias", P(lead, None)),
            (r"decoder/attention/out/bias", P(lead, None)),
            (r"decoder/ln_[12]/(gamma|beta)", P(lead, None)),
            # MoE rows derive from the canonical ops.moe table (its patterns
            # are suffix-matching), with the scanned leading layer dim
            # prepended to each spec — one source of truth.  (MoE cannot
            # combine with pipeline — rejected at config — so lead=None.)
        ] + [(pat, P(None, *spec)) for pat, spec in moe_partition_rules()])


# --------------------------------------------------- dtlint graph tier

from ..analysis import graph as _graph_lib  # noqa: E402  (registration)


@_graph_lib.trace_entry("gpt", hbm_budget=1 << 20)
def _graph_entries():
    """Registry-scale decode/prefill paths for the DT4xx pack: the
    chunked-prefill window (``decode_window``) and the single-token
    decode step traced abstractly on a tiny config.  DT401 watches for
    weights silently closed over instead of passed as ``params``;
    DT402 for a decode path whose matmuls get upcast to f32."""
    import jax

    model = gpt_tiny(vocab_size=64, hidden_size=32, num_heads=2,
                     intermediate_size=64, max_position=32,
                     dropout_rate=0.0)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    cache = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype),
        jax.eval_shape(lambda: model.init_cache(1, 32)))
    window = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    token = jax.ShapeDtypeStruct((1,), jnp.int32)
    return [
        _graph_lib.Target(
            "prefill_window",
            lambda p, c, w: model.decode_window(p, c, w, head="last"),
            (params, cache, window)),
        _graph_lib.Target(
            "decode_step",
            lambda p, c, t: model.decode_step(p, c, t),
            (params, cache, token)),
    ]
