"""Llama model family — GPT decoder configs with the Llama block recipe.

The Llama architecture (Touvron et al., 2023) is this repo's ``GPT``
decoder with four config switches, so the whole zoo surface — pjit/TP
sharding, KV-cache ``generate``/``beam_search``, GQA, ring attention,
pipeline stages, 1F1B — comes along for free:

  * ``norm="rmsnorm"``            (no centering, gamma only)
  * ``ffn_activation="swiglu"``   (gate/up/down, silu gate)
  * ``position_embedding="rope"`` (rotate-half convention, = HF)
  * ``use_bias=False, tied_head=False``

Reference parity note: the reference repo (TF-1.4 parameter-server
example scripts) has no transformer at all; this family serves the
driver's model-zoo breadth the same way BERT/ViT do.  HF checkpoint
interop lives in ``models/convert.py`` (``llama_from_hf``).
"""
from __future__ import annotations

from .gpt import GPT, GPTConfig

__all__ = ["llama_config", "llama", "llama_tiny", "llama2_7b", "llama3_8b"]


def llama_config(**kw) -> GPTConfig:
    """A ``GPTConfig`` with the Llama block recipe; any field can still be
    overridden (e.g. ``pipeline_stages``, ``seq_axis``, ``use_flash``)."""
    kw.setdefault("norm", "rmsnorm")
    kw.setdefault("ffn_activation", "swiglu")
    kw.setdefault("position_embedding", "rope")
    kw.setdefault("use_bias", False)
    kw.setdefault("tied_head", False)
    kw.setdefault("dropout_rate", 0.0)
    kw.setdefault("layer_norm_eps", 1e-5)
    return GPTConfig(**kw)


def llama(mesh=None, **kw) -> GPT:
    return GPT(llama_config(**kw), mesh=mesh)


def llama_tiny(mesh=None, **kw) -> GPT:
    """Test-sized Llama (GQA 4q/2kv) — the family's smoke config."""
    kw.setdefault("vocab_size", 512)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 2)
    kw.setdefault("intermediate_size", 256)
    kw.setdefault("max_position", 128)
    return llama(mesh=mesh, **kw)


def llama2_7b(mesh=None, **kw) -> GPT:
    """Llama-2-7B dimensions (MHA, 4k context, rope base 10000)."""
    kw.setdefault("vocab_size", 32000)
    kw.setdefault("hidden_size", 4096)
    kw.setdefault("num_layers", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("intermediate_size", 11008)
    kw.setdefault("max_position", 4096)
    return llama(mesh=mesh, **kw)


def llama3_8b(mesh=None, **kw) -> GPT:
    """Llama-3-8B dimensions (GQA 32q/8kv, 8k context, rope base 500k)."""
    kw.setdefault("vocab_size", 128256)
    kw.setdefault("hidden_size", 4096)
    kw.setdefault("num_layers", 32)
    kw.setdefault("num_heads", 32)
    kw.setdefault("num_kv_heads", 8)
    kw.setdefault("intermediate_size", 14336)
    kw.setdefault("max_position", 8192)
    kw.setdefault("rope_base", 500000.0)
    return llama(mesh=mesh, **kw)
