"""Low-level distributed training entrypoint — TPU-native.

Capability-parity rebuild of reference example.py (all cited lines refer to
/root/reference/example.py): the 64-bit XOR task (ref :24-48), the
128-128-32 MLP with dropout (ref :149-155), MSE + bitwise accuracy
(ref :157-164), Adam + global step (ref :168-170), monitored training with
chief election / checkpointing / StopAtStepHook (ref :187-192), TB summaries
at fractional-epoch steps (ref :172-174,219), per-5-epoch validation prints
(ref :222-226), and env-var cluster bootstrap with a single-machine fallback
(ref :59-68,108-143).

What is different — by design, not accident (SURVEY.md §7):
  * No parameter server, no gRPC: every process runs this same SPMD program;
    gradient sync is a compiled all-reduce over ICI implied by sharding the
    batch over the mesh's ``data`` axis.  ``JOB_NAME=ps`` processes are
    politely refused.
  * Synchronous data parallelism (the reference's async PS updates train on
    stale weights); one step = one global update.
  * The whole train step (fwd+bwd+Adam+metrics) is ONE XLA program; batches
    are prefetched to device, not fed per step over feed_dict.

Run:  python example.py [--device=tpu] [--log_dir=...] [--epochs=N]
Cluster topology comes from COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID or
the reference's legacy JOB_NAME/TASK_INDEX/WORKER_HOSTS env vars; with none
set this runs single-machine, exactly like the reference.
"""
import os
import sys

from distributed_tensorflow_tpu import utils
from distributed_tensorflow_tpu.utils import flags as flags_lib
from distributed_tensorflow_tpu.utils.flags import FLAGS

# ---------------------------------------------------------------------------
# Hyperparameters (parity with ref :12-19)
# ---------------------------------------------------------------------------
bits = 32                  # half the input width; label width
train_batch_size = 50      # global batch size
train_set_size = 30000
val_set_size = 1000
epochs = 50
print_rate = 5             # epochs between validation prints

# ---------------------------------------------------------------------------
# Env-var bootstrap -> flags (parity with ref :59-105, minus the str/int
# chief-election bug and the swapped data_dir/log_dir help strings)
# ---------------------------------------------------------------------------
flags_lib.DEFINE_string(
    "job_name", flags_lib.env_default("JOB_NAME", None),
    "Legacy role name ('worker'; 'ps' is refused — there is no parameter "
    "server on TPU)")
flags_lib.DEFINE_integer(
    "task_index",
    flags_lib.env_default("PROCESS_ID",
                          flags_lib.env_default("TASK_INDEX", 0, int), int),
    "Process index within the job; index 0 is chief (does checkpoint and "
    "summary writes)")
flags_lib.DEFINE_string(
    "coordinator", flags_lib.env_default("COORDINATOR_ADDRESS", None),
    "host:port of process 0 for multi-host runs")
flags_lib.DEFINE_integer(
    "num_processes", flags_lib.env_default("NUM_PROCESSES", 0, int),
    "Number of participating host processes (0 = infer from env)")
flags_lib.DEFINE_string(
    "worker_hosts", flags_lib.env_default("WORKER_HOSTS", None),
    "Legacy comma-separated worker list; first host becomes coordinator")
# Local-vs-cloud defaults via the clusterone-helper analogue (reference
# example.py:83-102): DTTPU_DATA_ROOT / DTTPU_LOGS_ROOT switch to managed
# roots, else the local fallback.
flags_lib.DEFINE_string(
    "data_dir", os.environ.get("DATA_DIR") or utils.get_data_path(
        "xor", local_root=os.path.join("logs", "data"), local_repo="xor"),
    "Directory containing/receiving training data")
flags_lib.DEFINE_string(
    "log_dir", os.environ.get("LOG_DIR") or utils.get_logs_path(
        os.path.join("logs", "xor")),
    "Directory for checkpoints and TensorBoard event files")
flags_lib.DEFINE_string(
    "device", "", "Force a JAX platform ('tpu', 'cpu'); empty = default")
flags_lib.DEFINE_integer("epochs", epochs, "Training epochs")
flags_lib.DEFINE_integer(
    "accum_steps", 1,
    "Gradient-accumulation microbatches per update (1 = off)")
flags_lib.DEFINE_bool(
    "async_checkpoint", False,
    "Write checkpoints on a background thread (never stalls the step)")
flags_lib.DEFINE_integer("batch_size", train_batch_size, "Global batch size")
flags_lib.DEFINE_integer("seed", 0, "PRNG seed")


def main() -> int:
    FLAGS.parse()
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)

    # Cluster bootstrap (replaces ClusterSpec/Server/replica_device_setter,
    # ref :108-143).  CLI flags overlay the environment so
    # ``--coordinator/--num_processes/--task_index`` work without env vars.
    from distributed_tensorflow_tpu.parallel import cluster
    env = dict(os.environ)
    if FLAGS.coordinator:
        env["COORDINATOR_ADDRESS"] = FLAGS.coordinator
    if FLAGS.num_processes:
        env["NUM_PROCESSES"] = str(FLAGS.num_processes)
    if FLAGS.worker_hosts:
        env["WORKER_HOSTS"] = FLAGS.worker_hosts
    if FLAGS.job_name:
        env["JOB_NAME"] = FLAGS.job_name
    env["PROCESS_ID"] = str(FLAGS.task_index)
    config = cluster.cluster_from_env(environ=env)
    if FLAGS.job_name == "ps" or config.is_legacy_ps:
        print("JOB_NAME=ps: no parameter-server role exists on TPU; "
              "gradient sync is an ICI all-reduce. Exiting.")
        if os.environ.get("DTTPU_LAUNCHER"):
            # under a supervisor, exit 0 would read as "completed" —
            # refuse loudly instead (fleet/launcher.py names the reason)
            return cluster.LEGACY_PS_EXIT_CODE
        return 0
    if not config.distributed:
        print("Running single-machine training")   # parity with ref :112
    cluster.initialize(config)

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, ops, optim, parallel, train
    from distributed_tensorflow_tpu.summary import SummaryWriter

    # Device mesh: all chips on one 'data' axis (the pjit generalization of
    # pmap+psum sync-DP; placement is sharding, not device pinning).
    mesh = parallel.data_parallel_mesh()
    is_chief = cluster.is_chief()
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}, chief={is_chief}")

    # Model graph (parity with ref :149-155).
    model = ops.serial(
        ops.Dense(128, activation="relu"),
        ops.Dropout(0.3),
        ops.Dense(128, activation="relu"),
        ops.Dropout(0.3),
        ops.Dense(bits, activation="sigmoid"),
    )

    # Optimizer + global step (ref :168-170); step lives in TrainState.
    optimizer = optim.adam()   # TF 1.4 defaults

    # Data (ref :24-48,184) — vectorized, reshuffled per epoch, sharded per
    # process for multi-host.
    (x_train, y_train), (x_val, y_val) = data.xor_data(
        train_set_size, val_set_size, seed=FLAGS.seed)
    batch_size = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)
    if batch_size != FLAGS.batch_size:
        print(f"batch_size {FLAGS.batch_size} -> {batch_size} "
              f"(divisible by {parallel.data_shards(mesh)} data shards)")
    # Each process feeds its 1/P share of the *global* batch; the prefetcher
    # assembles the global sharded array (batch_size is divisible by the
    # device count, hence by the process count).
    local_batch = batch_size // jax.process_count()
    dataset = data.Dataset(
        [x_train, y_train], local_batch, seed=FLAGS.seed,
        process_index=jax.process_index(), process_count=jax.process_count())
    total_batch = len(dataset)   # == global steps per epoch

    # Compiled train/eval steps: fwd+bwd+Adam+metrics in one XLA program,
    # batch sharded over 'data' (replaces the sess.run hot loop, ref
    # :207-213).
    metric_fns = {"accuracy": "bitwise_accuracy"}
    train_step = train.make_train_step(model, "mse", optimizer,
                                       metric_fns=metric_fns, mesh=mesh,
                                       seed=FLAGS.seed,
                                       accum_steps=FLAGS.accum_steps)
    eval_step = train.make_eval_step(model, "mse", metric_fns=metric_fns,
                                     mesh=mesh)

    state = train.init_train_state(model, optimizer,
                                   jax.random.PRNGKey(FLAGS.seed), (2 * bits,))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    batch_sharding = NamedSharding(mesh, P("data"))

    # Monitored session (parity with ref :187-192,219): StopAtStepHook at
    # epochs*steps_per_epoch global steps, chief-only checkpoints, TB
    # summaries on the reference's fractional-epoch x-axis.
    last_step = FLAGS.epochs * total_batch
    writer = SummaryWriter(FLAGS.log_dir) if is_chief else None
    if writer is not None:
        # model topology -> TB graph tab (parity with ref :195 add_graph)
        writer.add_graph(model)
    hooks = [train.StopAtStepHook(last_step=last_step),
             train.CheckpointHook(every_secs=60.0),
             train.PreemptionHook()]
    if writer is not None:
        hooks.append(train.SummaryHook(
            writer, every_steps=max(1, total_batch // 60),
            step_fn=lambda s: s / total_batch))

    val_batch = jax.device_put((x_val, y_val), batch_sharding)

    with train.TrainSession(state, train_step, checkpoint_dir=FLAGS.log_dir,
                            hooks=hooks, is_chief=is_chief,
                            async_checkpoint=FLAGS.async_checkpoint) as sess:
        start_epoch = sess.step // total_batch
        for epoch in range(start_epoch, FLAGS.epochs):
            if sess.should_stop():
                break
            # Epoch averages (parity with ref :216-217,226: the reference
            # prints loss/accuracy averaged over the epoch's 600 batches).
            # Sums accumulate ON DEVICE — one tiny add per step, a single
            # host fetch per epoch — so the async dispatch queue never
            # stalls on a per-step device->host sync.
            loss_sum = acc_sum = None
            n_batches = 0
            for batch in data.prefetch_to_device(iter(dataset),
                                                 sharding=batch_sharding):
                if sess.should_stop():
                    break
                m = sess.run_step(batch)
                loss_sum = (m["loss"] if loss_sum is None
                            else loss_sum + m["loss"])
                acc_sum = (m["accuracy"] if acc_sum is None
                           else acc_sum + m["accuracy"])
                n_batches += 1
            # Per-print_rate validation (parity with ref :222-226).
            if epoch % print_rate == 0 or epoch == FLAGS.epochs - 1:
                val = eval_step(sess.state, val_batch)
                avg_loss = (float(loss_sum) / n_batches) if n_batches else 0.0
                avg_acc = (float(acc_sum) / n_batches) if n_batches else 0.0
                print(f"Epoch: {epoch:4d}  loss: {avg_loss:.6f}  "
                      f"train acc: {avg_acc:.4f}  "
                      f"val acc: {float(val['accuracy']):.4f}", flush=True)
                if writer is not None:
                    writer.add_scalars(
                        {"val/accuracy": float(val["accuracy"]),
                         "val/loss": float(val["loss"])}, epoch)
    if writer is not None:
        writer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
